"""Cluster/task scheduling.

Capability-equivalent to the reference's raylet scheduling stack
(reference: src/ray/raylet/scheduling/cluster_task_manager.h,
cluster_resource_scheduler.h and the policies in
src/ray/raylet/scheduling/policy/ — hybrid/spread/node-affinity/bundle,
scored by least-resource): tasks wait for dependencies, then a policy picks
a node from the cluster resource view; infeasible tasks are queued and
surfaced as autoscaler demand. TPU-native addition: SliceAffinity — gang
placement onto a single ICI slice via slice-label resources.

In the local runtime every "node" executes in-process (a thread pool),
which is the moral equivalent of the reference's in-process multi-raylet
test Cluster (reference: python/ray/cluster_utils.py:108) — it exercises
real scheduling/spillback decisions without real remote nodes.
"""

from __future__ import annotations

import random
import threading
import time as _time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from .._private.config import config
from ..observability import get_recorder
from .resources import ResourceSet
from .task import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SliceAffinitySchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskSpec,
)


def _labels_match(spec, node) -> bool:
    """Hard node-label constraint: every selector key must equal the
    node's label (reference: NodeLabelSchedulingPolicy)."""
    if not spec.label_selector:
        return True
    return all(node.labels.get(k) == v
               for k, v in spec.label_selector.items())


def _is_constrained(strategy) -> bool:
    """True only for strategies that free capacity on an arbitrary node
    cannot absorb: hard node/slice affinity and PG bundles. Spread and
    soft affinity schedule anywhere, so they must be netted against free
    capacity like default tasks or the autoscaler over-scales."""
    if strategy is None or isinstance(strategy, SpreadSchedulingStrategy):
        return False
    if isinstance(strategy, (NodeAffinitySchedulingStrategy,
                             SliceAffinitySchedulingStrategy)):
        return not strategy.soft
    return True


class NodeState:
    """One schedulable node: a resource view plus an executor."""

    is_remote = False  # RemoteNodeState (node-daemon plane) overrides
    # False = excluded from placement (a cluster-mode driver's head
    # node: zero-resource work would otherwise all land local-first
    # on the driver instead of the daemons).
    schedulable = True

    def __init__(self, node_id: str, total: ResourceSet, max_workers: int):
        self.node_id = node_id
        self.total = total
        self.available = total
        # Resource-view bookkeeping: `available` is DERIVED as
        # total − charged − foreign. `charged` holds this scheduler's
        # own grants (tasks in flight, live actors, PG reservations);
        # `foreign` is other schedulers' usage estimated from heartbeat
        # load reports (resource-view sync, reference ray_syncer.h:88).
        self.charged = ResourceSet({})
        self.foreign = ResourceSet({})
        self.labels: Dict[str, str] = {}
        self.alive = True
        self.executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix=f"worker-{node_id}"
        )

    # Call only under the owning Scheduler's lock.
    def charge(self, resources: ResourceSet) -> None:
        self.charged = self.charged.add(resources)
        self._recompute_available()

    def uncharge(self, resources: ResourceSet) -> None:
        self.charged = self.charged.sub_clamp0(resources)
        self._recompute_available()

    def set_foreign(self, foreign: ResourceSet) -> None:
        self.foreign = foreign
        self._recompute_available()

    def _recompute_available(self) -> None:
        self.available = self.total.sub_clamp0(
            self.charged).sub_clamp0(self.foreign)

    def utilization(self) -> float:
        return self.available.scaled_utilization(self.total)

    def shutdown(self):
        self.alive = False
        self.executor.shutdown(wait=False, cancel_futures=True)


class Scheduler:
    """Resource-aware dispatcher over a set of nodes.

    Dispatch is event-driven: ``submit`` enqueues a dependency-resolved
    task; ``_pump`` (called on submit and on every resource release) grants
    resources and hands (task, node) to the dispatch callback.
    """

    def __init__(self, dispatch: Callable[[TaskSpec, NodeState], None]):
        self._lock = threading.Lock()
        self._nodes: Dict[str, NodeState] = {}
        self._queue: "deque[TaskSpec]" = deque()
        self._pump_state_lock = threading.Lock()
        self._pumping = False
        self._pump_again = False
        # Resource shapes proven unplaceable since the last capacity
        # change: a submit of a known-barren shape onto a saturated
        # cluster skips the pump entirely (amortized O(1) submission at
        # the 1M-queued-tasks scale point). Cleared whenever capacity
        # can have changed.
        self._barren_shapes: set = set()
        # shape key -> deque of parked specs (see _pump_once).
        self._parked: Dict[tuple, "deque[TaskSpec]"] = {}
        self._infeasible: List[TaskSpec] = []
        self._dispatch = dispatch
        self._rng = random.Random(0)
        self._spread_seq = 0

    # -- topology ---------------------------------------------------------
    def add_node(self, node: NodeState) -> None:
        with self._lock:
            self._nodes[node.node_id] = node
            self._barren_shapes.clear()
        self._pump()

    def remove_node(self, node_id: str) -> Optional[NodeState]:
        with self._lock:
            node = self._nodes.pop(node_id, None)
        if node:
            node.shutdown()
        return node

    def nodes(self) -> List[NodeState]:
        with self._lock:
            return list(self._nodes.values())

    def get_node(self, node_id: str) -> Optional[NodeState]:
        with self._lock:
            return self._nodes.get(node_id)

    # -- demand (autoscaler signal) --------------------------------------
    def pending_demand(self) -> List[ResourceSet]:
        with self._lock:
            pending = list(self._queue) + self._infeasible
            for q in self._parked.values():
                pending.extend(q)
            return [t.resources for t in pending]

    def pending_demand_detailed(self) -> List[tuple]:
        """[(ResourceSet, hard_constrained, label_selector)] —
        hard-constrained demand (PG bundles / hard node or slice
        affinity) can't be absorbed by arbitrary free capacity, so the
        autoscaler must not net it out; label-selector demand CAN be
        netted, but only against capacity whose labels satisfy the
        selector."""
        with self._lock:
            out = []
            pending = list(self._queue) + self._infeasible
            for q in self._parked.values():
                pending.extend(q)
            for t in pending:
                hard = _is_constrained(t.scheduling_strategy)
                out.append((t.resources, hard,
                            dict(t.label_selector or {})))
            return out

    # -- scheduling -------------------------------------------------------
    @staticmethod
    def _shape_key(spec: TaskSpec):
        """Cache key for unconstrained specs only — strategies and
        label selectors change placement beyond raw capacity."""
        if spec.scheduling_strategy is not None or spec.label_selector:
            return None
        return tuple(sorted(spec.resources.to_dict().items()))

    def submit(self, spec: TaskSpec) -> None:
        # Timestamp + recorder OUTSIDE the lock: observability work
        # (however cheap) has no business under the scheduler lock.
        spec.timing.setdefault("queued", _time.time())
        get_recorder().record(
            "scheduler", "task_queued", task=spec.display_name(),
            task_id=spec.task_id.hex())
        with self._lock:
            self._queue.append(spec)
            if self._shape_key(spec) in self._barren_shapes:
                return  # saturated for this shape; next release pumps
        self._pump()

    def cancel(self, task_id) -> bool:
        with self._lock:
            for q in (self._queue, self._infeasible,
                      *self._parked.values()):
                for i, t in enumerate(q):
                    if t.task_id == task_id:
                        del q[i]
                        return True
        return False

    def release(self, node_id: str, resources: ResourceSet) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.uncharge(resources)
            self._barren_shapes.clear()
        self._pump()

    def update_node_report(self, node_id: str,
                           reported_available: ResourceSet,
                           queued: int) -> None:
        """Merge a node's heartbeat load report into the local view
        (resource-view sync — capability of reference ray_syncer.h:88:
        every scheduler sees every node's load, including other
        drivers'). Foreign usage = reported usage minus our own charges
        (the daemon observes our dispatched tasks too); stale reports
        only make the view temporarily pessimistic — the next report
        recomputes it from scratch, so there is no drift."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return
            reported_used = node.total.sub_clamp0(reported_available)
            node.set_foreign(reported_used.sub_clamp0(node.charged))
            node.reported_queued = queued
            self._barren_shapes.clear()
        self._pump()

    def apply_spill_refusal(self, spec: TaskSpec, node_id: str,
                            reported_available: ResourceSet,
                            queued: int) -> None:
        """A daemon refused a spillable task: under ONE lock, return
        the task's charge and merge the refusal's authoritative load,
        then pump once. Split calls would pump between the two steps
        with the view still showing the refusing node free — granting
        more queued tasks to the node that just refused."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.uncharge(spec.resources)
            if node.alive:
                reported_used = node.total.sub_clamp0(reported_available)
                node.set_foreign(reported_used.sub_clamp0(node.charged))
                node.reported_queued = queued
            self._barren_shapes.clear()
        self._pump()

    def release_task(self, spec: TaskSpec, node_id: str) -> None:
        """Return a finished task's resources to wherever they were
        charged (PG bundle or node)."""
        charge = getattr(spec, "_pg_charge", None)
        if charge is not None:
            pg, idx = charge
            with self._lock:
                pg._bundle_available[idx] = \
                    pg._bundle_available[idx].add(spec.resources)
            self._pump()
        else:
            self.release(node_id, spec.resources)

    # After this many consecutive placement failures a pump pass stops
    # scanning: with a saturated cluster and a DEEP queue (the 1M
    # queued-tasks scale point), an uncapped scan makes every
    # submit/release O(queue) — O(n²) end to end. Tail tasks wait for
    # the next pump (every completion pumps, so nothing starves
    # indefinitely; bounded head-of-line unfairness is the same
    # trade the reference's per-tick dispatch caps make).
    _PUMP_FAIL_CAP = 64

    def _pump(self) -> None:
        # Coalesce concurrent pumps: hundreds of task completions per
        # second would otherwise convoy on the scheduler lock scanning
        # the same queue. _pumping is cleared under the SAME lock hold
        # that checks _pump_again — a separate finally would drop a
        # request arriving between the check and the clear (lost
        # wakeup: the last release's pump never runs → hang).
        with self._pump_state_lock:
            if self._pumping:
                self._pump_again = True
                return
            self._pumping = True
        from ..observability import event_stats as _estats

        while True:
            try:
                # Timed OUTSIDE self._lock (observability work never
                # rides inside the scheduler lock): the scheduler
                # loop's entry in the event_stats.h-equivalent
                # registry, surfaced at /api/event_stats.
                with _estats.timed("scheduler", "pump_once"):
                    self._pump_once()
            except BaseException:
                with self._pump_state_lock:
                    self._pumping = False
                raise
            with self._pump_state_lock:
                if not self._pump_again:
                    self._pumping = False
                    return
                self._pump_again = False

    def _grant_locked(self, spec: TaskSpec, node) -> None:
        charge = getattr(spec, "_pg_charge", None)
        if charge is not None:
            # Bundle resources were already reserved on the node at
            # PG creation; charge the bundle, not the node.
            pg, idx = charge
            pg._bundle_available[idx] = \
                pg._bundle_available[idx].subtract(spec.resources)
        else:
            node.charge(spec.resources)

    def _pump_once(self) -> None:
        granted = []
        with self._lock:
            # Re-examine infeasible tasks when topology changed.
            if self._infeasible:
                self._queue.extend(self._infeasible)
                self._infeasible = []
            # Head-window scan on a deque: unplaced items go back to
            # the FRONT in order and the unscanned tail is never
            # touched — a list rebuild here copies the whole queue
            # every pump, which is O(n²) end-to-end at the
            # 1M-queued-tasks scale point. Tasks of a shape that
            # already failed this capacity epoch are PARKED per shape
            # (not left in the queue): a placeable task is never
            # hidden behind an arbitrarily long run of unplaceable
            # ones, and the scan never re-reads them.
            still: List[TaskSpec] = []
            fails = 0
            scanned = 0
            limit = len(self._queue)
            while (self._queue and scanned < limit
                   and fails < self._PUMP_FAIL_CAP):
                spec = self._queue.popleft()
                scanned += 1
                key = self._shape_key(spec)
                if key is not None and key in self._barren_shapes:
                    self._parked.setdefault(key, deque()).append(spec)
                    continue  # cheap skip — NOT a scan failure
                node = self._pick_node(spec)
                if node is None:
                    fails += 1
                    if key is not None:
                        self._barren_shapes.add(key)
                        self._parked.setdefault(key,
                                                deque()).append(spec)
                    elif self._feasible_anywhere(spec):
                        still.append(spec)
                    else:
                        self._infeasible.append(spec)
                    continue
                self._grant_locked(spec, node)
                granted.append((spec, node))
            self._queue.extendleft(reversed(still))
            # Parked shapes: one placement probe per shape per pump —
            # O(#distinct shapes + grants), independent of how many
            # tasks are parked.
            for key in list(self._parked):
                q = self._parked[key]
                while q:
                    spec = q[0]
                    node = self._pick_node(spec)
                    if node is None:
                        self._barren_shapes.add(key)
                        if not self._feasible_anywhere(spec):
                            self._infeasible.extend(q)
                            q.clear()
                        break
                    self._barren_shapes.discard(key)
                    q.popleft()
                    self._grant_locked(spec, node)
                    granted.append((spec, node))
                if not q:
                    del self._parked[key]
        for spec, node in granted:
            spec.timing.setdefault("scheduled", _time.time())
            get_recorder().record(
                "scheduler", "task_granted", task=spec.display_name(),
                task_id=spec.task_id.hex(), node=node.node_id)
            self._dispatch(spec, node)

    def _feasible_anywhere(self, spec: TaskSpec) -> bool:
        return any(
            spec.resources.fits(n.total) and _labels_match(spec, n)
            for n in self._nodes.values()
            if n.alive and n.schedulable
        )

    # -- policies ---------------------------------------------------------
    def _pick_node(self, spec: TaskSpec) -> Optional[NodeState]:
        strat = spec.scheduling_strategy

        if isinstance(strat, PlacementGroupSchedulingStrategy):
            # PG tasks consume the bundle's reserved resources (which were
            # subtracted from node.available at PG creation), so fitness is
            # checked against the bundle, not the node
            # (reference: bundle resource accounting in
            # placement_group_resource_manager.h).
            pg = strat.placement_group
            # Per-bundle gating (no whole-PG _committed check): after a
            # node death, surviving bundles keep dispatching while the
            # lost ones are re-placed — an unplaced bundle is simply
            # absent from _bundle_nodes and skipped below.
            idx = strat.placement_group_bundle_index
            indices = ([idx] if idx >= 0
                       else range(len(pg._bundle_available)))
            for i in indices:
                node = self._nodes.get(pg._bundle_nodes[i] or "")
                if node is None or not node.alive:
                    continue
                if not _labels_match(spec, node):
                    continue  # hard label constraint applies to bundles
                if spec.resources.fits(pg._bundle_available[i]):
                    spec._pg_charge = (pg, i)
                    return node
            return None

        # Spillback redirect (reference: client retry at the refusal's
        # retry_at_raylet_address): a daemon that refused this task named
        # a better node off its own, fresher view — try it first. The
        # hint is consumed whether or not it lands, so a stale redirect
        # can't pin the task.
        hint = getattr(spec, "_spill_hint", None)
        if hint is not None:
            spec._spill_hint = None
            node = self._nodes.get(hint)
            # Deliberately NO local fits() check: our own view of the
            # hinted node may be the stale thing that caused the refusal.
            # The target daemon re-checks admission and can refuse again
            # (with the refuser now excluded), so a bad hint costs one
            # round-trip, not correctness.
            if (node is not None and node.alive and node.schedulable
                    and _labels_match(spec, node)):
                return node

        fitting = [
            n for n in self._nodes.values()
            if n.alive and n.schedulable
            and spec.resources.fits(n.available)
        ]
        fitting = [n for n in fitting if _labels_match(spec, n)]
        excluded = getattr(spec, "_spill_excluded", None)
        if excluded:
            # Prefer nodes that haven't refused this task; fall back to
            # them only when nothing else fits (their capacity may have
            # freed since the refusal).
            fresh = [n for n in fitting if n.node_id not in excluded]
            if fresh:
                fitting = fresh
        if not fitting:
            return None

        if isinstance(strat, NodeAffinitySchedulingStrategy):
            node = self._nodes.get(strat.node_id)
            if (node is not None and node.alive
                    and _labels_match(spec, node)
                    and spec.resources.fits(node.available)):
                return node
            return self._hybrid(fitting) if strat.soft else None

        if isinstance(strat, SliceAffinitySchedulingStrategy):
            # Slice membership is modeled as a node label.
            on_slice = [n for n in fitting
                        if n.labels.get("tpu-slice") == strat.slice_id]
            if on_slice:
                return self._least_loaded(on_slice)
            return self._hybrid(fitting) if strat.soft else None

        if isinstance(strat, SpreadSchedulingStrategy):
            # Round-robin, not least-loaded (reference:
            # spread_scheduling_policy.cc next_spread_node_index_):
            # actors hold 0 CPUs while alive, so a least-loaded min()
            # ties on every node and packs all spread actors onto the
            # first one.
            self._spread_seq += 1
            ordered = sorted(fitting, key=lambda n: n.node_id)
            return ordered[self._spread_seq % len(ordered)]

        return self._hybrid(fitting)

    def _hybrid(self, fitting: List[NodeState]) -> NodeState:
        """Reference default (hybrid_scheduling_policy.h:50): prefer the
        local/first node until its utilization crosses spread_threshold,
        then pick the least-loaded of a random top-k sample."""
        local = fitting[0]
        if local.utilization() < config.scheduler_spread_threshold:
            return local
        k = max(1, int(len(fitting) * config.scheduler_top_k_fraction))
        sample = self._rng.sample(fitting, min(k, len(fitting)))
        return self._least_loaded(sample)

    @staticmethod
    def _least_loaded(nodes: List[NodeState]) -> NodeState:
        return min(nodes, key=lambda n: n.utilization())
