"""Object spilling — overflow for the object store, onto any storage.

Capability-equivalent of the reference's spilling stack
(reference: src/ray/raylet/local_object_manager.h:41 SpillObjects /
restore, python/ray/_private/external_storage.py:72 FileSystemStorage
:246, :445 ExternalStorageSmartOpenImpl for S3 — when the store crosses
its memory budget, primary copies move to external storage and restore
transparently on access): sealed objects past the high watermark are
written as flat SerializedObject frames through the pluggable
ExternalStorage plane; the in-memory entry becomes a stub holding the
blob URL; get() restores on touch. With a `cp://` spill target the
blobs live in the control plane's KV and outlive the writing host —
restore needs only the URL, from any process.
"""

from __future__ import annotations

import os
import threading

from .external_storage import (
    ExternalStorage,
    FileSystemStorage,
    is_url,
    storage_for_url,
)
from .ids import ObjectID
from .serialization import SerializedObject


class ObjectSpiller:
    """Spill/restore through an ExternalStorage backend. `target` is a
    local directory (classic file spilling) or any storage URL
    (`cp://host:port/spill`, `mem://bucket/spill`)."""

    def __init__(self, target: str):
        if is_url(target):
            self.storage: ExternalStorage = storage_for_url(target)
            rest = target.split("://", 1)[1]
            _, _, prefix = rest.partition("/")
            self._prefix = (prefix.rstrip("/") + "/") if prefix else ""
        else:
            os.makedirs(target, exist_ok=True)
            self.storage = FileSystemStorage(target)
            self._prefix = ""
        self.directory = target  # kept name: session wiring reads it
        self._lock = threading.Lock()
        self.spilled_bytes = 0
        self.spilled_objects = 0
        self.restored_objects = 0

    def spill(self, object_id: ObjectID, data: SerializedObject) -> str:
        frame = data.to_bytes()
        url = self.storage.put_blob(self._prefix + object_id.hex(),
                                    frame)
        with self._lock:
            self.spilled_bytes += len(frame)
            self.spilled_objects += 1
        return url

    def restore(self, url: str) -> SerializedObject:
        frame = self.storage.get_blob(url)
        with self._lock:
            self.restored_objects += 1
        return SerializedObject.from_bytes(frame)

    def delete(self, url: str) -> None:
        self.storage.delete_blob(url)

    def stats(self) -> dict:
        with self._lock:
            return {
                "spilled_objects": self.spilled_objects,
                "spilled_bytes": self.spilled_bytes,
                "restored_objects": self.restored_objects,
            }


def restore_from_url(url: str) -> SerializedObject:
    """Restore a spilled object from its URL alone — any process, no
    spiller instance needed (reference capability:
    object_manager restoring by spilled URL recorded with the owner)."""
    return SerializedObject.from_bytes(storage_for_url(url).get_blob(url))
