"""Object spilling — disk overflow for the object store.

Capability-equivalent of the reference's spilling stack
(reference: src/ray/raylet/local_object_manager.h:41 SpillObjects /
restore, python/ray/_private/external_storage.py:72 FileSystemStorage
:246 — when the store crosses its memory budget, primary copies move to
external storage and restore transparently on access): sealed objects
past the high watermark are written to <session>/spill as flat
SerializedObject frames; the in-memory entry becomes a stub holding the
file path; get() restores on touch.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .ids import ObjectID
from .serialization import SerializedObject


class ObjectSpiller:
    """Filesystem external storage (reference: FileSystemStorage)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self.spilled_bytes = 0
        self.spilled_objects = 0
        self.restored_objects = 0

    def _path(self, object_id: ObjectID) -> str:
        return os.path.join(self.directory, object_id.hex())

    def spill(self, object_id: ObjectID, data: SerializedObject) -> str:
        path = self._path(object_id)
        tmp = path + ".tmp"
        frame = data.to_bytes()
        with open(tmp, "wb") as f:
            f.write(frame)
        os.replace(tmp, path)  # atomic: no half-written spill files
        with self._lock:
            self.spilled_bytes += len(frame)
            self.spilled_objects += 1
        return path

    def restore(self, path: str) -> SerializedObject:
        with open(path, "rb") as f:
            frame = f.read()
        with self._lock:
            self.restored_objects += 1
        return SerializedObject.from_bytes(frame)

    def delete(self, path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "spilled_objects": self.spilled_objects,
                "spilled_bytes": self.spilled_bytes,
                "restored_objects": self.restored_objects,
            }
