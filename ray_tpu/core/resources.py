"""Resource model.

Capability-equivalent to the reference's fixed-point resource vectors
(reference: src/ray/common/scheduling/cluster_resource_data.h,
fixed_point.h) — resources are named quantities in 1/10000 granularity so
fractional chips ("TPU": 0.5) behave exactly under add/subtract, with
predefined CPU / TPU / memory / object_store_memory plus arbitrary custom
resources (e.g. per-slice labels like "tpu-slice-0").
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping

GRANULARITY = 10_000

CPU = "CPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, TPU, MEMORY, OBJECT_STORE_MEMORY)


def _to_fixed(v: float) -> int:
    return int(round(v * GRANULARITY))


def _from_fixed(v: int) -> float:
    return v / GRANULARITY


class ResourceSet:
    """A fixed-point bag of named resources. Immutable-style API."""

    __slots__ = ("_r",)

    def __init__(self, amounts: Mapping[str, float] | None = None, *,
                 _fixed: Dict[str, int] | None = None):
        if _fixed is not None:
            self._r = {k: v for k, v in _fixed.items() if v != 0}
        else:
            self._r = {}
            for k, v in (amounts or {}).items():
                if v < 0:
                    raise ValueError(f"Negative resource {k}={v}")
                f = _to_fixed(v)
                if f:
                    self._r[k] = f

    def get(self, name: str) -> float:
        return _from_fixed(self._r.get(name, 0))

    def is_empty(self) -> bool:
        return not self._r

    def names(self) -> Iterable[str]:
        return self._r.keys()

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fixed(v) for k, v in self._r.items()}

    def fits(self, available: "ResourceSet") -> bool:
        return all(available._r.get(k, 0) >= v for k, v in self._r.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet(_fixed=out)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._r)
        for k, v in other._r.items():
            nv = out.get(k, 0) - v
            if nv < 0:
                raise ValueError(
                    f"Resource {k} would go negative: {_from_fixed(nv)}")
            out[k] = nv
        return ResourceSet(_fixed=out)

    def sub_clamp0(self, other: "ResourceSet") -> "ResourceSet":
        """Element-wise subtraction clamped at zero (availability-view
        arithmetic for resource-view sync, where stale reports must not
        drive a view negative)."""
        out = dict(self._r)
        for k, v in other._r.items():
            out[k] = max(0, out.get(k, 0) - v)
        return ResourceSet(_fixed=out)

    def scaled_utilization(self, total: "ResourceSet") -> float:
        """Max over resources of used/total — the hybrid policy's load signal."""
        util = 0.0
        for k, tot in total._r.items():
            if tot <= 0:
                continue
            used = tot - self._r.get(k, 0)
            util = max(util, used / tot)
        return util

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._r == other._r

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


def task_resources(num_cpus: float | None, num_tpus: float | None,
                   memory: float | None,
                   resources: Mapping[str, float] | None,
                   *, default_num_cpus: float = 1.0) -> ResourceSet:
    """Assemble a task/actor resource request from @remote options."""
    amounts: Dict[str, float] = {}
    amounts[CPU] = default_num_cpus if num_cpus is None else num_cpus
    if num_tpus:
        amounts[TPU] = num_tpus
    if memory:
        amounts[MEMORY] = memory
    for k, v in (resources or {}).items():
        if k in (CPU, TPU):
            raise ValueError(
                f"Use num_cpus/num_tpus instead of resources[{k!r}]")
        amounts[k] = v
    return ResourceSet(amounts)
