"""TaskSpec and options.

Capability-equivalent to the reference's TaskSpecification + @ray.remote
option set (reference: src/ray/common/task/task_spec.h and
python/ray/_private/ray_option_utils.py): the full option surface —
num_cpus/num_tpus/resources/memory, num_returns, max_retries /
retry_exceptions, max_restarts / max_task_retries, name, scheduling
strategy, placement-group bundles, runtime_env, concurrency groups,
lifetime, max_concurrency — validated in one place.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .ids import ActorID, ObjectID, TaskID
from .resources import ResourceSet, task_resources


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


# ---------------------------------------------------------------------------
# Options
# ---------------------------------------------------------------------------

_TASK_ONLY = {"num_returns", "max_retries", "retry_exceptions",
              "max_calls"}
_ACTOR_ONLY = {"max_restarts", "max_task_retries", "max_concurrency",
               "lifetime", "get_if_exists", "namespace",
               "concurrency_groups"}

_VALID = {
    "num_cpus", "num_tpus", "num_gpus", "memory", "resources", "name",
    "scheduling_strategy", "placement_group", "placement_group_bundle_index",
    "runtime_env", "accelerator_type", "label_selector",
} | _TASK_ONLY | _ACTOR_ONLY


def validate_options(opts: Dict[str, Any], *, is_actor: bool) -> Dict[str, Any]:
    for k in opts:
        if k not in _VALID:
            raise ValueError(f"Unknown option {k!r}. Valid: {sorted(_VALID)}")
        if is_actor and k in _TASK_ONLY:
            raise ValueError(f"Option {k!r} is only valid for tasks")
        if not is_actor and k in _ACTOR_ONLY:
            raise ValueError(f"Option {k!r} is only valid for actors")
    if "num_gpus" in opts and opts["num_gpus"]:
        raise ValueError(
            "num_gpus is not supported on the TPU runtime; use num_tpus")
    nr = opts.get("num_returns", 1)
    if not (nr == "streaming" or nr == "dynamic"
            or (isinstance(nr, int) and nr >= 0)):
        raise ValueError(f"num_returns must be int>=0 or 'streaming': {nr!r}")
    resources = opts.get("resources")
    if resources is not None and not isinstance(resources, dict):
        raise ValueError("resources must be a dict")
    ls = opts.get("label_selector")
    if ls is not None and not (
            isinstance(ls, dict)
            and all(isinstance(k, str) and isinstance(v, str)
                    for k, v in ls.items())):
        raise ValueError(
            "label_selector must be a dict of str->str "
            f"(got {ls!r})")
    cg = opts.get("concurrency_groups")
    if cg is not None and not (
            isinstance(cg, dict)
            and all(isinstance(k, str) and isinstance(v, int) and v > 0
                    for k, v in cg.items())):
        raise ValueError(
            "concurrency_groups must be a dict of str -> int>0 "
            f"(got {cg!r})")
    if "runtime_env" in opts:
        from .runtime_env import validate as _validate_renv

        # Keep the NORMALIZED env (validate canonicalizes e.g. the pip
        # list form and resolves the wheelhouse env var at submission
        # time) — discarding it would ship the raw spec to workers.
        opts["runtime_env"] = _validate_renv(opts["runtime_env"])
    return opts


@dataclass
class SchedulingStrategy:
    """Base; see parallel/placement for SliceAffinity and bundles."""


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: str
    soft: bool = False


@dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class SliceAffinitySchedulingStrategy(SchedulingStrategy):
    """TPU-native: co-schedule onto one ICI slice (gang member)."""
    slice_id: str
    soft: bool = False


# ---------------------------------------------------------------------------
# TaskSpec
# ---------------------------------------------------------------------------

@dataclass
class FunctionDescriptor:
    module: str
    qualname: str
    # Serialized callable; workers in other processes unpickle it once and
    # cache by function_id (reference: _private/function_manager.py).
    function_id: bytes = b""

    def name(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclass
class TaskSpec:
    task_id: TaskID
    task_type: TaskType
    descriptor: FunctionDescriptor
    # args/kwargs may contain ObjectRefs — resolved before dispatch.
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    num_returns: Any  # int or "streaming"
    resources: ResourceSet
    return_ids: List[ObjectID] = field(default_factory=list)
    # retry policy
    max_retries: int = 0
    retry_exceptions: Any = False  # False | True | list[type]
    retries_left: int = 0
    # actor-method redelivery (max_task_retries): None = not yet
    # initialized from the actor's budget; redelivered marks a spec
    # requeued after a crash (its pending entry must be preserved).
    task_retries_left: Optional[int] = None
    redelivered: bool = False
    # Worker recycling: retire the executing worker process after it has
    # run this function max_calls times (reference: max_calls — bounds
    # leaky user code). 0 = unlimited.
    max_calls: int = 0
    # actor linkage
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    # scheduling
    scheduling_strategy: Optional[SchedulingStrategy] = None
    # Hard node-label constraint: every key must match the node's label
    # (reference: NodeLabelSchedulingPolicy / label_selector option).
    label_selector: Optional[Dict[str, str]] = None
    name: str = ""
    runtime_env: Optional[Dict[str, Any]] = None
    # set for actor-creation tasks
    actor_class: Any = None
    actor_creation_opts: Optional[Dict[str, Any]] = None
    # distributed tracing (Dapper-style): set at submission when a trace
    # is active; carried through scheduling into worker execution so
    # cross-process spans link into one trace.
    trace_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    # lifecycle timestamps (time.time() epoch): submitted/queued/
    # scheduled/running/finished, stamped as the spec moves through the
    # pipeline and surfaced via state.list_tasks / summarize_tasks.
    timing: Dict[str, float] = field(default_factory=dict)

    def is_actor_task(self) -> bool:
        return self.task_type == TaskType.ACTOR_TASK

    def display_name(self) -> str:
        return self.name or self.descriptor.name()

    def dep_ids(self) -> List[str]:
        """Hex ids of top-level ObjectRef args/kwargs — the object
        edges of the dynamic task graph. Matches the dependency set
        the dispatcher waits on (`_submit_when_ready` scans exactly
        the top-level positions); refs nested inside containers are
        resolved by value at materialization and are not graph edges
        here. Deduped, submission order preserved."""
        from .object_ref import ObjectRef

        out: List[str] = []
        seen = set()
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, ObjectRef):
                h = a.id().hex()
                if h not in seen:
                    seen.add(h)
                    out.append(h)
        return out

    def return_hexes(self) -> List[str]:
        return [r.hex() for r in self.return_ids]


def build_resources(opts: Dict[str, Any], *, is_actor: bool) -> ResourceSet:
    # Actors default to 1 CPU for creation-task placement but 0 HELD
    # while alive (reference: _private/ray_option_utils.py — actors
    # default num_cpus=0 lifetime; that is what lets 10k+ actors share
    # a node, release/benchmarks many_actors). We model the held
    # resources, so the actor default is 0; tasks stay 1.
    default_cpus = 1.0 if not is_actor else 0.0
    extra = opts.get("resources")
    acc = opts.get("accelerator_type")
    if acc:
        # accelerator_type must be the node's advertised type string
        # (e.g. "v5litepod-8", what _private/accelerators
        # accelerator_type() reports) → a sliver of the node's
        # "TPU-<type>" resource (reference: accelerator_type becomes
        # an implicit 0.001 accelerator resource; nodes advertise
        # theirs at Runtime init via accelerators.pod_resources).
        extra = dict(extra or {})
        extra.setdefault(f"TPU-{acc}", 0.001)
    return task_resources(
        opts.get("num_cpus"), opts.get("num_tpus"), opts.get("memory"),
        extra, default_num_cpus=default_cpus,
    )
