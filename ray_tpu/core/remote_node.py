"""Driver-side integration of the per-host node-daemon plane.

Gives the Runtime real REMOTE nodes: `RemoteNodeState` entries in the
scheduler whose dispatch pushes packed tasks to a `NodeDaemon` over TCP
(node/client.py), with bulk objects moving between per-host shm arenas
on the native object-transfer plane and the driver's resource view kept
in sync from heartbeat load reports (the ray_syncer.h:88 capability).

Reference capabilities mirrored: the driver⇄raylet⇄worker dispatch path
(node_manager.proto RequestWorkerLease + core_worker.proto PushTask),
ownership-based object locations (OwnershipBasedObjectDirectory — here
the owner's store records each object's node in its `_ShmMarker`), and
actor restart-with-replacement on node death
(gcs_actor_manager.h:513 ReconstructActor).
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .._private.config import config
from ..observability import get_recorder
from .exceptions import (ActorDiedError, ObjectLostError,
                         ObjectStoreFullError, TaskCancelledError)
from .ids import ObjectID
from .object_ref import ObjectRef
from .resources import ResourceSet
from .scheduler import NodeState, _is_constrained
from .task import TaskSpec, TaskType

logger = logging.getLogger("ray_tpu")


def apply_native_dispatch_timing(timing: Dict[str, float],
                                 nd: Dict[str, Any], *,
                                 trace_id: Optional[str] = None,
                                 parent_span_id: Optional[str] = None,
                                 node_id: str = "",
                                 now: Optional[float] = None
                                 ) -> Optional[dict]:
    """Fold a native ``dispatch_timing`` frame into a warm task's
    lifecycle stamps and build the synthetic daemon dispatch span.

    Warm tasks run zero daemon-side Python, so the daemon never opens
    its ``daemon:task`` span and never stamps ``running`` — the trace
    showed submit → execute with a hole. The C loop's wall-clock
    stamps (admission arrival / worker write / reply forward) close
    it: ``running`` back-fills from the worker-write stamp and the
    dispatch span is synthesized driver-side in the exact shape
    util.tracing.span records. Daemon clocks can skew from the
    driver's, so stamps are clamped into the task's own
    scheduled→now window instead of trusted blindly. Returns the span
    event (caller records it), or None when the stamps are unusable.
    Pure — unit tested without a cluster."""
    try:
        recv = float(nd.get("recv_ts") or 0.0)
        write = float(nd.get("write_ts") or 0.0)
        fwd = float(nd.get("forward_ts") or 0.0)
    except (TypeError, ValueError):
        return None
    if not (recv > 0.0 and write >= recv and fwd >= write):
        return None
    now = time.time() if now is None else now
    lo = timing.get("scheduled") or timing.get("queued") \
        or timing.get("submitted")
    hi = timing.get("finished") or now
    if lo is not None:
        span_lo = max(min(recv, hi), lo)
        span_hi = max(min(write, hi), span_lo)
    else:
        span_lo, span_hi = recv, write
    timing.setdefault("scheduled", span_lo)
    timing.setdefault("running", span_hi)
    import uuid

    span_id = uuid.uuid4().hex[:16]
    return {
        "name": "daemon:task", "cat": "daemon_dispatch", "ph": "X",
        "ts": span_lo * 1e6, "dur": (span_hi - span_lo) * 1e6,
        "pid": f"daemon:{node_id}", "tid": f"span:{span_id}",
        "args": {"parent": parent_span_id, "trace_id": trace_id,
                 "node_id": node_id, "native": True,
                 "task_id": nd.get("tid"), "forward_ts": fwd},
    }


class _FetchLost(Exception):
    """An arg's payload is on a node that is gone — reconstruct."""

    def __init__(self, oid: ObjectID):
        self.oid = oid


class RemoteNodeState(NodeState):
    """A schedulable node hosted by a NodeDaemon on (possibly) another
    machine. The executor threads only drive socket round-trips."""

    is_remote = True

    def __init__(self, node_id: str, total: ResourceSet, meta: dict):
        from ..node.client import NodeClient

        n_cpus = int(total.to_dict().get("CPU", 1) or 1)
        super().__init__(node_id, total,
                         max_workers=max(4, n_cpus * 2 + 4))
        self.meta = meta
        self.host = meta.get("host", "127.0.0.1")
        self.dispatch_port = int(meta["dispatch_port"])
        self.object_port = int(meta["object_port"])
        self.client = NodeClient(node_id, self.host, self.dispatch_port,
                                 self.object_port)
        self.exported_fids: set = set()
        self.reported_queued = 0   # from heartbeat load reports

    def utilization(self) -> float:
        # Queue depth reported by the daemon (other drivers' load too)
        # breaks ties toward idle nodes.
        return (self.available.scaled_utilization(self.total)
                + 0.01 * self.reported_queued)

    def shutdown(self):
        super().shutdown()
        self.client.close()


class RemotePlane:
    """Everything cluster-mode: control-plane attach, node membership,
    resource-view sync, remote task execution, cross-node object pulls."""

    def __init__(self, rt, address: str, advertise_host: str = "127.0.0.1"):
        from .._native import control_client as cc

        self.rt = rt
        self.address = address
        self.advertise_host = advertise_host
        host, _, port = address.partition(":")
        self.control = cc.ControlClient(int(port), host=host)

        # Serve the driver's own arena so daemons can pull `ray.put`
        # args. Bind 0.0.0.0 only when the driver advertises a
        # non-loopback address — an unauthenticated transfer port must
        # not be exposed for single-machine clusters.
        self.transfer_server = None
        self.object_port = 0
        if rt.shm is not None:
            from .._native.object_transfer import TransferServer

            bind_all = advertise_host not in ("127.0.0.1", "localhost")
            self.transfer_server = TransferServer(
                rt._shm_name, 0, bind_all=bind_all)
            self.object_port = self.transfer_server.port

        # node_id -> (host, object_port): survives until node death.
        self._endpoints: Dict[str, Tuple[str, int]] = {}
        # Multi-location directory bookkeeping: reverse index
        # node_id -> {ObjectID} of markers listing that node as a
        # location (so node death scrubs them in O(node's objects)),
        # and per-source pull counts aggregated from the daemons'
        # pull_complete reports (bench/dashboard proof the broadcast
        # is a relay tree, not a star).
        self._located: Dict[str, set] = {}
        self._located_lock = threading.Lock()
        self._pull_source_counts: Dict[str, int] = {}
        from .._native.pull_pool import PullClientPool

        self._pulls = (PullClientPool(rt._shm_name)
                       if rt.shm is not None else None)
        self._stop = threading.Event()
        self._known: set = set()
        # node_id -> monotonic time of a connection-failure drop; gates
        # re-join from the control plane's stale ALIVE view (see
        # _sync_nodes_locked quarantine).
        self._dropped_at: Dict[str, float] = {}
        # Guards membership mutation: sync_nodes runs from the poll
        # thread AND the pubsub callback — without this two racers
        # could each build a RemoteNodeState for the same node (one
        # leaking its executor + connections).
        self._sync_lock = threading.Lock()
        # Guards cross-driver actor attachment (a duplicate proxy
        # would leak its threads + daemon connection).
        self._attach_lock = threading.Lock()

        # runtime_env packaging: local dirs → content-addressed pkg://
        # URIs uploaded once to the control plane's KV; daemons
        # materialize them (runtime_env_packaging.py).
        # abspath → (tree_signature, uri)
        self._renv_uri_cache: Dict[str, Tuple[str, str]] = {}

        self.sync_nodes()
        with contextlib.suppress(Exception):
            self.control.subscribe("node_events", self._on_node_event)
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="remote-plane-poll")
        self._poll_thread.start()

    # -- membership + resource-view sync --------------------------------
    def sync_nodes(self) -> None:
        try:
            nodes = self.control.list_nodes()
        except Exception:  # noqa: BLE001 — control plane hiccup
            return
        with self._sync_lock:
            to_drop = self._sync_nodes_locked(nodes)
        # Dropped OUTSIDE the lock: _drop_node re-acquires it
        # (re-entering a plain Lock deadlocks the poll thread).
        for nid in to_drop:
            self._drop_node(nid)

    def _sync_nodes_locked(self, nodes) -> List[str]:
        to_drop: List[str] = []
        for n in nodes:
            nid = n["node_id"]
            try:
                meta = json.loads(n["meta"]) if n["meta"] else {}
            except ValueError:
                meta = {}
            if meta.get("node_kind") != "daemon":
                continue
            if not n["alive"]:
                if nid in self._known:
                    to_drop.append(nid)
                continue
            # Quarantine: a node WE dropped on a connection failure must
            # not rejoin from the control plane's still-ALIVE view until
            # its health expiry had a chance to run — otherwise a dead
            # daemon ping-pongs back into the scheduler (and PG repair
            # re-places bundles onto it) every poll for the whole
            # expiry window. A merely-slow node rejoins after the
            # quarantine lapses.
            dropped_at = self._dropped_at.get(nid)
            if dropped_at is not None:
                import time as _time

                if _time.monotonic() - dropped_at < 15.0:
                    continue
                del self._dropped_at[nid]
            if nid not in self._known:
                total = ResourceSet(meta.get("resources", {"CPU": 1.0}))
                node = RemoteNodeState(nid, total, meta)
                node.labels.update(meta.get("labels") or {})
                # Daemons report completed pulls out-of-band on the
                # dispatch socket; those reports feed the object
                # directory's location sets.
                node.client.on_pull_complete = self._on_pull_complete
                self._known.add(nid)
                self._endpoints[nid] = (node.host, node.object_port)
                self.rt.scheduler.add_node(node)
                logger.info("joined remote node %s (%s:%d)",
                            nid, node.host, node.dispatch_port)
            if n.get("load"):
                with contextlib.suppress(ValueError):
                    load = json.loads(n["load"])
                    self.rt.scheduler.update_node_report(
                        nid, ResourceSet(load.get("available", {})),
                        int(load.get("queued", 0)))
                    node = self.rt.scheduler.get_node(nid)
                    if node is not None:
                        # Full report (incl. per-host stats) for the
                        # dashboard's cluster view.
                        node.last_load = load
        return to_drop

    def _on_node_event(self, payload: bytes) -> None:
        text = payload.decode(errors="replace")
        state, _, nid = text.partition(":")
        if state == "DEAD":
            self._drop_node(nid)
        elif state == "ALIVE":
            self.sync_nodes()

    @staticmethod
    def _is_refused(err) -> bool:
        """Connection REFUSED = the daemon process is gone (its
        listener died with it) — worth quarantining. Timeouts/resets
        under load are transient and must heal on the next sync."""
        return isinstance(err, ConnectionRefusedError) or \
            "refused" in str(err).lower()

    def _drop_node(self, node_id: str, *,
                   quarantine: bool = False) -> None:
        with self._sync_lock:
            if node_id not in self._known:
                return
            self._known.discard(node_id)
            if quarantine:
                import time as _time

                self._dropped_at[node_id] = _time.monotonic()
        self._endpoints.pop(node_id, None)
        if self._pulls is not None:
            self._pulls.drop(node_id)
        self._deregister_node_locations(node_id)
        node = self.rt.scheduler.remove_node(node_id)
        logger.warning("remote node %s died", node_id)
        # Placement groups with bundles on the dead node re-place them
        # on survivors (reference: gcs_placement_group_manager.h
        # reschedule-on-node-death); queued work targeting those
        # bundles dispatches once the repair commits.
        from .placement_group import repair_for_dead_node

        repair_for_dead_node(self.rt, node_id)
        # Actors placed there: sever their connections so their mailbox
        # threads observe the death NOW and run restart-with-replacement
        # instead of waiting on a half-open TCP connection.
        with self.rt._actors_lock:
            actors = [st for st in self.rt._actors.values()
                      if getattr(st.node, "node_id", None) == node_id]
        for st in actors:
            conn = getattr(st, "_conn", None)
            if conn is not None:
                conn.close()
        del node

    def _poll_loop(self) -> None:
        while not self._stop.wait(config.cluster_poll_interval_s):
            self.sync_nodes()
            self._publish_demand()

    def _publish_demand(self) -> None:
        """Publish this driver's pending demand to the control plane
        (autoscaler v2: the control plane owns the cluster-wide demand
        view — reference gcs_autoscaler_state_manager.h; MonitorV2
        merges every driver's report)."""
        try:
            from ..autoscaler.v2 import DEMAND_PREFIX, serialize_demand

            detailed = self.rt.scheduler.pending_demand_detailed()
            self.control.kv_put(
                DEMAND_PREFIX + self.rt.job_id.hex(),
                serialize_demand(detailed))
        except Exception:  # noqa: BLE001 — best-effort report
            pass

    # -- arg packing ------------------------------------------------------
    def _fetch_candidates(self, d, target) -> List[Tuple[str, int]]:
        """Fallback-ordered source endpoints for marker `d` as pulled
        BY `target`: [relay-tree parent, confirmed locations...,
        primary owner]. The parent comes first so a broadcast forms
        pipelined chains (the parent serves committed chunks while its
        own tail is still arriving); the primary comes last as the
        always-correct anchor. The daemon tries them least-loaded-first
        with per-source fallback, so a stale or dead entry costs one
        failed attempt, never the pull."""
        cands: List[Tuple[str, int]] = []
        seen: set = set()

        def add(ep) -> None:
            if ep is not None and ep not in seen:
                seen.add(ep)
                cands.append(ep)

        pend = getattr(d, "pending", None)
        if pend is not None and target is not None:
            tid = target.node_id
            try:
                i = pend.index(tid)
            except ValueError:
                i = len(pend)
                pend.append(tid)
                with self._located_lock:
                    self._located.setdefault(tid, set()).add(
                        ObjectID(d.key))
            if i > 0:
                # Binary tree over dispatch order: consumer i's parent
                # is consumer (i-1)//2 → producer fan-out is 2, total
                # producer bytes ~O(log N) of the star cost.
                add(self._endpoints.get(pend[(i - 1) // 2]))
        for nid in list(getattr(d, "locations", ()) or ()):
            add(self._endpoints.get(nid))
        loc = getattr(d, "node_id", None)
        if loc is None:
            if self.rt.shm is not None and self.rt.shm.contains(d.key):
                add((self.advertise_host, self.object_port))
        else:
            add(self._endpoints.get(loc))
        return cands

    def pack_arg(self, v, fetch: List[Tuple[bytes, list]],
                 target: RemoteNodeState):
        """ObjectRef → wire marker + fetch hint. Mirrors
        Runtime._pack_arg but payloads may live on ANY node's arena.
        Fetch entries are (key, [(host, port), ...]) — a fallback-
        ordered multi-source location list — and are deduped per
        message by key (two args sharing one object need one pull)."""
        from ..core.runtime import _ShmMarker
        from .worker_proc import SerArg, ShmArg

        if not isinstance(v, ObjectRef):
            return v
        rt = self.rt
        while True:
            stored = rt.store.get_if_exists(v.id())
            if stored is None:
                rt._require_recoverable(v.id())
                rt._maybe_reconstruct([v.id()])
                stored = rt.store.get([v.id()], timeout=None)[0]
            d = stored.data
            if not isinstance(d, _ShmMarker):
                return SerArg(d.to_bytes(), stored.is_error)
            for key, _eps in fetch:
                if key == d.key:
                    return ShmArg(d.key, stored.is_error)
            # The candidate list may include the target's own endpoint:
            # the fetch entry makes the daemon CHECK presence
            # (contains() short-circuits a self-pull), so a payload
            # evicted on the target surfaces as fetch_failed →
            # reconstruction instead of a user-visible KeyError in the
            # worker.
            cands = self._fetch_candidates(d, target)
            if cands:
                fetch.append((d.key, cands))
                return ShmArg(d.key, stored.is_error)
            # Payload gone (evicted locally / node dead) — reconstruct.
            rt.store.delete([v.id()])
            rt._require_recoverable(v.id())
            rt._maybe_reconstruct([v.id()])

    def persist_detached_spec(self, st) -> None:
        """Persist a detached actor's creation spec in the control
        plane's KV so ANY surviving daemon can reconstruct it after its
        node dies — with no driver attached (reference:
        gcs_actor_manager.h:513 ReconstructActor; the GCS owns the
        actor FSM cluster-wide). The spec's restarts_left is the ONE
        cluster-wide restart budget: drivers never recreate detached
        actors themselves (they re-attach to the reconstruction), so
        the budget cannot be double-spent."""
        import cloudpickle

        def _has_ref(x) -> bool:
            if isinstance(x, ObjectRef):
                return True
            if isinstance(x, (list, tuple, set)):
                return any(_has_ref(v) for v in x)
            if isinstance(x, dict):
                return any(_has_ref(v) for v in x.values())
            return False

        if _has_ref(st.init_args) or _has_ref(st.init_kwargs):
            # A reconstruction has no driver to resolve refs (and the
            # ref's owner may be the thing that died). Plain-value
            # constructor args are the supported shape; say so once
            # instead of persisting a spec that crashes on restart.
            logger.warning(
                "detached actor %s has ObjectRef constructor args; "
                "cluster-owned reconstruction disabled for it (pass "
                "plain values to keep restarts available)",
                st.actor_id.hex()[:12])
            return
        spec = {
            "cls": cloudpickle.dumps(st.cls),
            "args": cloudpickle.dumps(st.init_args),
            "kwargs": cloudpickle.dumps(st.init_kwargs),
            "resources": st.resources.to_dict(),
            "restarts_left": int(st.max_restarts),
        }
        if st.runtime_env:
            spec["runtime_env"] = self.prepare_runtime_env(
                st.runtime_env)
        self.control.kv_put("detached_spec/" + st.actor_id.hex(),
                            cloudpickle.dumps(spec), overwrite=True)

    def prepare_runtime_env(self, renv):
        """Local working_dir/py_modules dirs → pkg:// URIs in the
        control plane's KV (uploaded once per content hash). No lock
        around the zip/upload I/O — a large tree must not serialize
        every other submission; a concurrent double-zip of the same
        tree is benign (content-addressed, idempotent upload)."""
        if not renv:
            return renv
        from . import runtime_env_packaging as pkg
        from .._native.control_client import AlreadyExistsError

        def upload(uri: str, blob: bytes) -> None:
            with contextlib.suppress(AlreadyExistsError):
                self.control.kv_put(pkg.KV_PREFIX + uri, blob,
                                    overwrite=False)

        return pkg.prepare_for_upload(renv, upload,
                                      self._renv_uri_cache)

    # -- remote execution -------------------------------------------------
    def _build_task_msg(self, spec: TaskSpec, node: RemoteNodeState
                        ) -> Dict[str, Any]:
        import cloudpickle

        streaming = spec.num_returns in ("streaming", "dynamic")
        fetch: List[Tuple[bytes, list]] = []
        msg = {
            "type": "task", "task_id": spec.task_id,
            "fid": spec.descriptor.function_id,
            "args": tuple(self.pack_arg(a, fetch, node)
                          for a in spec.args),
            "kwargs": {k: self.pack_arg(v, fetch, node)
                       for k, v in spec.kwargs.items()},
            "num_returns": 0 if streaming else spec.num_returns,
            "return_ids": [oid.binary() for oid in spec.return_ids],
            "streaming": streaming,
            "fetch": fetch,
            "resources": spec.resources.to_dict(),
            "max_calls": spec.max_calls,
            # The daemon's memory monitor prefers retriable victims
            # (worker_killing_policy.h RetriableFIFO).
            "retriable": spec.retries_left > 0,
            # Freely-placed tasks may be refused by a saturated daemon
            # (spillback) and rescheduled here; constrained placement
            # (node affinity, PG bundles — their resources are already
            # reserved) must run where sent.
            "spillable": (getattr(spec, "_pg_charge", None) is None
                          and not _is_constrained(
                              spec.scheduling_strategy)),
        }
        if getattr(spec, "trace_id", None):
            # Trace context crosses the control-plane socket. Cold
            # path: the daemon re-enters it and interposes its
            # dispatch span. Warm path: no daemon Python runs, so
            # want_timing asks the C loop for wall-clock dispatch
            # stamps and the driver synthesizes the equivalent span
            # (apply_native_dispatch_timing).
            msg["trace_id"] = spec.trace_id
            msg["parent_span_id"] = spec.parent_span_id
            msg["want_timing"] = True
        elif config.enable_timeline:
            # Untraced but timeline-enabled runs still want warm-path
            # lifecycle back-fill for `ray_tpu timeline` / list_tasks.
            msg["want_timing"] = True
        excl = getattr(spec, "_spill_excluded", None)
        if msg["spillable"] and excl:
            # Nodes that already refused this task: a refusing daemon's
            # redirect must not bounce it back to one of them.
            msg["spill_exclude"] = sorted(excl)
        if streaming and spec.task_id in self.rt._generators:
            # Live consumer only — reconstruction re-runs have nobody
            # sending credits; a watermark would deadlock the worker.
            msg["backpressure"] = config.generator_backpressure_max_items
        if spec.runtime_env:
            msg["runtime_env"] = self.prepare_runtime_env(
                spec.runtime_env)
        if spec.descriptor.function_id not in node.exported_fids:
            msg["fn"] = cloudpickle.dumps(
                self.rt.function_manager.get(spec.descriptor.function_id))
        return msg

    def execute_remote(self, spec: TaskSpec, node: RemoteNodeState) -> None:
        from ..node.client import NodeDispatchError
        from .runtime import _wrap
        from .worker_proc import WorkerCrashedError

        rt = self.rt
        t0 = time.monotonic()
        retried = False
        released = False  # charge already returned (spillback path)
        streaming = spec.num_returns in ("streaming", "dynamic")
        gst = rt._generators.get(spec.task_id) if streaming else None
        try:
            if spec.task_id in rt._cancelled:
                raise TaskCancelledError(spec.display_name())

            def on_stream(item):
                oid = ObjectID.for_return(spec.task_id, item["index"])
                with rt.lineage_lock:
                    rt.lineage[oid] = spec
                rt._store_packed(oid, item["payload"],
                                 node_id=node.node_id)
                if gst is not None:
                    ref = rt.register_ref(ObjectRef(oid))
                    with gst.cv:
                        gst.refs.append(ref)
                        gst.cv.notify_all()

            def set_ack(fn):
                if gst is not None:
                    with gst.cv:
                        gst.ack_cb = fn

            reply = None
            for _attempt in (0, 1):
                msg = self._build_task_msg(spec, node)
                if _attempt:
                    import cloudpickle

                    msg["fn"] = cloudpickle.dumps(
                        rt.function_manager.get(
                            spec.descriptor.function_id))
                reply = node.client.call(
                    msg, on_stream=on_stream if streaming else None,
                    ack_setter=set_ack if streaming else None)
                if not reply.get("need_fn"):
                    break
            node.exported_fids.add(spec.descriptor.function_id)
            # Merge daemon/worker-side spans BEFORE any error handling —
            # failed executions have spans too, and they are the
            # interesting ones.
            for ev in reply.get("spans") or ():
                with contextlib.suppress(Exception):
                    rt.events.record_raw(ev)
            nd_tm = reply.get("_nd_timing")
            if nd_tm:
                # Warm-path dispatch stamps: back-fill the lifecycle
                # phases the native hand-off skipped and synthesize
                # the daemon dispatch span the Python plane would have
                # recorded.
                with contextlib.suppress(Exception):
                    span_ev = apply_native_dispatch_timing(
                        spec.timing, nd_tm, trace_id=spec.trace_id,
                        parent_span_id=spec.parent_span_id,
                        node_id=node.node_id)
                    if span_ev is not None:
                        from ..util import tracing as _tracing

                        # Same record-time sampling verdict every
                        # other span in the trace got.
                        if spec.trace_id is None or \
                                _tracing.trace_sampled(spec.trace_id):
                            rt.events.record_raw(span_ev)
            if reply.get("spillback"):
                # The daemon is saturated (another driver raced us for
                # its capacity — our heartbeat view was stale). In one
                # locked step, release our charge (held, it would make
                # heartbeat foreign-netting hide exactly the usage that
                # caused the refusal) and merge the refusal's
                # authoritative load; then reschedule. No user retry is
                # burned (reference: lease spillback,
                # hybrid_scheduling_policy.h:50).
                released = True
                load = reply.get("load") or {}
                excl = getattr(spec, "_spill_excluded", None) or set()
                excl.add(node.node_id)
                spec._spill_excluded = excl
                # Honor the daemon's redirect (reference: the client
                # retries AT retry_at_raylet_address): the refuser's view
                # of the cluster is usually fresher than ours — the
                # scheduler tries the named node first on reschedule.
                hint = reply.get("retry_at")
                if hint and hint not in excl:
                    spec._spill_hint = hint
                rt.scheduler.apply_spill_refusal(
                    spec, node.node_id,
                    ResourceSet(load.get("available") or {}),
                    int(load.get("queued") or 0))
                retried = True
                rt._submit_when_ready(spec)
                return
            if reply.get("fetch_failed"):
                # An arg's payload vanished between packing and the
                # daemon's pull: reconstruct it and requeue without
                # burning user retries (object loss, not task failure —
                # reference: object_recovery_manager.h).
                key = reply["fetch_failed"]
                oid = ObjectID(key)
                spec._fetch_retries = getattr(spec, "_fetch_retries", 0) + 1
                if spec._fetch_retries > 3:
                    raise ObjectLostError(
                        f"arg {oid.hex()[:16]} unfetchable after "
                        "3 reconstruction attempts")
                rt.store.delete([oid])
                rt._maybe_reconstruct([oid])
                retried = True
                rt._submit_when_ready(spec)
                return
            if reply.get("crashed"):
                raise WorkerCrashedError(reply["crashed"])
            if reply.get("error") is not None:
                raise rt._unpack_error(reply["error"])
            if streaming and gst is not None:
                with gst.cv:
                    gst.done = True
                    gst.cv.notify_all()
                rt._generators.pop(spec.task_id, None)
            else:
                for oid, packed in zip(spec.return_ids, reply["returns"]):
                    rt._store_packed(oid, packed, node_id=node.node_id)
        except NodeDispatchError as e:
            # Connection-level failure: the daemon is unreachable. Drop
            # the node NOW (socket-error failure detection — reference:
            # workers detect raylet death via the socket) so the retry
            # lands elsewhere; if the daemon is actually fine, the next
            # membership sync re-adds it. A REFUSED connection means
            # the process is gone — quarantine so the control plane's
            # stale ALIVE view can't ping-pong it back in.
            self._drop_node(node.node_id,
                            quarantine=self._is_refused(e))
            retried = rt._maybe_retry_system(spec, e)
            if not retried:
                rt._store_error(spec, _wrap(spec, e), t0)
        except WorkerCrashedError as e:
            retried = rt._maybe_retry_system(spec, e)
            if not retried:
                rt._store_error(spec, _wrap(spec, e), t0)
        except BaseException as e:  # noqa: BLE001
            retried = rt._maybe_retry(spec, e)
            if not retried:
                rt._store_error(spec, _wrap(spec, e), t0)
        finally:
            if not retried:
                # Remote executions finish here, not in the local
                # worker loop — stamp it so phase_durations gets a
                # total even when intermediate phases were skipped.
                spec.timing.setdefault("finished", time.time())
                rt._task_finished(spec)
            if not released:
                rt.scheduler.release_task(spec, node.node_id)
            rt.events.record(spec.display_name(), t0, time.monotonic(),
                             node.node_id, spec.task_id.hex(),
                             timing=spec.timing, trace_id=spec.trace_id,
                             deps=spec.dep_ids(),
                             returns=spec.return_hexes())

    # -- object directory (multi-location) -------------------------------
    def _on_pull_complete(self, node_id: str, reply: Dict[str, Any]
                          ) -> None:
        """A daemon finished pulling objects for a task: register it
        as an additional source for each (reference:
        ownership_based_object_directory.h — the owner's location set
        grows as copies spread). Runs on connection-reader threads;
        must never raise (the caller suppresses, but a failure here
        only loses a hint)."""
        nid = reply.get("node_id") or node_id
        for item in reply.get("pulls") or ():
            try:
                key, src = item[0], item[1]
                self._register_location(nid, bytes(key), str(src))
            except Exception:  # noqa: BLE001 — malformed entry
                continue

    def _register_location(self, node_id: str, key: bytes,
                           src: str) -> None:
        from ..core.runtime import _ShmMarker

        oid = ObjectID(key)
        stored = self.rt.store.get_if_exists(oid)
        if stored is None or not isinstance(stored.data, _ShmMarker):
            return
        stored.data.add_location(node_id)
        with self._located_lock:
            self._located.setdefault(node_id, set()).add(oid)
            self._pull_source_counts[src] = \
                self._pull_source_counts.get(src, 0) + 1
        get_recorder().record(
            "object_transfer", "location_added",
            object_id=oid.hex()[:16], node=node_id, source=src)

    def _deregister_node_locations(self, node_id: str) -> None:
        """Node death: its arena is gone — scrub it from every marker
        that listed it (as confirmed location or relay-tree pending)
        so no future fetch hint points at a dead endpoint."""
        from ..core.runtime import _ShmMarker

        with self._located_lock:
            oids = self._located.pop(node_id, set())
        for oid in oids:
            stored = self.rt.store.get_if_exists(oid)
            if stored is not None and isinstance(stored.data,
                                                _ShmMarker):
                stored.data.discard_location(node_id)
        if oids:
            get_recorder().record(
                "object_transfer", "locations_scrubbed",
                node=node_id, count=len(oids))

    def pull_source_counts(self) -> Dict[str, int]:
        """source endpoint -> completed-pull count, aggregated from
        daemon pull_complete reports (proves broadcast shape)."""
        with self._located_lock:
            return dict(self._pull_source_counts)

    def prefetch_objects(self, refs, node_ids) -> Dict[str, int]:
        """Pre-stage objects on target nodes ahead of the calls that
        consume them (the RLHF weight-refresh plane): each node's
        daemon gets a `weight_refresh` message with relay fetch hints
        and pulls immediately, so by the time the generator actors'
        refresh calls arrive their arg fetches short-circuit on
        contains(). Dispatch order walks `node_ids` as given and
        `_fetch_candidates` enrolls each node in the marker's relay
        tree as it goes — the prefetch wave IS the broadcast tree.
        Best-effort: a node that cannot prefetch reports -1 and its
        actor-call args fall back to the normal pull path.
        Returns node_id -> prefetched-object count."""
        from ..core.runtime import _ShmMarker

        markers = []
        for ref in refs:
            stored = self.rt.store.get_if_exists(ref.id())
            if stored is not None and isinstance(stored.data,
                                                _ShmMarker):
                markers.append(stored.data)
        out: Dict[str, int] = {}
        if not markers:
            return out
        for nid in node_ids:
            node = self.rt.scheduler.get_node(nid)
            if not isinstance(node, RemoteNodeState):
                continue
            fetch = [(d.key, self._fetch_candidates(d, node))
                     for d in markers]
            fetch = [(k, eps) for k, eps in fetch if eps]
            if not fetch:
                continue
            try:
                reply = node.client.call({"type": "weight_refresh",
                                          "fetch": fetch})
                out[nid] = int(reply.get("pulled", 0))
            except Exception:  # noqa: BLE001 — prefetch is advisory
                out[nid] = -1
        if out:
            get_recorder().record(
                "rlhf", "weight_refresh_prefetch",
                objects=len(markers), nodes=len(out),
                pulled=sum(v for v in out.values() if v > 0))
        return out

    # -- cross-node object pulls (driver get) ----------------------------
    def ensure_local(self, marker) -> None:
        """Pull a remote-located object into the driver's arena from
        ANY live location (confirmed secondaries first-class, primary
        as anchor), with per-source fallback. Raises KeyError when it
        cannot be fetched (→ reconstruction)."""
        rt = self.rt
        if rt.shm is None or self._pulls is None:
            raise KeyError(marker.key)
        if rt.shm.contains(marker.key):
            return
        eps: List[Tuple[str, int]] = []
        seen: set = set()
        for nid in list(getattr(marker, "locations", ()) or ()):
            ep = self._endpoints.get(nid)
            if ep is not None and ep not in seen:
                seen.add(ep)
                eps.append(ep)
        if marker.node_id is not None:
            ep = self._endpoints.get(marker.node_id)
            if ep is not None and ep not in seen:
                eps.append(ep)
        if not eps:
            raise KeyError(marker.key)
        try:
            # The object key is the dedup/fairness bucket: concurrent
            # gets of the same object coalesce into one wire transfer.
            self._pulls.pull_multi(marker.key, eps, marker.key)
        except Exception as e:  # noqa: BLE001 — all sources died mid-pull
            if not rt.shm.contains(marker.key):
                if "store full" in str(e):
                    # Sources are alive; OUR arena can't admit the
                    # object. Not an eviction — callers may stream the
                    # bytes inline (fetch_inline) instead of burning
                    # the location set on a reconstruction.
                    raise ObjectStoreFullError(
                        f"local arena cannot admit "
                        f"{marker.key.hex()[:16]}") from e
                raise KeyError(marker.key) from None

    def fetch_inline(self, marker) -> Optional[bytes]:
        """Stream an object's bytes straight off a holder's transfer
        port into driver memory — no local arena residency. Fallback
        for objects larger than the driver's arena: the marker (and
        its location directory) stay intact. Returns None when no
        source can serve it."""
        from .._native.object_transfer import (TransferError,
                                               fetch_object_bytes)

        eps: List[Tuple[str, int]] = []
        seen: set = set()
        for nid in list(getattr(marker, "locations", ()) or ()):
            ep = self._endpoints.get(nid)
            if ep is not None and ep not in seen:
                seen.add(ep)
                eps.append(ep)
        if marker.node_id is not None:
            ep = self._endpoints.get(marker.node_id)
            if ep is not None and ep not in seen:
                eps.append(ep)
        for host, port in eps:
            try:
                blob = fetch_object_bytes(host, port, marker.key)
            except (TransferError, OSError):
                continue  # source died mid-stream: next candidate
            if blob is not None:
                get_recorder().record(
                    "object_transfer", "fetch_inline",
                    object_id=marker.key.hex()[:16],
                    source=f"{host}:{port}", bytes=len(blob))
                return blob
        return None

    # -- cross-driver actors ----------------------------------------------
    def attach_named_actor(self, scoped: str):
        """Look a named actor up in the control plane's actor table and
        attach a local PROXY through which this driver's calls reach
        the daemon hosting it (reference: cross-job named-actor lookup
        via GcsActorManager). Returns the ActorID or None."""
        import json as _json

        from .ids import ActorID

        try:
            hexid = self.control.get_named_actor(scoped)
            info = self.control.get_actor(hexid)
        except Exception:  # noqa: BLE001 — unknown name
            return None
        if info.get("state") == "DEAD":
            return None
        try:
            meta = _json.loads(info.get("meta") or "{}")
        except ValueError:
            meta = {}
        node = self.rt.scheduler.get_node(meta.get("node_id", ""))
        if node is None or not getattr(node, "is_remote", False):
            return None
        aid = ActorID(bytes.fromhex(hexid))
        proxy_cls = remote_actor_proxy_cls()
        with self._attach_lock:
            with self.rt._actors_lock:
                existing = self.rt._actors.get(aid)
                if existing is not None:
                    if not existing.dead.is_set():
                        return aid
                    # A previously-attached proxy died (e.g. transient
                    # network failure) while the REAL actor may live
                    # on: drop it and re-attach fresh.
                    self.rt._actors.pop(aid, None)
            st = proxy_cls(
                self.rt, aid, _ProxyStub, (), {},
                node=node, name=scoped,
                max_concurrency=1, max_restarts=0,
                resources=_EMPTY_RESOURCES,
                concurrency_groups=dict(
                    meta.get("concurrency_groups") or {}))
            st.method_defaults = dict(meta.get("method_defaults") or {})
            with self.rt._actors_lock:
                self.rt._actors[aid] = st
                self.rt._named_actors.pop(scoped, None)
                self.rt._named_actors[scoped] = aid
                self.rt._scoped_by_actor.setdefault(aid, scoped)
        return aid

    # -- actor placement --------------------------------------------------
    def replace_node_for(self, st) -> Optional[RemoteNodeState]:
        """Find a new home for an actor whose node died; charges the
        actor's resources on the chosen node (the old charge died with
        the old node). Reference: GcsActorScheduler re-leasing a worker
        on a live node after node failure."""
        deadline = time.monotonic() + config.actor_replace_timeout_s
        while time.monotonic() < deadline:
            nodes = [n for n in self.rt.scheduler.nodes()
                     if isinstance(n, RemoteNodeState) and n.alive
                     and st.resources.fits(n.available)]
            if nodes:
                node = min(nodes, key=lambda n: n.utilization())
                with self.rt.scheduler._lock:
                    node.charge(st.resources)
                return node
            time.sleep(0.1)
        return None

    def shutdown(self) -> None:
        self._stop.set()
        with contextlib.suppress(Exception):
            from ..autoscaler.v2 import DEMAND_PREFIX

            self.control.kv_del(DEMAND_PREFIX + self.rt.job_id.hex())
        with contextlib.suppress(Exception):
            self.control.close()
        if self._pulls is not None:
            self._pulls.close()
        if self.transfer_server is not None:
            with contextlib.suppress(Exception):
                self.transfer_server.stop()


# ---------------------------------------------------------------------------
# Remote actors
# ---------------------------------------------------------------------------

_remote_actor_cls = None


def remote_actor_state_cls():
    """RemoteProcActorState, built lazily (runtime.py imports this
    module's names lazily too — a top-level subclass would be a cycle)."""
    global _remote_actor_cls
    if _remote_actor_cls is not None:
        return _remote_actor_cls

    import cloudpickle

    from ..node.client import NodeDispatchError
    from .exceptions import TaskError
    from .runtime import ProcActorState, _wrap
    from .worker_proc import WorkerCrashedError

    class RemoteProcActorState(ProcActorState):
        """An actor hosted by a dedicated worker on a REMOTE node
        daemon. Reuses ActorState's mailbox/restart machinery; the
        dedicated long-lived connection (one in-flight call, serial)
        preserves per-caller call order. Node death severs the
        connection → the normal restartable-crash path runs, and
        _construct re-places the actor on a surviving node
        (reference: gcs_actor_manager.h:513 ReconstructActor)."""

        def __init__(self, *args, **kwargs):
            self._conn = None
            super().__init__(*args, **kwargs)

        @property
        def _plane(self) -> RemotePlane:
            return self.rt.remote_plane

        def _construct(self, gen: int) -> bool:
            plane = self._plane
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            node_gone = (not self.node.alive
                         or self.node.node_id not in plane._known)
            if gen > 0 and self.detached and node_gone:
                # NODE-death restart of a detached actor is
                # CLUSTER-owned (a surviving daemon reconstructs from
                # the persisted spec; reference: gcs_actor_manager.h
                # ReconstructActor). The driver only RE-ATTACHES —
                # recreating here would race the adoption into two
                # live instances and double-spend the restart budget.
                # A worker crash with the node ALIVE follows the
                # normal driver recreate below (the daemon also
                # self-restarts crashed detached actors; create is
                # idempotent on the daemon side via the actor map).
                return self._rebind_detached(gen)
            # Node-resolution loop: an unreachable node is DROPPED and a
            # replacement picked without burning max_restarts — node
            # unreachability is placement failure, not actor failure
            # (reference: GcsActorScheduler retries leasing elsewhere).
            deadline = time.monotonic() + config.actor_replace_timeout_s
            last_err: Optional[BaseException] = None
            while time.monotonic() < deadline:
                if (not self.node.alive
                        or self.node.node_id not in plane._known):
                    node = plane.replace_node_for(self)
                    if node is None:
                        break
                    self.node = node
                conn = None
                try:
                    fetch: List[Tuple[bytes, list]] = []
                    msg = {
                        "type": "actor_create", "task_id": None,
                        "actor_id": self.actor_id.binary(),
                        "cls": cloudpickle.dumps(self.cls),
                        "args": tuple(
                            plane.pack_arg(a, fetch, self.node)
                            for a in self.init_args),
                        "kwargs": {
                            k: plane.pack_arg(v, fetch, self.node)
                            for k, v in self.init_kwargs.items()},
                        "fetch": fetch,
                        "resources": self.resources.to_dict(),
                        "detached": self.detached,
                    }
                    if self.runtime_env:
                        msg["runtime_env"] = plane.prepare_runtime_env(
                            self.runtime_env)
                    conn = self.node.client.open_conn()
                    reply = conn.request(msg)
                except NodeDispatchError as e:
                    if conn is not None:
                        conn.close()
                    last_err = e
                    plane._drop_node(self.node.node_id,
                                     quarantine=plane._is_refused(e))
                    time.sleep(0.1)
                    continue
                except OSError as e:  # open_conn refused
                    last_err = e
                    plane._drop_node(self.node.node_id,
                                     quarantine=plane._is_refused(e))
                    time.sleep(0.1)
                    continue
                try:
                    if reply.get("crashed"):
                        raise WorkerCrashedError(reply["crashed"])
                    if reply.get("fetch_failed"):
                        raise WorkerCrashedError(
                            "actor init arg unfetchable "
                            f"({ObjectID(reply['fetch_failed']).hex()[:16]})")
                    if reply.get("error") is not None:
                        raise self.rt._unpack_error(reply["error"])
                    self._conn = conn
                    self.instance = conn  # marker: lives remotely
                    self.ready.set()
                    # Restart/migration: refresh the actor-table
                    # location so cross-driver lookups find the NEW
                    # node (idempotent — the table accepts a same-id
                    # re-registration; creation-time registration
                    # happens in create_actor via the same helper).
                    if getattr(self, "_cp_registered", False) or (
                            self.detached
                            or self.rt._scoped_by_actor.get(
                                self.actor_id)):
                        scoped = self.rt._scoped_by_actor.get(
                            self.actor_id) or ""
                        with contextlib.suppress(Exception):
                            self.rt.register_in_actor_table(
                                self, scoped)
                    return True
                except BaseException as e:  # noqa: BLE001
                    conn.close()
                    if isinstance(e, WorkerCrashedError):
                        self._restartable_kill = True
                    self.death_cause = TaskError(
                        self.cls.__name__ + ".__init__", e)
                    self._die(gen)
                    return False
            self.death_cause = ActorDiedError(
                self.actor_id.hex(),
                f"no surviving node can host this actor "
                f"(last error: {last_err})")
            self._restartable_kill = False
            self._die(gen)
            return False

        def _run_method(self, spec: TaskSpec):
            rt = self.rt
            plane = self._plane
            spec.redelivered = False
            t0 = time.monotonic()
            streaming = spec.num_returns in ("streaming", "dynamic")
            gst = rt._generators.get(spec.task_id) if streaming else None
            try:
                fetch: List[Tuple[bytes, list]] = []
                msg = {
                    "type": "actor_call", "task_id": spec.task_id,
                    "actor_id": self.actor_id.binary(),
                    "method": spec.method_name,
                    "args": tuple(plane.pack_arg(a, fetch, self.node)
                                  for a in spec.args),
                    "kwargs": {k: plane.pack_arg(v, fetch, self.node)
                               for k, v in spec.kwargs.items()},
                    "num_returns": 0 if streaming else spec.num_returns,
                    "return_ids": [oid.binary()
                                   for oid in spec.return_ids],
                    "streaming": streaming,
                    "fetch": fetch,
                }
                if getattr(spec, "trace_id", None):
                    msg["trace_id"] = spec.trace_id
                    msg["parent_span_id"] = spec.parent_span_id
                if streaming and gst is not None:
                    msg["backpressure"] = \
                        config.generator_backpressure_max_items
                if self.runtime_env:
                    msg["runtime_env"] = plane.prepare_runtime_env(
                        self.runtime_env)

                def on_stream(item):
                    oid = ObjectID.for_return(spec.task_id, item["index"])
                    with rt.lineage_lock:
                        rt.lineage[oid] = spec
                    rt._store_packed(oid, item["payload"],
                                     node_id=self.node.node_id)
                    if gst is not None:
                        ref = rt.register_ref(ObjectRef(oid))
                        with gst.cv:
                            gst.refs.append(ref)
                            gst.cv.notify_all()

                if gst is not None:
                    with gst.cv:
                        gst.ack_cb = self._conn.send_ack
                try:
                    reply = self._conn.request(
                        msg, on_stream=on_stream if streaming else None)
                finally:
                    if gst is not None:
                        with gst.cv:
                            gst.ack_cb = None
                for ev in reply.get("spans") or ():
                    with contextlib.suppress(Exception):
                        rt.events.record_raw(ev)
                if reply.get("crashed"):
                    raise WorkerCrashedError(reply["crashed"])
                if reply.get("fetch_failed"):
                    raise WorkerCrashedError(
                        "actor call arg unfetchable")
                if reply.get("error") is not None:
                    err = rt._unpack_error(reply["error"])
                    from .runtime import _ActorExit

                    if isinstance(err, _ActorExit):
                        rt._store_results(spec, None, t0)
                        self.death_cause = ActorDiedError(
                            self.actor_id.hex(),
                            "exit_actor() was called.")
                        self.dead.set()
                        return
                    raise err
                if streaming and gst is not None:
                    with gst.cv:
                        gst.done = True
                        gst.cv.notify_all()
                    rt._generators.pop(spec.task_id, None)
                else:
                    for oid, packed in zip(spec.return_ids,
                                           reply["returns"]):
                        rt._store_packed(oid, packed,
                                         node_id=self.node.node_id)
            except (WorkerCrashedError, NodeDispatchError) as e:
                # A KILLED detached/named actor must not be resurrected
                # by its owner's restart machinery: another driver's
                # ray.kill records DEAD in the actor table — honor it
                # (reference: GcsActorManager kill marks the actor
                # non-restartable cluster-wide).
                if getattr(self, "_cp_registered", False) or \
                        self.detached:
                    try:
                        info = plane.control.get_actor(
                            self.actor_id.hex())
                        if info.get("state") == "DEAD":
                            self.death_cause = ActorDiedError(
                                self.actor_id.hex(),
                                "killed via ray.kill() (cross-driver)")
                            self._restartable_kill = False
                            rt._store_error(spec, self.death_cause, t0)
                            self.dead.set()
                            return
                    except Exception:  # noqa: BLE001
                        pass
                left = spec.task_retries_left
                if left is None:
                    left = self.max_task_retries
                will_restart = self.restarts < self.max_restarts
                self.death_cause = ActorDiedError(
                    self.actor_id.hex(), f"actor worker died: {e}")
                self._restartable_kill = True
                if (left != 0) and will_restart and not streaming:
                    spec.task_retries_left = (left - 1 if left > 0
                                              else left)
                    spec.redelivered = True
                    self.redeliver_q.put(spec)
                    self.dead.set()
                    return
                rt._store_error(spec, _wrap(spec, e), t0)
                self.dead.set()
            except BaseException as e:  # noqa: BLE001
                rt._store_error(spec, _wrap(spec, e), t0)
            finally:
                if not spec.redelivered:
                    rt._task_finished(spec)

        def _rebind_detached(self, gen: int) -> bool:
            """Wait for the cluster's reconstruction of this detached
            actor and point this driver's mailbox at its new home."""
            plane = self._plane
            old_node_id = self.node.node_id
            # Reconstruction worst case = health expiry + adoption
            # retries (2+4+...s) + env setup; actor_replace_timeout_s
            # (placement-failure scale) is far too short for it.
            deadline = time.monotonic() + max(
                60.0, 3 * config.actor_replace_timeout_s)
            while time.monotonic() < deadline:
                try:
                    info = plane.control.get_actor(self.actor_id.hex())
                    meta = json.loads(info.get("meta") or "{}")
                except Exception:  # noqa: BLE001
                    time.sleep(0.5)
                    continue
                if info.get("state") == "DEAD":
                    break
                nid = meta.get("node_id", "")
                node = self.rt.scheduler.get_node(nid)
                if (nid and nid != old_node_id and node is not None
                        and node.alive
                        and getattr(node, "is_remote", False)):
                    try:
                        conn = node.client.open_conn()
                    except Exception:  # noqa: BLE001
                        time.sleep(0.5)
                        continue
                    self.node = node
                    self._conn = conn
                    self.instance = conn
                    self.ready.set()
                    return True
                time.sleep(0.5)
            self.death_cause = ActorDiedError(
                self.actor_id.hex(),
                "detached actor was not reconstructed in time")
            self._die(gen)
            return False

        def _send_actor_kill(self) -> None:
            """Deliver actor_kill to the daemon, surviving a closed
            NodeClient: after a (possibly stale) driver-side drop the
            pooled client raises immediately, so fall back to ONE
            fresh direct connection — a genuinely dead daemon refuses
            it fast, a stale-dropped one processes the kill and frees
            the actor's charge."""
            msg = {"type": "actor_kill",
                   "actor_id": self.actor_id.binary()}
            try:
                self.node.client.call(msg)
                return
            except Exception:  # noqa: BLE001 — client closed/broken
                pass
            try:
                from ..node.client import NodeConn

                conn = NodeConn(self.node.host, self.node.dispatch_port,
                                timeout=2.0)
                try:
                    conn.request(msg)
                finally:
                    conn.close()
            except Exception:  # noqa: BLE001 — daemon really gone
                pass

        def kill(self, *, no_restart: bool = True):
            # Kill the daemon-side instance EAGERLY: an in-flight call
            # blocks this actor's mailbox thread in conn.request until
            # the worker process dies, and _die (which also fires
            # actor_kill) only runs after that thread exits — waiting
            # for _die to send the kill would deadlock a stuck actor
            # and leak its daemon + driver resource charges forever.
            self._send_actor_kill()
            super().kill(no_restart=no_restart)

        def _die(self, gen: int):
            # Skip ProcActorState._die (pool retire) — the worker lives
            # on the daemon; tell it to drop the actor instead.
            from .runtime import ActorState

            ActorState._die(self, gen)
            if self.dead.is_set():
                conn, self._conn = self._conn, None
                if conn is not None:
                    conn.close()
                # Best-effort even when the driver's view says the node
                # is dead — the view can be a stale drop while the
                # daemon still hosts (and charges for) the actor.
                self._send_actor_kill()

    _remote_actor_cls = RemoteProcActorState
    return _remote_actor_cls


class _ProxyStub:
    """Placeholder class for attached (non-owned) remote actors."""


_EMPTY_RESOURCES = ResourceSet({})
_remote_proxy_cls = None


def remote_actor_proxy_cls():
    """Proxy for an actor OWNED BY ANOTHER DRIVER (attached via the
    control plane's actor table): calls flow over a dedicated daemon
    connection like an owned remote actor, but this driver neither
    constructs, restarts, nor (implicitly) kills it."""
    global _remote_proxy_cls
    if _remote_proxy_cls is not None:
        return _remote_proxy_cls

    from .exceptions import ActorDiedError as _ADE
    from .runtime import ActorState

    base = remote_actor_state_cls()

    class RemoteActorProxy(base):  # type: ignore[misc,valid-type]
        def __init__(self, *args, **kwargs):
            self._explicit_kill = False
            super().__init__(*args, **kwargs)
            # The underlying actor belongs to another driver: OUR
            # shutdown must not reap it (only explicit ray.kill).
            self.detached = True

        def _construct(self, gen: int) -> bool:
            # Attach, don't create: the actor already lives on the
            # daemon; just open this driver's call connection.
            try:
                self._conn = self.node.client.open_conn()
                self.instance = self._conn
                self.ready.set()
                return True
            except Exception as e:  # noqa: BLE001
                self.death_cause = _ADE(self.actor_id.hex(),
                                        f"cannot reach host: {e}")
                self._restartable_kill = False
                self._die(gen)
                return False

        def kill(self, *, no_restart: bool = True):
            # Explicit cross-driver kill IS allowed (reference:
            # ray.kill on a detached actor from any job). The base
            # class sends the daemon-side kill eagerly.
            self._explicit_kill = True
            super().kill(no_restart=no_restart)

        def _die(self, gen: int):
            ActorState._die(self, gen)
            if self.dead.is_set():
                conn, self._conn = self._conn, None
                if conn is not None:
                    conn.close()
                if self._explicit_kill:
                    self._send_actor_kill()
                    # Record the death for other drivers' lookups.
                    with contextlib.suppress(Exception):
                        self.rt.remote_plane.control.update_actor(
                            self.actor_id.hex(), "DEAD")

    _remote_proxy_cls = RemoteActorProxy
    return _remote_proxy_cls
