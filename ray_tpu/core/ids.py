"""Binary ID types for ray_tpu.

Mirrors the capability of the reference's ID scheme
(reference: src/ray/common/id.h) — JobID ⊂ ActorID ⊂ TaskID, and ObjectIDs
that embed their owning TaskID plus a return/put index so that lineage
(which task produced this object) is recoverable from the ID alone.

Layout (bytes, big-endian indices):
    JobID    = 4 random bytes
    ActorID  = JobID (4) + 8 random            = 12
    TaskID   = ActorID (12) + 12 random        = 24
    ObjectID = TaskID (24) + 4-byte LE index   = 28
The index space is split: indices < PUT_INDEX_BASE are task returns,
indices >= PUT_INDEX_BASE are `put` objects, matching the reference's
return/put partitioning.
"""

from __future__ import annotations

import os
import threading

_JOB_LEN = 4
_ACTOR_LEN = 12
_TASK_LEN = 24
_OBJECT_LEN = 28

PUT_INDEX_BASE = 1 << 24  # indices above this are ray_tpu.put() objects

_NIL_TASK = b"\xff" * _TASK_LEN


class BaseID:
    __slots__ = ("_bytes",)
    _LEN = 0

    def __init__(self, b: bytes):
        if len(b) != self._LEN:
            raise ValueError(
                f"{type(self).__name__} requires {self._LEN} bytes, got {len(b)}"
            )
        self._bytes = bytes(b)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls._LEN))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls._LEN)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self._LEN

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"


class JobID(BaseID):
    _LEN = _JOB_LEN


class ActorID(BaseID):
    _LEN = _ACTOR_LEN

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(_ACTOR_LEN - _JOB_LEN))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_LEN])


class TaskID(BaseID):
    _LEN = _TASK_LEN

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        return cls(
            job_id.binary()
            + b"\x00" * (_ACTOR_LEN - _JOB_LEN)
            + os.urandom(_TASK_LEN - _ACTOR_LEN)
        )

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(_TASK_LEN - _ACTOR_LEN))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:_ACTOR_LEN])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_LEN])


class ObjectID(BaseID):
    _LEN = _OBJECT_LEN

    @classmethod
    def for_return(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        assert 0 <= return_index < PUT_INDEX_BASE
        return cls(task_id.binary() + return_index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + (PUT_INDEX_BASE + put_index).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        """The task that created this object (lineage addressing)."""
        return TaskID(self._bytes[:_TASK_LEN])

    def index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_LEN:], "little")

    def is_put(self) -> bool:
        return self.index() >= PUT_INDEX_BASE

    def is_return(self) -> bool:
        return not self.is_put()

    def return_index(self) -> int:
        assert self.is_return()
        return self.index()


class _PutCounter:
    """Per-process monotonically increasing put index."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


put_counter = _PutCounter()
