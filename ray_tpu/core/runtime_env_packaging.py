"""Runtime-env packaging + URI cache.

Capability-equivalent of the reference's runtime-env packaging pipeline
(reference: python/ray/_private/runtime_env/packaging.py — zip a local
working_dir/py_modules dir, content-address it as a gcs:// URI, upload
to GCS KV; uri_cache.py — per-node cache of materialized URIs with
size-based eviction; the per-node agent materializes envs before a
lease is granted, runtime_env_agent.py:161).

Here: directories are zipped deterministically, content-addressed as
``pkg://<sha256-16>.zip``, uploaded once to the control plane's KV; the
NODE DAEMON materializes them into a local URICache before forwarding
the task to a worker (node/daemon.py), so worker code sees plain local
paths. ``file://`` URIs (shared filesystems) skip the KV hop.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import shutil
import threading
import zipfile
from typing import Any, Callable, Dict, List, Optional, Tuple

KV_PREFIX = "_renv_pkg/"
PKG_SCHEME = "pkg://"
# Must stay under the control plane's inbound frame cap — an oversized
# kv_put would kill the driver's shared control connection. Bigger
# trees should ship as file:// URIs on a shared filesystem.
_MAX_PACKAGE_BYTES = 48 * 1024 * 1024


def package_directory(path: str) -> Tuple[str, bytes]:
    """Zip `path` deterministically; returns (uri, zip_bytes). The URI
    is content-addressed, so identical trees dedupe across jobs."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"not a directory: {path}")
    buf = io.BytesIO()
    entries: List[Tuple[str, str]] = []
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            entries.append((full, os.path.relpath(full, path)))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for full, rel in entries:
            # Fixed timestamp → byte-stable archive → stable hash.
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = 0o644 << 16
            with open(full, "rb") as f:
                zf.writestr(info, f.read())
    blob = buf.getvalue()
    if len(blob) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package for {path} is {len(blob)} bytes "
            f"(max {_MAX_PACKAGE_BYTES}); ship large trees as a "
            f"file:// URI on a shared filesystem instead")
    digest = hashlib.sha256(blob).hexdigest()[:16]
    return f"{PKG_SCHEME}{digest}.zip", blob


def is_uri(value: str) -> bool:
    return isinstance(value, str) and (
        value.startswith(PKG_SCHEME) or value.startswith("file://"))


class URICache:
    """Materialized-URI cache with total-size LRU eviction
    (reference: _private/runtime_env/uri_cache.py). get() returns the
    extracted directory for a URI, fetching + unzipping at most once."""

    def __init__(self, base_dir: str,
                 max_total_bytes: int = 2 * 1024**3,
                 min_idle_before_evict_s: float = 3600.0):
        self.base_dir = base_dir
        self.max_total_bytes = max_total_bytes
        # Entries touched more recently than this are never evicted —
        # a materialized working_dir may be the cwd of a RUNNING task
        # (the reference's uri_cache only evicts unreferenced URIs; an
        # idle window is the bound used here).
        self.min_idle_before_evict_s = min_idle_before_evict_s
        os.makedirs(base_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._sizes: Dict[str, int] = {}
        self._last_used: Dict[str, float] = {}
        self._order: List[str] = []  # LRU: oldest first

    def _dir_for(self, uri: str) -> str:
        name = hashlib.sha256(uri.encode()).hexdigest()[:24]
        return os.path.join(self.base_dir, name)

    def get(self, uri: str,
            fetch: Callable[[str], bytes]) -> str:
        """Local directory containing the URI's extracted contents.
        `fetch(uri)` must return the zip bytes on a cache miss."""
        import time as _time

        target = self._dir_for(uri)
        with self._lock:
            if uri in self._sizes:
                self._order.remove(uri)
                self._order.append(uri)
                self._last_used[uri] = _time.monotonic()
                return target
        if uri.startswith("file://"):
            blob = open(uri[len("file://"):], "rb").read()
        else:
            blob = fetch(uri)
        # Per-thread scratch dir: concurrent misses of the same URI
        # (thread-per-connection daemon) must not extract into each
        # other's tree; the loser's install is discarded under the lock.
        tmp = f"{target}.tmp{os.getpid()}-{threading.get_ident()}"
        with contextlib.suppress(FileNotFoundError):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            for info in zf.infolist():
                # Zip-slip guard: entries must extract under tmp.
                dest = os.path.realpath(os.path.join(tmp, info.filename))
                if not dest.startswith(os.path.realpath(tmp) + os.sep):
                    raise ValueError(
                        f"unsafe path in package: {info.filename!r}")
            zf.extractall(tmp)
        size = sum(os.path.getsize(os.path.join(r, f))
                   for r, _d, fs in os.walk(tmp) for f in fs)
        with self._lock:
            if uri not in self._sizes:
                with contextlib.suppress(FileNotFoundError):
                    shutil.rmtree(target)
                os.replace(tmp, target)
                self._sizes[uri] = size
                self._order.append(uri)
                self._last_used[uri] = _time.monotonic()
                self._evict_locked()
            else:
                with contextlib.suppress(FileNotFoundError):
                    shutil.rmtree(tmp)
        return target

    def _evict_locked(self) -> None:
        import time as _time

        total = sum(self._sizes.values())
        now = _time.monotonic()
        i = 0
        while total > self.max_total_bytes and i < len(self._order):
            victim = self._order[i]
            # Skip recently-used entries: a running task may be chdir'd
            # into (or importing from) that directory.
            if (now - self._last_used.get(victim, 0.0)
                    < self.min_idle_before_evict_s):
                i += 1
                continue
            self._order.pop(i)
            total -= self._sizes.pop(victim, 0)
            self._last_used.pop(victim, None)
            with contextlib.suppress(FileNotFoundError):
                shutil.rmtree(self._dir_for(victim))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._sizes),
                    "total_bytes": sum(self._sizes.values())}


def tree_signature(path: str) -> str:
    """Cheap change-detection signature of a directory (paths + sizes +
    mtimes): repeated submissions re-zip only when the tree changed
    (the reference re-hashes on every upload_package_if_needed)."""
    sig = hashlib.sha256()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            sig.update(f"{os.path.relpath(full, path)}:{st.st_size}:"
                       f"{st.st_mtime_ns};".encode())
    return sig.hexdigest()


def prepare_for_upload(renv: Optional[Dict[str, Any]],
                       upload: Callable[[str, bytes], None],
                       _cache: Dict[str, Tuple[str, str]]
                       ) -> Optional[Dict[str, Any]]:
    """Rewrite local directories in a runtime_env to content-addressed
    pkg:// URIs, uploading each distinct tree once (driver side —
    reference: upload_package_if_needed). `_cache` maps abspath →
    (tree_signature, uri); an edited tree re-zips and re-uploads."""
    if not renv:
        return renv
    out = dict(renv)

    def to_uri(path: str) -> str:
        if is_uri(path):
            return path
        key = os.path.abspath(str(path))
        sig = tree_signature(key)
        cached = _cache.get(key)
        if cached is not None and cached[0] == sig:
            return cached[1]
        uri, blob = package_directory(key)
        upload(uri, blob)
        _cache[key] = (sig, uri)
        return uri

    wd = out.get("working_dir")
    if wd and not is_uri(str(wd)):
        out["working_dir"] = to_uri(str(wd))
    pm = out.get("py_modules")
    if pm:
        out["py_modules"] = [
            to_uri(str(p)) if os.path.isdir(str(p)) or is_uri(str(p))
            else str(p)
            for p in pm]
    return out


def materialize(renv: Optional[Dict[str, Any]], cache: URICache,
                fetch: Callable[[str], bytes]
                ) -> Optional[Dict[str, Any]]:
    """Resolve pkg://+file:// URIs in a runtime_env to local extracted
    directories (node-daemon side — the reference's per-node agent
    GetOrCreateRuntimeEnv step)."""
    if not renv:
        return renv
    out = dict(renv)
    wd = out.get("working_dir")
    if wd and is_uri(str(wd)):
        out["working_dir"] = cache.get(str(wd), fetch)
    pm = out.get("py_modules")
    if pm:
        out["py_modules"] = [
            cache.get(str(p), fetch) if is_uri(str(p)) else str(p)
            for p in pm]
    return out
