"""Node memory monitor + OOM worker-killing policy.

Capability-equivalent of the reference's memory monitor
(reference: src/ray/common/memory_monitor.h:52 — sample node memory
usage on a timer, compare against a usage threshold) and its
worker-killing policies (reference: src/ray/raylet/
worker_killing_policy.h — RetriableFIFO: kill the task submitted LAST
among retriable ones first, so the oldest work survives and the kill
is recoverable). Killing a worker process surfaces as a retryable
system failure to the owner, which reschedules the task — instead of
the kernel OOM-killer taking the whole node down.

Usage is node-level (total − MemAvailable)/total from /proc/meminfo,
like the reference; an injectable usage_fn supports deterministic
tests and cgroup-scoped deployments.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("ray_tpu")


def proc_meminfo_usage() -> float:
    """Fraction of node memory in use, from /proc/meminfo."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return 1.0 - avail / total


def usage_fn_from_config():
    """The configured usage source: the fault-injection file if set
    (chaos tests), else /proc/meminfo."""
    from .._private.config import config

    path = config.memory_monitor_usage_file
    if not path:
        return proc_meminfo_usage

    def from_file() -> float:
        try:
            with open(path) as f:
                return float(f.read().strip() or 0.0)
        except (OSError, ValueError):
            return 0.0

    return from_file


class MemoryMonitor:
    """Samples memory usage; above the threshold, kills one victim per
    tick (retriable-last-submitted first — RetriableFIFO).

    victims_fn() → [(submit_order_key, retriable, kill_cb, label)].
    kill_cb() must make the kill surface as a retryable system failure
    for retriable victims.
    """

    def __init__(self, victims_fn: Callable[[], List[Tuple]],
                 *, threshold: float,
                 interval_s: float = 0.25,
                 usage_fn: Optional[Callable[[], float]] = None,
                 min_kill_interval_s: float = 1.0):
        self._victims_fn = victims_fn
        self.threshold = threshold
        self.interval_s = interval_s
        self.usage_fn = usage_fn or proc_meminfo_usage
        self.min_kill_interval_s = min_kill_interval_s
        self.kills = 0
        self._last_kill = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="memory-monitor")

    def start(self) -> "MemoryMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — monitor must not die
                logger.exception("memory monitor tick failed")

    def tick(self) -> bool:
        """One sample; returns True if a victim was killed."""
        usage = self.usage_fn()
        if usage < self.threshold:
            return False
        now = time.monotonic()
        if now - self._last_kill < self.min_kill_interval_s:
            return False  # give the previous kill time to free memory
        victim = self._pick_victim(self._victims_fn())
        if victim is None:
            logger.warning(
                "memory usage %.1f%% above threshold %.1f%% but no "
                "killable worker task", usage * 100,
                self.threshold * 100)
            return False
        order, retriable, kill_cb, label = victim
        logger.warning(
            "memory usage %.1f%% ≥ %.1f%%: killing %s task %s to "
            "relieve pressure (it will be retried)" if retriable else
            "memory usage %.1f%% ≥ %.1f%%: killing %s task %s "
            "(NOT retriable — it will fail)",
            usage * 100, self.threshold * 100,
            "retriable" if retriable else "non-retriable", label)
        try:
            kill_cb()
        except Exception:  # noqa: BLE001
            logger.exception("failed to kill %s", label)
            return False
        self.kills += 1
        self._last_kill = now
        return True

    @staticmethod
    def _pick_victim(victims: List[Tuple]) -> Optional[Tuple]:
        """RetriableFIFO (reference worker_killing_policy.h): among
        retriable tasks pick the LAST submitted; only if none are
        retriable, the last-submitted non-retriable one."""
        if not victims:
            return None
        retriable = [v for v in victims if v[1]]
        pool = retriable or victims
        return max(pool, key=lambda v: v[0])
