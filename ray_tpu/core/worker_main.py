"""Worker process entry point.

Capability-equivalent to the reference's default_worker.py + the
CoreWorker task-execution loop (reference:
_private/workers/default_worker.py; CoreWorkerProcess::
RunTaskExecutionLoop → execute_task _raylet.pyx:1644): connect back to
the driver's socket, register, then loop executing pushed tasks. Objects
larger than the inline threshold are written to / read from the shared
C++ shm store; only ids cross the socket.

Also hosts actor instances: `actor_create` instantiates the class in
this process; subsequent `actor_call`s run its methods here, in arrival
order (the per-caller ordering the reference's actor submit queue
guarantees — there is a single caller, the driver).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import socket
import sys
import traceback
from typing import Any, Dict, Optional


def _runtime_env(renv: Optional[Dict[str, Any]]):
    # Lazy import: pulling in ray_tpu.core.runtime_env at module scope
    # would run the full ray_tpu package __init__ (jax and friends) at
    # worker startup and blow the spawn-accept deadline.
    from ray_tpu.core.runtime_env import applied

    return applied(renv)


def _setup(args):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    shm = None
    if args.shm:
        try:
            from ray_tpu._native.shm_store import ShmStore

            shm = ShmStore(args.shm, create=False)
        except Exception:  # noqa: BLE001 — shm optional; fall back inline
            shm = None
    return sock, shm


def _unpack_args(packed_args, packed_kwargs, shm, pinned=None):
    """Resolve wire args. With `pinned` (a list), shm-resident args
    deserialize ZERO-COPY — numpy values are read-only views straight
    into the arena, no GiB-scale copy on the consume path — and their
    keys are appended for the caller to shm.release() once the task
    AND its result packing are done (the pin keeps eviction off the
    span while user code can still see it). Without `pinned`, buffers
    are copied out and the pin drops immediately — actor messages use
    this, since an actor may legitimately stash an arg in its state
    long past the call."""
    from ray_tpu.core import serialization
    from ray_tpu.core.worker_proc import SerArg, ShmArg

    def resolve(v):
        if isinstance(v, (ShmArg, SerArg)):
            if isinstance(v, ShmArg):
                if shm is None:
                    raise RuntimeError("shm arg but no shm store attached")
                view = shm.get(v.key, pin=True)
                if view is None:
                    raise KeyError(v.key.hex())
                if pinned is not None:
                    pinned.append(v.key)
                    data = serialization.SerializedObject.from_bytes(
                        view, copy=False)
                    value = serialization.deserialize(data)
                else:
                    try:
                        data = serialization.SerializedObject.from_bytes(
                            view)
                        value = serialization.deserialize(data)
                    finally:
                        shm.release(v.key)
            else:
                value = serialization.deserialize(
                    serialization.SerializedObject.from_bytes(v.data))
            if v.is_error:
                raise value
            return value
        return v

    args = tuple(resolve(a) for a in packed_args)
    kwargs = {k: resolve(v) for k, v in packed_kwargs.items()}
    return args, kwargs


def _pack_value(value, shm, inline_max: int, key: bytes):
    """serialize; big payloads go to shm under `key` (the return
    ObjectID — so the driver's store/lineage see the same id), small
    payloads ship inline. Returns a wire tuple."""
    from ray_tpu.core import serialization

    data = serialization.serialize(value)
    blob = data.to_bytes()
    if shm is not None and len(blob) > inline_max:
        try:
            shm.put(key, blob)
            return ("shm", key)
        except Exception as e:  # noqa: BLE001 — store full/dup: ship inline
            # Re-executed task (lineage reconstruction): the arena may
            # already hold this key from the first run — the put fails
            # duplicate, but the shm reference is still valid.
            try:
                if shm.contains(key):
                    return ("shm", key)
            except Exception:  # noqa: BLE001 — fall through to inline
                pass
            # Inlining a large payload silently turns the transfer
            # plane into a dispatch-socket push — loud breadcrumb.
            print(f"worker: shm put of {len(blob)} B result failed "
                  f"({type(e).__name__}: {e}); shipping inline",
                  file=sys.stderr, flush=True)
    return ("ser", blob)


def _pack_error(exc: BaseException):
    from ray_tpu.core import serialization

    try:
        data = serialization.serialize(exc)
    except Exception:  # noqa: BLE001 — unpicklable exception
        data = serialization.serialize(
            RuntimeError("".join(traceback.format_exception(exc))))
    return ("ser", data.to_bytes())


# The worker's shm attachment, for components that need the store
# outside the task path (compiled-DAG channels resolve through here —
# a worker process has no global Runtime).
WORKER_SHM = None


def main() -> None:
    global WORKER_SHM
    # Cross-process lock tracing: arm BEFORE any lock is created so the
    # worker's order graph is complete. No-op unless
    # RAY_TPU_LOCKTRACE_DIR is set (see devtools/locktrace.py).
    from ray_tpu.devtools.locktrace import maybe_install_from_env

    maybe_install_from_env()
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    ap.add_argument("--worker-id", type=int, required=True)
    ap.add_argument("--shm", default=None)
    ap.add_argument("--inline-max", type=int, default=100 * 1024)
    args = ap.parse_args()

    from ray_tpu.core.worker_proc import recv_msg, send_msg

    sock, shm = _setup(args)
    WORKER_SHM = shm
    # Run as `python -m ...` this module is `__main__`; consumers import
    # the canonical name — publish the attachment there too.
    import ray_tpu.core.worker_main as _canonical

    _canonical.WORKER_SHM = shm
    send_msg(sock, {"type": "hello", "worker_id": args.worker_id,
                    "pid": os.getpid()})

    # Worker-side tracing: there is no Runtime in this process, so
    # finished spans buffer here and piggyback on result replies — the
    # driver merges them into its event buffer, giving `ray_tpu
    # timeline` a multi-process trace.
    from ray_tpu.util import tracing as _tracing

    _tracing.set_process_label(str(os.getpid()))
    _span_buf: list = []
    _tracing.setup_tracing(_span_buf.append)

    # Always-on low-duty-cycle profiler: retained snapshots under the
    # node's shared contprof ring (the daemon exports its resolved dir
    # via RAY_TPU_CONTPROF_DIR) so a postmortem can ask what this
    # worker was doing minutes before it died.
    try:
        from ray_tpu.observability.continuous import (
            start_continuous_profiler)

        start_continuous_profiler("worker")
    except Exception:  # noqa: BLE001 — observability must not stop boot
        pass

    def _drain_spans():
        out = list(_span_buf)
        _span_buf.clear()
        return out

    fn_cache: Dict[bytes, Any] = {}
    actors: Dict[bytes, Any] = {}

    def get_fn(msg):
        fid = msg["fid"]
        if fid not in fn_cache:
            import cloudpickle

            fn_cache[fid] = cloudpickle.loads(msg["fn"])
        return fn_cache[fid]

    # Strictly read-one/reply-one over the dedicated daemon socket:
    # one task is in flight per worker at a time. The native hand-off
    # plane (src/node_dispatch.cc) relies on this — replies carry no
    # connection tag because the loop can attribute each reply to the
    # single driver connection whose task the worker is running. Any
    # future pipelining here would need a conn-id echoed in replies.
    while True:
        msg = recv_msg(sock)
        mtype = msg.get("type")
        if mtype == "shutdown":
            return
        if mtype == "ping":
            send_msg(sock, {"type": "pong", "worker_id": args.worker_id})
            continue
        if mtype == "gen_ack":
            # Late consumption credit from a finished stream — ignore.
            continue
        if mtype == "profile":
            # On-demand stack capture for the cluster profiler: sample
            # this worker's threads for the requested duration and
            # reply terminally ("profile_result" ends the request like
            # a "result" frame does).
            from ray_tpu.observability.stack_sampler import sample_stacks

            try:
                samples = sample_stacks(
                    min(float(msg.get("duration_s") or 2.0), 60.0),
                    float(msg.get("interval_s") or 0.01))
                send_msg(sock, {"type": "profile_result",
                                "pid": os.getpid(), "samples": samples})
            except Exception as e:  # noqa: BLE001 — report, stay alive
                send_msg(sock, {"type": "profile_result",
                                "pid": os.getpid(), "samples": {},
                                "error": f"{type(e).__name__}: {e}"})
            continue

        task_id = msg.get("task_id")
        # Arena spans pinned for this message's zero-copy args —
        # released only after the result (which may serialize views of
        # those spans) is on the wire.
        pinned: list = []

        def _release_pins(pinned=pinned, shm=shm):
            while pinned:
                with contextlib.suppress(Exception):
                    shm.release(pinned.pop())

        # Re-enter the driver's trace: the outer span covers unpack +
        # user code in THIS process, parented to the driver's execute
        # span; an inner span isolates the user call itself.
        traced = msg.get("trace_id") is not None
        trace_cm = contextlib.ExitStack()
        if traced:
            trace_cm.enter_context(_tracing.trace_context(
                msg["trace_id"], msg.get("parent_span_id")))
            trace_cm.enter_context(_tracing.span(
                f"worker:{mtype}", "worker_execute",
                task_id=task_id.hex() if task_id is not None else None))

        def _run_span(label):
            return (_tracing.span(f"run:{label}", "worker_run")
                    if traced else contextlib.nullcontext())

        try:
            if mtype == "task":
                fn = get_fn(msg)
                call_args, call_kwargs = _unpack_args(
                    msg["args"], msg["kwargs"], shm, pinned)
                with _runtime_env(msg.get("runtime_env")), \
                        _run_span(getattr(fn, "__qualname__", "task")):
                    result = fn(*call_args, **call_kwargs)
            elif mtype == "actor_create":
                import cloudpickle

                cls = cloudpickle.loads(msg["cls"])
                call_args, call_kwargs = _unpack_args(
                    msg["args"], msg["kwargs"], shm)
                with _runtime_env(msg.get("runtime_env")), \
                        _run_span(getattr(cls, "__qualname__", "actor")):
                    actors[msg["actor_id"]] = cls(*call_args, **call_kwargs)
                result = None
            elif mtype == "actor_call":
                inst = actors.get(msg["actor_id"])
                if inst is None:
                    raise RuntimeError(
                        f"actor {msg['actor_id'].hex()} not in this worker")
                if msg["method"] == "__ray_tpu_apply__":
                    # Injected-callable execution (compiled-DAG pinned
                    # loops; mirrors ActorState._bind_method).
                    def method(fn, *a, _inst=inst, **kw):
                        return fn(_inst, *a, **kw)
                else:
                    method = getattr(inst, msg["method"])
                call_args, call_kwargs = _unpack_args(
                    msg["args"], msg["kwargs"], shm)
                with _runtime_env(msg.get("runtime_env")), \
                        _run_span(msg["method"]):
                    result = method(*call_args, **call_kwargs)
            elif mtype == "actor_kill":
                actors.pop(msg["actor_id"], None)
                result = None
            else:
                raise RuntimeError(f"unknown message type {mtype!r}")
            import inspect

            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.run(result)
        except BaseException as e:  # noqa: BLE001 — user code may raise anything
            trace_cm.close()
            send_msg(sock, {"type": "result", "task_id": task_id,
                            "error": _pack_error(e),
                            "spans": _drain_spans()})
            _release_pins()
            continue
        trace_cm.close()

        streaming = msg.get("streaming", False)
        if streaming and hasattr(result, "__next__"):
            from ray_tpu.core.ids import ObjectID

            # Credit-based backpressure (reference: GeneratorWaiter,
            # core_worker.h): pause after `bp` unacknowledged items;
            # the driver grants a credit whenever the consumer takes
            # one. 0 = unbounded.
            bp = msg.get("backpressure", 0)
            inflight = 0
            i = 0
            try:
                for item in result:
                    key = ObjectID.for_return(task_id, i).binary()
                    send_msg(sock, {
                        "type": "gen_item", "task_id": task_id, "index": i,
                        "payload": _pack_value(item, shm, args.inline_max,
                                               key)})
                    i += 1
                    inflight += 1
                    while bp and inflight >= bp:
                        note = recv_msg(sock)
                        ntype = note.get("type")
                        if ntype == "gen_ack":
                            inflight -= note.get("n", 1)
                        elif ntype == "shutdown":
                            return
                        # anything else mid-stream is unexpected; skip
                send_msg(sock, {"type": "result", "task_id": task_id,
                                "error": None, "returns": [],
                                "gen_count": i, "spans": _drain_spans()})
            except BaseException as e:  # noqa: BLE001
                send_msg(sock, {"type": "result", "task_id": task_id,
                                "error": _pack_error(e), "gen_count": i,
                                "spans": _drain_spans()})
            finally:
                _release_pins()
            continue

        n = msg.get("num_returns", 1)
        return_ids = msg.get("return_ids", [])
        if n == 0 or task_id is None:
            returns = []
        elif n == 1:
            returns = [_pack_value(result, shm, args.inline_max,
                                   return_ids[0])]
        else:
            values = tuple(result)
            if len(values) != n:
                send_msg(sock, {
                    "type": "result", "task_id": task_id,
                    "error": _pack_error(ValueError(
                        f"declared num_returns={n} but returned "
                        f"{len(values)} values")),
                    "spans": _drain_spans()})
                _release_pins()
                continue
            returns = [_pack_value(v, shm, args.inline_max, return_ids[i])
                       for i, v in enumerate(values)]
        send_msg(sock, {"type": "result", "task_id": task_id,
                        "error": None, "returns": returns,
                        "spans": _drain_spans()})
        _release_pins()


if __name__ == "__main__":
    main()
