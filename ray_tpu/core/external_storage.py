"""Pluggable external storage — one plane for spilling AND checkpoints.

Capability-equivalent of the reference's external-storage stack
(reference: python/ray/_private/external_storage.py:72 ExternalStorage
ABC, :246 FileSystemStorage, :445 ExternalStorageSmartOpenImpl — the
S3-style remote driver behind object spilling; and
train/_internal/storage.py:98-110 — pyarrow.fs URI resolution behind
checkpoint persistence). TPU-native twist: the remote-shaped backend
rides the control plane's KV (`cp://host:port/prefix`), so spilled
objects and checkpoints survive the death of the host that wrote them
without any cloud dependency — and a real cloud driver is one subclass
away (same blob/dir interface).

URL schemes:
  file:///abs/dir      — local filesystem (also plain paths, no scheme)
  cp://host:port/pre   — control-plane KV ("remote": URL-addressed,
                         byte-stream up/download, no shared local paths)
  mem://bucket/pre     — in-process dict (unit tests)
"""

from __future__ import annotations

import io
import os
import shutil
import tarfile
import threading
from typing import Dict, List, Tuple


class ExternalStorage:
    """Blob + directory storage addressed by URL. put/upload return the
    full URL; get/download/delete take URLs produced by ANY process
    (restore-on-survivor needs no shared local state)."""

    # -- blobs (spilled objects) ------------------------------------------
    def put_blob(self, key: str, data: bytes) -> str:
        raise NotImplementedError

    def get_blob(self, url: str) -> bytes:
        raise NotImplementedError

    def delete_blob(self, url: str) -> None:
        raise NotImplementedError

    # -- directories (checkpoints) ----------------------------------------
    def upload_dir(self, local_dir: str, key: str) -> str:
        raise NotImplementedError

    def download_dir(self, url: str, local_dir: str) -> None:
        raise NotImplementedError

    def delete_dir(self, url: str) -> None:
        raise NotImplementedError

    def exists(self, url: str) -> bool:
        raise NotImplementedError


def _tar_dir(local_dir: str) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        tf.add(local_dir, arcname=".")
    return buf.getvalue()


def _untar_dir(data: bytes, local_dir: str) -> None:
    os.makedirs(local_dir, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r") as tf:
        tf.extractall(local_dir, filter="data")


class FileSystemStorage(ExternalStorage):
    """reference: _private/external_storage.py:246 FileSystemStorage."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, url: str) -> str:
        if url.startswith("file://"):
            return url[len("file://"):]
        return url

    def put_blob(self, key: str, data: bytes) -> str:
        path = os.path.join(self.root, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: no half-written blobs
        return "file://" + path

    def get_blob(self, url: str) -> bytes:
        with open(self._path(url), "rb") as f:
            return f.read()

    def delete_blob(self, url: str) -> None:
        try:
            os.remove(self._path(url))
        except FileNotFoundError:
            pass

    def upload_dir(self, local_dir: str, key: str) -> str:
        dest = os.path.join(self.root, key)
        if os.path.abspath(local_dir) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(local_dir, dest)
        return "file://" + dest

    def download_dir(self, url: str, local_dir: str) -> None:
        src = self._path(url)
        if os.path.abspath(src) != os.path.abspath(local_dir):
            shutil.copytree(src, local_dir, dirs_exist_ok=True)

    def delete_dir(self, url: str) -> None:
        shutil.rmtree(self._path(url), ignore_errors=True)

    def exists(self, url: str) -> bool:
        return os.path.exists(self._path(url))


class ControlPlaneStorage(ExternalStorage):
    """Remote-shaped storage on the control plane's KV: URL-addressed,
    explicit byte up/download, nothing local shared — what spilled
    objects and checkpoints need to outlive their writer's host
    (reference capability: ExternalStorageSmartOpenImpl / S3)."""

    KV_PREFIX = "extstore/"

    def __init__(self, address: str):
        self.address = address  # host:port

    # One client per (address, thread-agnostic) — the ControlClient is
    # internally thread-safe (reader thread demuxes replies).
    _clients: Dict[str, object] = {}
    _clients_lock = threading.Lock()

    def _client(self):
        with ControlPlaneStorage._clients_lock:
            cli = ControlPlaneStorage._clients.get(self.address)
            if cli is None:
                from .._native.control_client import ControlClient

                host, _, port = self.address.partition(":")
                cli = ControlClient(int(port), host=host)
                ControlPlaneStorage._clients[self.address] = cli
            return cli

    def _kv_key(self, url_or_key: str) -> str:
        if url_or_key.startswith("cp://"):
            rest = url_or_key[len("cp://"):]
            _, _, key = rest.partition("/")
        else:
            key = url_or_key
        return self.KV_PREFIX + key

    def _url(self, key: str) -> str:
        return f"cp://{self.address}/{key}"

    def put_blob(self, key: str, data: bytes) -> str:
        self._client().kv_put(self._kv_key(key), data, overwrite=True)
        return self._url(key)

    def get_blob(self, url: str) -> bytes:
        return self._client().kv_get(self._kv_key(url))

    def delete_blob(self, url: str) -> None:
        try:
            self._client().kv_del(self._kv_key(url))
        except Exception:  # noqa: BLE001 — delete is best-effort
            pass

    def upload_dir(self, local_dir: str, key: str) -> str:
        self._client().kv_put(self._kv_key(key + ".tar"),
                              _tar_dir(local_dir), overwrite=True)
        return self._url(key)

    def download_dir(self, url: str, local_dir: str) -> None:
        _untar_dir(self._client().kv_get(self._kv_key(url) + ".tar"),
                   local_dir)

    def delete_dir(self, url: str) -> None:
        try:
            self._client().kv_del(self._kv_key(url) + ".tar")
        except Exception:  # noqa: BLE001
            pass

    def exists(self, url: str) -> bool:
        cli = self._client()
        k = self._kv_key(url)
        return bool(cli.kv_exists(k) or cli.kv_exists(k + ".tar"))


class InMemoryStorage(ExternalStorage):
    """Process-local fake with remote semantics (unit tests)."""

    _buckets: Dict[str, Dict[str, bytes]] = {}
    _lock = threading.Lock()

    def __init__(self, bucket: str):
        self.bucket = bucket
        with InMemoryStorage._lock:
            InMemoryStorage._buckets.setdefault(bucket, {})

    def _store(self) -> Dict[str, bytes]:
        return InMemoryStorage._buckets[self.bucket]

    def _key(self, url_or_key: str) -> str:
        if url_or_key.startswith("mem://"):
            rest = url_or_key[len("mem://"):]
            _, _, key = rest.partition("/")
            return key
        return url_or_key

    def _url(self, key: str) -> str:
        return f"mem://{self.bucket}/{key}"

    def put_blob(self, key: str, data: bytes) -> str:
        with InMemoryStorage._lock:
            self._store()[self._key(key)] = bytes(data)
        return self._url(key)

    def get_blob(self, url: str) -> bytes:
        with InMemoryStorage._lock:
            return self._store()[self._key(url)]

    def delete_blob(self, url: str) -> None:
        with InMemoryStorage._lock:
            self._store().pop(self._key(url), None)

    def upload_dir(self, local_dir: str, key: str) -> str:
        with InMemoryStorage._lock:
            self._store()[self._key(key) + ".tar"] = _tar_dir(local_dir)
        return self._url(key)

    def download_dir(self, url: str, local_dir: str) -> None:
        with InMemoryStorage._lock:
            data = self._store()[self._key(url) + ".tar"]
        _untar_dir(data, local_dir)

    def delete_dir(self, url: str) -> None:
        with InMemoryStorage._lock:
            self._store().pop(self._key(url) + ".tar", None)

    def exists(self, url: str) -> bool:
        with InMemoryStorage._lock:
            k = self._key(url)
            return k in self._store() or (k + ".tar") in self._store()


def is_url(path: str) -> bool:
    return isinstance(path, str) and "://" in path


def storage_for_url(url: str) -> ExternalStorage:
    """Resolve the storage backend from any URL this plane produced.
    Works in ANY process — restore needs only the URL."""
    if url.startswith("file://") or "://" not in url:
        path = url[len("file://"):] if url.startswith("file://") else url
        return FileSystemStorage(os.path.dirname(path) or "/")
    if url.startswith("cp://"):
        rest = url[len("cp://"):]
        address, _, _ = rest.partition("/")
        return ControlPlaneStorage(address)
    if url.startswith("mem://"):
        rest = url[len("mem://"):]
        bucket, _, _ = rest.partition("/")
        return InMemoryStorage(bucket)
    raise ValueError(f"unknown storage scheme in {url!r}")
