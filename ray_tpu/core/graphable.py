"""``@graphable`` — declares a function safe to capture as a task graph.

A graphable function is one whose submission structure (the ``.remote()``
calls and dag binds it performs, and the ref dataflow between them) is
meant to be captured once and replayed as pre-encoded frames by the
compiled-dag / dispatch-replay plane (ROADMAP item 3). The marker is a
declaration of intent, not a behavior change: decorated callables run
exactly as before. What it buys:

- ``raylint --xp`` treats the function as a graph-capture entry point:
  the ``effects``/``graphcap`` analyses verify that everything reachable
  from it is pure enough to replay (no wall-clock/randomness reads, no
  global or ``self`` mutation, no I/O, no control flow on runtime
  values) and extract its static task graph (``--graph-out``).
- the static↔dynamic verifier (tests/test_graph_capture.py) asserts the
  extracted graph matches what one real execution actually submits.

Use it on the per-iteration driver of a steady-state pipeline (an RLHF
training step, a serve app builder) — not on setup/teardown code, whose
effects are the point.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["graphable", "is_graphable"]

_MARK = "__ray_tpu_graphable__"


def graphable(fn: Optional[Callable] = None, *,
              name: Optional[str] = None):
    """Mark ``fn`` as a graph-capture entry point.

    Supports both ``@graphable`` and ``@graphable(name="step")``. The
    optional ``name`` overrides the entry label in graph artifacts.
    """

    def mark(f: Callable) -> Callable:
        setattr(f, _MARK, name or getattr(f, "__qualname__", f.__name__))
        return f

    if fn is not None:
        return mark(fn)
    return mark


def is_graphable(obj: Any) -> bool:
    return getattr(obj, _MARK, None) is not None
