"""conda / container runtime-env plugins (spawn-level isolation).

Capability-equivalent to the reference's conda and container plugins
(reference: python/ray/_private/runtime_env/conda.py — env creation +
worker launched via the env's own interpreter; container.py — worker
command wrapped in `podman run` with the session dir mounted). Unlike
env_vars/working_dir/py_modules/pip (applied around the invocation,
runtime_env.py), these two change THE WORKER PROCESS ITSELF, so they act
at spawn time: the worker command line is wrapped.

This image ships neither conda nor podman/docker and blocks installs, so
the integration is GATED: shape validation and command assembly are pure
functions (tested), the binary probe decides between the real spawn
wrap and a documented refusal that points at the supported alternative
(the offline pip wheelhouse plugin, runtime_env_pip.py, covers
dependency isolation without either binary).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "normalize_conda",
    "normalize_container",
    "conda_binary",
    "container_runtime",
    "wrap_cmd_conda",
    "wrap_cmd_container",
    "materialize_conda",
    "conda_spec_file_content",
    "conda_site_packages",
    "RuntimeEnvUnsupportedError",
]


class RuntimeEnvUnsupportedError(RuntimeError):
    """A runtime_env plugin's host dependency is missing."""


# ---------------------------------------------------------------------------
# Normalization (pure; mirrors the reference's accepted shapes)
# ---------------------------------------------------------------------------

def normalize_conda(spec: Union[str, Dict[str, Any], List[str]]
                    ) -> Dict[str, Any]:
    """Accepted shapes (reference: conda.py get_conda_dict):
    - "env-name" or "environment.yml" path (str)
    - {"dependencies": [...]} environment dict
    - ["numpy", "pandas"] dependency list
    Returns a canonical {"kind": "name"|"yaml"|"spec", ...} dict."""
    if isinstance(spec, str):
        if spec.endswith((".yml", ".yaml")):
            if not os.path.isfile(spec):
                raise ValueError(f"conda yaml not found: {spec}")
            with open(spec) as f:
                content = f.read()
            return {"kind": "yaml", "content": content,
                    "path": os.path.abspath(spec)}
        return {"kind": "name", "name": spec}
    if isinstance(spec, (list, tuple)):
        deps = [str(d) for d in spec]
        if not deps:
            raise ValueError("conda dependency list is empty")
        return {"kind": "spec", "env": {"dependencies": deps}}
    if isinstance(spec, dict):
        if "dependencies" not in spec:
            raise ValueError(
                "conda environment dict needs a 'dependencies' key")
        return {"kind": "spec", "env": dict(spec)}
    raise TypeError(f"runtime_env['conda'] must be a str, list or dict, "
                    f"got {type(spec).__name__}")


def normalize_container(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Accepted shape (reference: container.py — {"image": ...,
    "worker_path"?, "run_options"?: [...]}.)"""
    if not isinstance(spec, dict):
        raise TypeError("runtime_env['container'] must be a dict")
    if not spec.get("image"):
        raise ValueError("runtime_env['container'] needs an 'image'")
    out = {"image": str(spec["image"])}
    ro = spec.get("run_options", [])
    if not isinstance(ro, (list, tuple)) or not all(
            isinstance(o, str) for o in ro):
        raise ValueError("container.run_options must be a list of strings")
    out["run_options"] = [str(o) for o in ro]
    if spec.get("worker_path"):
        out["worker_path"] = str(spec["worker_path"])
    unknown = set(spec) - {"image", "run_options", "worker_path"}
    if unknown:
        raise ValueError(f"unsupported container keys {sorted(unknown)}")
    return out


# ---------------------------------------------------------------------------
# Host probes
# ---------------------------------------------------------------------------

def conda_binary() -> Optional[str]:
    for name in ("conda", "mamba", "micromamba"):
        p = shutil.which(name)
        if p:
            return p
    return None


def container_runtime() -> Optional[str]:
    for name in ("podman", "docker"):
        p = shutil.which(name)
        if p:
            return p
    return None


def _require(binary: Optional[str], what: str, alternative: str) -> str:
    if binary is None:
        raise RuntimeEnvUnsupportedError(
            f"runtime_env[{what!r}] needs a {what} runtime on the host "
            f"and none was found. {alternative}")
    return binary


_CONDA_ALT = (
    "This image has no conda and blocks installs; for dependency "
    "isolation use the offline pip plugin instead — "
    "runtime_env={'pip': [...]} resolves against a local wheelhouse "
    "(RAY_TPU_WHEELHOUSE) with content-addressed caching "
    "(core/runtime_env_pip.py)."
)
_CONTAINER_ALT = (
    "Install podman or docker on every node, or ship code with "
    "working_dir/py_modules packages and dependencies via the offline "
    "pip plugin."
)


# ---------------------------------------------------------------------------
# Spawn-command wrapping (pure given a binary path)
# ---------------------------------------------------------------------------

def wrap_cmd_conda(cmd: List[str], conda: Dict[str, Any],
                   *, binary: Optional[str] = None,
                   cache_root: Optional[str] = None) -> List[str]:
    """Worker command -> `conda run` inside the env (reference:
    conda.py — the worker's py_executable becomes the env python)."""
    binary = binary or _require(conda_binary(), "conda", _CONDA_ALT)
    if conda["kind"] == "name":
        return [binary, "run", "-n", conda["name"], "--no-capture-output",
                *cmd]
    prefix = materialize_conda(conda, binary=binary, cache_root=cache_root)
    return [binary, "run", "-p", prefix, "--no-capture-output", *cmd]


def wrap_cmd_container(cmd: List[str], container: Dict[str, Any],
                       *, binary: Optional[str] = None,
                       session_dir: Optional[str] = None) -> List[str]:
    """Worker command -> `podman run` with the session dir and shm
    plane mounted and host networking (the worker must reach the
    daemon's unix socket + shm arena) — reference: container.py
    get_container_driver command assembly."""
    binary = binary or _require(container_runtime(), "container",
                                _CONTAINER_ALT)
    wrapped = [binary, "run", "--rm", "--network", "host",
               "-v", "/dev/shm:/dev/shm"]
    if session_dir:
        wrapped += ["-v", f"{session_dir}:{session_dir}"]
    cwd = os.getcwd()
    wrapped += ["-v", f"{cwd}:{cwd}", "-w", cwd]
    wrapped += list(container.get("run_options", []))
    wrapped.append(container["image"])
    wrapped += list(cmd)
    return wrapped


# ---------------------------------------------------------------------------
# Conda env materialization (content-addressed, flock'd like the pip
# plugin's wheelhouse cache)
# ---------------------------------------------------------------------------

def conda_spec_file_content(conda: Dict[str, Any]) -> str:
    """Environment-file text for `conda env create -f`. A 'yaml' kind
    passes through verbatim; a 'spec' kind emits its env dict as JSON —
    a strict YAML subset conda accepts — preserving nested entries
    (channels, the {"pip": [...]} dependency dict) exactly."""
    if conda["kind"] == "yaml":
        return conda["content"]
    if conda["kind"] == "spec":
        return json.dumps(conda["env"], indent=2)
    raise ValueError(f"no spec file for conda kind {conda['kind']!r}")


def conda_site_packages(prefix: str) -> Optional[str]:
    """The env's site-packages dir, for in-process path application
    (same interpreter-stays caveat as the pip plugin)."""
    import glob as _glob

    hits = sorted(_glob.glob(
        os.path.join(prefix, "lib", "python*", "site-packages")))
    return hits[-1] if hits else None

def _conda_cache_root() -> str:
    return os.environ.get(
        "RAY_TPU_CONDA_CACHE",
        os.path.join(os.path.expanduser("~"), ".ray_tpu", "conda_envs"))


def materialize_conda(conda: Dict[str, Any], *,
                      binary: Optional[str] = None,
                      cache_root: Optional[str] = None) -> str:
    """Create (once per content hash per host) and return the env
    prefix. Named envs are assumed to exist already."""
    binary = binary or _require(conda_binary(), "conda", _CONDA_ALT)
    if conda["kind"] == "name":
        raise ValueError("named conda envs are used in place, not created")
    content = conda.get("content") or json.dumps(conda["env"],
                                                 sort_keys=True)
    h = hashlib.sha256(content.encode()).hexdigest()[:16]
    root = cache_root or _conda_cache_root()
    prefix = os.path.join(root, h)
    ready = os.path.join(prefix, ".ray_tpu_ready")
    if os.path.exists(ready):
        return prefix
    os.makedirs(root, exist_ok=True)
    import fcntl

    lock_path = os.path.join(root, f".{h}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(ready):
            return prefix
        spec_path = os.path.join(root, f"{h}.yml")
        with open(spec_path, "w") as f:
            f.write(conda_spec_file_content(conda))
        # Always `env create -f`: a flat `conda create <deps>` would drop
        # non-string dependency entries — the nested {"pip": [...]} dict
        # and channels that validate() tells users to put here.
        args = [binary, "env", "create", "-p", prefix, "-f", spec_path]
        try:
            subprocess.run(args, check=True, capture_output=True,
                           text=True, timeout=1800)
        except subprocess.CalledProcessError as e:
            shutil.rmtree(prefix, ignore_errors=True)
            raise RuntimeEnvUnsupportedError(
                f"conda env creation failed: {e.stderr[-2000:]}") from e
        except subprocess.TimeoutExpired as e:
            # A half-built prefix with no .ready marker would poison the
            # cache slot forever (conda refuses an existing prefix).
            shutil.rmtree(prefix, ignore_errors=True)
            raise RuntimeEnvUnsupportedError(
                "conda env creation timed out after 1800s") from e
        with open(ready, "w") as f:
            f.write(h)
    return prefix
