"""Serialization layer.

Capability-equivalent to the reference's SerializationContext
(reference: python/ray/_private/serialization.py) — cloudpickle with
out-of-band buffer support so large numpy/jax arrays round-trip without an
extra copy, and ObjectRef capture during serialization so that refs pickled
inside arguments are tracked for distributed refcounting (borrowing).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle
import numpy as np


class SerializedObject:
    """A serialized value: a pickle stream plus raw out-of-band buffers.

    Buffers may be zero-copy memoryviews of the CALLER's memory (numpy
    arrays etc.) — consumers must either copy them out within the
    originating call (shm/socket/spill writes do) or call
    `ensure_owned()` before retaining the object (the in-process memory
    store does), otherwise a later caller-side mutation would corrupt
    the stored value.
    """

    __slots__ = ("payload", "buffers", "contained_refs")

    def __init__(self, payload: bytes, buffers: List[bytes],
                 contained_refs: List[Any]):
        self.payload = payload
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        return len(self.payload) + sum(len(b) for b in self.buffers)

    def ensure_owned(self) -> "SerializedObject":
        """Materialize borrowed buffer views into owned bytes
        (idempotent; one copy per borrowed buffer)."""
        self.buffers = [b if isinstance(b, bytes) else bytes(b)
                        for b in self.buffers]
        return self

    def frames(self) -> List[Any]:
        """The flat-frame parts (same layout as to_bytes) WITHOUT
        joining — lets writers copy straight into their destination
        (shm arena, socket) with a single memcpy per part."""
        parts: List[Any] = [
            len(self.buffers).to_bytes(4, "little"),
            len(self.payload).to_bytes(8, "little"),
            self.payload,
        ]
        for b in self.buffers:
            parts.append(len(b).to_bytes(8, "little"))
            parts.append(b)
        return parts

    def to_bytes(self) -> bytes:
        """Flatten to a single contiguous frame (for spill files and
        socket sends). Layout: [4B nbuf][8B len payload][payload]
        [8B len buf0][buf0]... (join copies each part exactly once —
        memoryview parts are buffer-protocol inputs, not pre-copied).
        """
        return b"".join(self.frames())

    @classmethod
    def from_bytes(cls, data: memoryview | bytes, *,
                   copy: bool = True) -> "SerializedObject":
        """Parse the flat frame. copy=False keeps the raw buffers as
        read-only views of `data` — zero-copy, so a GiB-scale object
        deserializes without faulting in a second copy — but the result
        (and values deserialized from it) is only valid while the
        backing memory is; callers own that lifetime (the worker pins
        the shm span for the duration of the task)."""
        mv = memoryview(data)
        nbuf = int.from_bytes(mv[:4], "little")
        off = 4
        plen = int.from_bytes(mv[off:off + 8], "little")
        off += 8
        payload = bytes(mv[off:off + plen])
        off += plen
        bufs = []
        for _ in range(nbuf):
            blen = int.from_bytes(mv[off:off + 8], "little")
            off += 8
            if copy:
                bufs.append(bytes(mv[off:off + blen]))
            else:
                bufs.append(mv[off:off + blen].toreadonly())
            off += blen
        return cls(payload, bufs, [])


class SerializationContext:
    """Pickle-5 out-of-band serializer with ObjectRef tracking."""

    def __init__(self):
        self._local = threading.local()

    # -- ObjectRef capture ------------------------------------------------
    def _note_ref(self, ref):
        refs = getattr(self._local, "captured_refs", None)
        if refs is not None:
            refs.append(ref)

    def serialize(self, value: Any) -> SerializedObject:
        self._local.captured_refs = []
        buffers: List[pickle.PickleBuffer] = []

        def buffer_callback(buf: pickle.PickleBuffer) -> bool:
            buffers.append(buf)
            return False  # out-of-band

        try:
            payload = cloudpickle.dumps(
                value, protocol=5, buffer_callback=buffer_callback
            )
            # Zero-copy: raw views of the value's own buffers. Retainers
            # call ensure_owned(); immediate writers (shm/socket/spill)
            # copy exactly once, into their destination.
            raw = [b.raw() for b in buffers]
            return SerializedObject(payload, raw, list(self._local.captured_refs))
        finally:
            self._local.captured_refs = None

    def deserialize(self, s: SerializedObject) -> Any:
        return pickle.loads(s.payload, buffers=[memoryview(b) for b in s.buffers])


_context: Optional[SerializationContext] = None


def get_context() -> SerializationContext:
    global _context
    if _context is None:
        _context = SerializationContext()
    return _context


def serialize(value: Any) -> SerializedObject:
    return get_context().serialize(value)


def deserialize(s: SerializedObject) -> Any:
    return get_context().deserialize(s)
