"""@remote for functions.

Capability-equivalent to the reference's RemoteFunction
(reference: python/ray/remote_function.py:40 — `_remote` :262 routes into
core_worker.submit_task): decorator surface, `.remote(...)`, `.options(...)`
override chaining, and `.bind(...)` for DAG construction.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

from .runtime import global_runtime
from .task import validate_options


class RemoteFunction:
    def __init__(self, func: Callable, opts: Dict[str, Any]):
        self._func = func
        self._opts = validate_options(dict(opts), is_actor=False)
        self._descriptor = None
        self._descriptor_runtime = None  # invalidate across shutdown/init
        functools.update_wrapper(self, func)

    def _get_descriptor(self):
        rt = global_runtime()
        if self._descriptor is None or self._descriptor_runtime is not rt:
            self._descriptor = rt.function_manager.register(self._func)
            self._descriptor_runtime = rt
        return self._descriptor

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._func.__qualname__!r} cannot be called "
            "directly. Use .remote()."
        )

    def remote(self, *args, **kwargs):
        from ..client import get_client

        c = get_client()
        if c is not None:
            return c.call_function(self._func, args, kwargs, self._opts)
        return global_runtime().submit_task(
            self._func, self._get_descriptor(), args, kwargs, self._opts)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        rf = RemoteFunction(self._func, merged)
        rf._descriptor = self._descriptor
        rf._descriptor_runtime = self._descriptor_runtime
        return rf

    def bind(self, *args, **kwargs):
        """DAG-node construction (reference: python/ray/dag/dag_node.py)."""
        from ..dag.node import FunctionNode
        return FunctionNode(self, args, kwargs)

    def __getstate__(self):
        # The descriptor cache pins the live Runtime (locks, threads) —
        # never ship it; deserialized copies re-register lazily.
        state = dict(self.__dict__)
        state["_descriptor"] = None
        state["_descriptor_runtime"] = None
        return state

    @property
    def underlying_function(self) -> Callable:
        return self._func
