"""Worker processes — the out-of-process execution plane.

Capability-equivalent to the reference's raylet WorkerPool + direct task
push (reference: src/ray/raylet/worker_pool.h:156 — spawn/cache language
workers, exec'd from a command template; src/ray/core_worker/transport/
direct_task_transport.h — lease a worker, PushTask over RPC, reuse while
same-shape tasks keep coming). Here:

- the driver listens on a per-session unix socket; each spawned worker
  process connects and says hello (the raylet's worker registration
  handshake, worker_pool.h RegisterWorker);
- tasks are pushed to an idle worker as framed cloudpickle messages and
  the worker streams back results (PushTask / ReplyPushTask);
- the OBJECT plane does not ride the sockets: every payload larger than
  the inline threshold travels through the C++ shared-memory store
  (src/shm_store.cc) and only its 28-byte id crosses the socket —
  zero-copy on the host, the plasma property;
- function definitions are exported once per (worker, function) and
  cached worker-side (reference: _private/function_manager.py exports to
  GCS KV; here the export is pushed on first use);
- a worker crash (socket EOF) fails in-flight tasks with a retryable
  system error and the pool respawns a replacement — the same recovery
  contract as worker-process death under a raylet.

GIL note: each worker is a real OS process, so task execution is truly
parallel, unlike the in-process thread-pool nodes.
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu")

_LEN = struct.Struct("!Q")


class WorkerCrashedError(RuntimeError):
    """The worker process died while owning a task (retryable)."""


def send_msg(sock: socket.socket, obj: Any) -> None:
    import cloudpickle

    payload = cloudpickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    import pickle

    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WorkerCrashedError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


# ---------------------------------------------------------------------------
# Argument / result wire encoding (object plane stays in shm)
# ---------------------------------------------------------------------------

class ShmArg:
    """Top-level ObjectRef arg whose payload lives in the shm store."""

    __slots__ = ("key", "is_error")

    def __init__(self, key: bytes, is_error: bool):
        self.key = key
        self.is_error = is_error


class SerArg:
    """Top-level ObjectRef arg shipped as serialized bytes (small or
    shm-less fallback)."""

    __slots__ = ("data", "is_error")

    def __init__(self, data: bytes, is_error: bool):
        self.data = data
        self.is_error = is_error


# ---------------------------------------------------------------------------
# Driver-side worker handle + pool
# ---------------------------------------------------------------------------

class WorkerProcess:
    """Driver-side handle to one spawned worker process."""

    def __init__(self, worker_id: int, proc: subprocess.Popen,
                 sock: socket.socket):
        self.worker_id = worker_id
        self.proc = proc
        self.sock = sock
        self.exported_fns: set = set()   # function ids pushed to this worker
        self.fn_calls: dict = {}         # function id -> executions (max_calls)
        self.alive = True
        self.pid = proc.pid
        self.dedicated = False           # actor-owned: not in the idle pool
        # Consumer threads send gen_ack credits while run_task's thread
        # is mid-conversation — sends must not interleave.
        self._send_lock = threading.Lock()

    def send_ack(self, n: int) -> None:
        """Grant the streaming producer `n` consumption credits
        (generator backpressure — reference: GeneratorWaiter)."""
        try:
            with self._send_lock:
                send_msg(self.sock, {"type": "gen_ack", "n": n})
        except OSError:
            pass  # worker died; run_task surfaces it

    def run_task(self, msg: Dict[str, Any],
                 on_stream: Optional[Callable[[Dict[str, Any]], None]] = None
                 ) -> Dict[str, Any]:
        """Push one task and read messages until its terminal reply.
        Streaming items (generators) are handed to on_stream."""
        try:
            with self._send_lock:
                send_msg(self.sock, msg)
            while True:
                reply = recv_msg(self.sock)
                if reply.get("type") == "gen_item":
                    if on_stream is not None:
                        on_stream(reply)
                    continue
                return reply
        except (WorkerCrashedError, OSError, EOFError) as e:
            self.alive = False
            raise WorkerCrashedError(
                f"worker {self.worker_id} (pid {self.pid}) died: {e}"
            ) from e

    def shutdown(self):
        self.alive = False
        try:
            send_msg(self.sock, {"type": "shutdown"})
        except OSError:
            pass
        try:
            self.sock.close()
        finally:
            if self.proc.poll() is None:
                try:
                    self.proc.terminate()
                    self.proc.wait(timeout=2)
                except Exception:  # noqa: BLE001
                    self.proc.kill()

    def kill(self):
        """Hard-kill (fault-injection: reference NodeKillerActor)."""
        self.alive = False
        try:
            self.proc.kill()
        except Exception:  # noqa: BLE001
            pass


class WorkerPool:
    """Spawns and leases worker processes (reference: worker_pool.h:156).

    acquire() leases an idle worker (blocking); release() returns it.
    Dead workers are discarded and respawned to keep capacity."""

    def __init__(self, num_workers: int, *, shm_name: Optional[str],
                 env: Optional[Dict[str, str]] = None,
                 logs_dir: Optional[str] = None):
        self.num_workers = num_workers
        self.shm_name = shm_name
        self._env = env
        self._logs_dir = logs_dir
        self._idle: "queue.Queue[WorkerProcess]" = queue.Queue()
        self._all: Dict[int, WorkerProcess] = {}
        self._lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        # Native-dispatch hooks (set by the node daemon after its C
        # loop starts; None = pure-Python pool, unchanged behavior):
        #   idle_sink(w) -> bool    consume an idling worker (register
        #                           its socket with the native loop);
        #                           False = keep it in _idle
        #   idle_source(timeout) -> WorkerProcess | None
        #                           one bounded wait for an idle worker
        #                           owned by the native loop; acquire()
        #                           loops on None
        #   on_discard(w)           worker leaving the pool for good
        #                           (retire/discard) — unregister it
        self.idle_sink: Optional[Callable[[WorkerProcess], bool]] = None
        self.idle_source: Optional[
            Callable[[Optional[float]], Optional[WorkerProcess]]] = None
        self.on_discard: Optional[Callable[[WorkerProcess], None]] = None

        self._sock_dir = tempfile.mkdtemp(prefix="ray_tpu_")
        self._sock_path = os.path.join(self._sock_dir, "workers.sock")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._sock_path)
        self._listener.listen(max(8, num_workers))
        # Hello routing: concurrent spawns (e.g. two crashed workers
        # respawning from different threads) must not steal each
        # other's connections off the shared listener — a stolen-and-
        # closed hello kills the other spawn's worker. One thread
        # accepts at a time; arrived connections are parked by
        # worker_id for their waiter.
        self._accept_lock = threading.Lock()
        self._hello_cv = threading.Condition()
        self._hellos: Dict[int, socket.socket] = {}

        for _ in range(num_workers):
            self._spawn()

    def _await_hello(self, wid: int, deadline: float) -> socket.socket:
        while True:
            with self._hello_cv:
                conn = self._hellos.pop(wid, None)
                if conn is not None:
                    return conn
            if time.monotonic() >= deadline:
                # A late hello may still get parked for us by another
                # accepter; reap it so the fd cannot leak.
                with self._hello_cv:
                    conn = self._hellos.pop(wid, None)
                if conn is not None:
                    return conn
                raise TimeoutError(
                    f"worker {wid} did not connect before deadline")
            # One accepter at a time; everyone else waits on the cv.
            if self._accept_lock.acquire(timeout=0.1):
                try:
                    with self._hello_cv:
                        conn = self._hellos.pop(wid, None)
                    if conn is not None:
                        return conn
                    self._listener.settimeout(
                        max(0.1, deadline - time.monotonic()))
                    try:
                        conn, _ = self._listener.accept()
                    except (socket.timeout, TimeoutError):
                        continue
                    try:
                        # A connected-but-silent or crashed-at-startup
                        # worker must not wedge (we hold _accept_lock)
                        # or abort an unrelated spawn.
                        conn.settimeout(5)
                        hello = recv_msg(conn)
                        conn.settimeout(None)
                    except Exception:  # noqa: BLE001
                        with contextlib.suppress(OSError):
                            conn.close()
                        continue
                    # Only a typed hello registers a worker: anything
                    # else on this socket (a stray client, a worker
                    # speaking a future protocol) must not be mistaken
                    # for the spawn we are waiting on.
                    is_hello = (isinstance(hello, dict)
                                and hello.get("type") == "hello")
                    got = hello.get("worker_id") if is_hello else None
                    if got is not None and got == wid:
                        return conn
                    if not isinstance(got, int):
                        with contextlib.suppress(OSError):
                            conn.close()
                        continue
                    with self._hello_cv:
                        stale = self._hellos.pop(got, None)
                        self._hellos[got] = conn
                        self._hello_cv.notify_all()
                    if stale is not None:
                        with contextlib.suppress(OSError):
                            stale.close()
                finally:
                    self._accept_lock.release()
            else:
                with self._hello_cv:
                    self._hello_cv.wait(timeout=0.1)

    def _spawn_proc(self) -> WorkerProcess:
        with self._lock:
            wid = self._next_id
            self._next_id += 1
        cmd = [sys.executable, "-m", "ray_tpu.core.worker_main",
               "--socket", self._sock_path, "--worker-id", str(wid)]
        if self.shm_name:
            cmd += ["--shm", self.shm_name]
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        # Workers must not grab the (single) TPU chip the driver owns.
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Worker stdout/stderr go to per-worker session log files, tailed
        # back to the driver by the LogMonitor (reference: the raylet
        # redirects worker logs under /tmp/ray/session_*/logs).
        stdout = stderr = None
        if self._logs_dir:
            stdout = open(os.path.join(
                self._logs_dir, f"worker-{wid}.out"), "ab", buffering=0)
            stderr = open(os.path.join(
                self._logs_dir, f"worker-{wid}.err"), "ab", buffering=0)
        proc = subprocess.Popen(cmd, env=env, cwd=os.getcwd(),
                                stdout=stdout, stderr=stderr)
        if stdout is not None:
            stdout.close()
            stderr.close()
        try:
            conn = self._await_hello(wid, time.monotonic() + 30)
        except TimeoutError:
            with contextlib.suppress(Exception):
                proc.kill()
            # A hello parked for us after the deadline would leak its fd.
            with self._hello_cv:
                late = self._hellos.pop(wid, None)
            if late is not None:
                with contextlib.suppress(OSError):
                    late.close()
            raise
        w = WorkerProcess(wid, proc, conn)
        with self._lock:
            self._all[wid] = w
        return w

    def _spawn(self) -> WorkerProcess:
        w = self._spawn_proc()
        sink = self.idle_sink
        if sink is None or not sink(w):
            self._idle.put(w)
        return w

    def spawn_dedicated(self) -> WorkerProcess:
        """Spawn a worker OWNED by an actor (reference: the raylet starts
        a fresh worker process per actor). Never enters the idle pool, so
        long-lived actors cannot starve the task plane."""
        w = self._spawn_proc()
        w.dedicated = True
        return w

    def retire(self, w: WorkerProcess) -> None:
        """Terminate a dedicated worker (actor death) without respawning
        pool capacity."""
        with self._lock:
            self._all.pop(w.worker_id, None)
        cb = self.on_discard
        if cb is not None:
            with contextlib.suppress(Exception):
                cb(w)
        try:
            w.shutdown()
        except Exception:  # noqa: BLE001
            pass

    def get_worker(self, wid: int) -> Optional[WorkerProcess]:
        with self._lock:
            return self._all.get(wid)

    def acquire(self, timeout: Optional[float] = None) -> WorkerProcess:
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            left = (deadline - time.monotonic()) if deadline else None
            if left is not None and left <= 0:
                raise TimeoutError("no idle worker")
            src = self.idle_source
            if src is not None:
                w = src(left)
                if w is None:
                    continue
            else:
                w = self._idle.get(timeout=left)
            if w.alive and w.proc.poll() is None:
                return w
            self._discard(w)

    def recycle(self, w: WorkerProcess) -> None:
        """Retire a pool worker; the replacement spawns on a
        background thread so task completion doesn't pay the process
        start (reference: the raylet replaces workers asynchronously).
        """
        self._discard(w, respawn_in_background=True)

    def release(self, w: WorkerProcess) -> None:
        if self._closed:
            return
        if w.alive and w.proc.poll() is None:
            sink = self.idle_sink
            if sink is None or not sink(w):
                self._idle.put(w)
        else:
            self._discard(w)

    def _discard(self, w: WorkerProcess,
                 respawn_in_background: bool = False) -> None:
        """Drop a worker and respawn a replacement (pool workers
        only; dedicated actor workers are replaced by actor restart)."""
        with self._lock:
            self._all.pop(w.worker_id, None)
        cb = self.on_discard
        if cb is not None:
            with contextlib.suppress(Exception):
                cb(w)
        try:
            w.shutdown()
        except Exception:  # noqa: BLE001
            pass
        if self._closed or w.dedicated:
            return

        def respawn():
            # Re-check at spawn time: shutdown() may have landed while
            # this thread was starting (else an orphan worker Popens
            # against a closed listener and blocks its hello ~30s).
            if self._closed:
                return
            try:
                self._spawn()
            except Exception:  # noqa: BLE001
                logger.exception("worker respawn failed")

        if respawn_in_background:
            threading.Thread(target=respawn, daemon=True,
                             name="worker-respawn").start()
        else:
            respawn()

    def workers(self) -> List[WorkerProcess]:
        with self._lock:
            return list(self._all.values())

    def shutdown(self):
        self._closed = True
        for w in self.workers():
            w.shutdown()
        with self._lock:
            self._all.clear()
        with self._hello_cv:
            parked = list(self._hellos.values())
            self._hellos.clear()
        for conn in parked:
            with contextlib.suppress(OSError):
                conn.close()
        try:
            self._listener.close()
            os.unlink(self._sock_path)
            os.rmdir(self._sock_dir)
        except OSError:
            pass
