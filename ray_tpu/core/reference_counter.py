"""Distributed reference counting (single-process authority).

Capability-equivalent to the reference's ReferenceCounter
(reference: src/ray/core_worker/reference_count.h): every ObjectRef held in
Python holds a local reference; refs serialized into task arguments create
borrows; when the count for an object reaches zero the object is eligible
for deletion from the store and its lineage can be released. In the
multiprocess runtime the owner worker runs this table and borrowers report
via the node daemon; in local mode it is simply process-wide.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from .ids import ObjectID


class ReferenceCounter:
    def __init__(self, on_zero: Optional[Callable[[ObjectID], None]] = None):
        self._lock = threading.Lock()
        self._local: Dict[ObjectID, int] = {}
        self._borrows: Dict[ObjectID, int] = {}
        self._pinned: Set[ObjectID] = set()
        self._on_zero = on_zero

    def set_on_zero(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_zero = cb

    def add_local_ref(self, oid: ObjectID, n: int = 1) -> None:
        with self._lock:
            self._local[oid] = self._local.get(oid, 0) + n

    def remove_local_ref(self, oid: ObjectID) -> None:
        fire = False
        with self._lock:
            c = self._local.get(oid, 0) - 1
            if c <= 0:
                self._local.pop(oid, None)
                if (self._borrows.get(oid, 0) <= 0
                        and oid not in self._pinned):
                    fire = True
            else:
                self._local[oid] = c
        if fire and self._on_zero is not None:
            self._on_zero(oid)

    def add_borrow(self, oid: ObjectID) -> None:
        with self._lock:
            self._borrows[oid] = self._borrows.get(oid, 0) + 1

    def remove_borrow(self, oid: ObjectID) -> None:
        fire = False
        with self._lock:
            c = self._borrows.get(oid, 0) - 1
            if c <= 0:
                self._borrows.pop(oid, None)
                if (self._local.get(oid, 0) <= 0
                        and oid not in self._pinned):
                    fire = True
            else:
                self._borrows[oid] = c
        if fire and self._on_zero is not None:
            self._on_zero(oid)

    def pin(self, oid: ObjectID) -> None:
        """Pin for the duration of task execution (args must not vanish)."""
        with self._lock:
            self._pinned.add(oid)

    def unpin(self, oid: ObjectID) -> None:
        fire = False
        with self._lock:
            self._pinned.discard(oid)
            if (self._local.get(oid, 0) <= 0
                    and self._borrows.get(oid, 0) <= 0):
                fire = True
        if fire and self._on_zero is not None:
            self._on_zero(oid)

    def count(self, oid: ObjectID) -> int:
        with self._lock:
            return self._local.get(oid, 0) + self._borrows.get(oid, 0)

    def tracked(self) -> int:
        with self._lock:
            return len(self._local) + len(self._borrows)
