"""@remote for classes: ActorClass / ActorHandle / ActorMethod.

Capability-equivalent to the reference's actor surface
(reference: python/ray/actor.py — ActorClass :544, `_remote` :829,
ActorMethod._remote :268): `.remote()` creation, `.options()` chaining,
handle pickling (by actor id), named/detached actors, per-method options,
`exit_actor()`.
"""

from __future__ import annotations

from typing import Any, Dict

from .ids import ActorID
from .runtime import _ActorExit, global_runtime
from .task import validate_options


def method(**opts):
    """Per-method defaults (reference: @ray.method — num_returns,
    concurrency_group). Stored on the function; the runtime reads them
    at submit time."""
    allowed = {"num_returns", "concurrency_group"}
    bad = set(opts) - allowed
    if bad:
        raise ValueError(
            f"@method supports {sorted(allowed)}; got {sorted(bad)}")

    def wrap(fn):
        fn._ray_method_opts = dict(opts)
        return fn

    return wrap


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 opts: Dict[str, Any] | None = None):
        self._handle = handle
        self._method_name = method_name
        self._opts = opts or {}

    def remote(self, *args, **kwargs):
        return global_runtime().submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs,
            self._opts)

    def options(self, **opts) -> "ActorMethod":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorMethod(self._handle, self._method_name, merged)

    def bind(self, *args, **kwargs):
        from ..dag.node import ActorMethodNode
        return ActorMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly. "
            "Use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID):
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:16]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id,))

    def _ray_terminate(self):
        global_runtime().kill_actor(self._actor_id)


class ActorClass:
    def __init__(self, cls: type, opts: Dict[str, Any]):
        self._cls = cls
        self._opts = validate_options(dict(opts), is_actor=True)
        self.__name__ = cls.__name__
        self.__qualname__ = cls.__qualname__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated "
            "directly. Use .remote()."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ..client import get_client

        c = get_client()
        if c is not None:
            return c.create_actor(self._cls, args, kwargs, self._opts)
        actor_id = global_runtime().create_actor(
            self._cls, args, kwargs, self._opts)
        return ActorHandle(actor_id)

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def bind(self, *args, **kwargs):
        from ..dag.node import ClassNode
        return ClassNode(self, args, kwargs)

    @property
    def underlying_class(self) -> type:
        return self._cls


def exit_actor():
    """Terminate the current actor from inside a method
    (reference: python/ray/actor.py exit_actor)."""
    raise _ActorExit()


def get_actor(name: str, namespace: "str | None" = None) -> ActorHandle:
    from ..client import get_client

    c = get_client()
    if c is not None:
        return c.get_named_actor(name, namespace)
    return ActorHandle(global_runtime().get_actor(name, namespace))
