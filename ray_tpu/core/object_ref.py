"""ObjectRef — the distributed future.

Capability-equivalent to the reference's ObjectRef
(reference: python/ray/includes/object_ref.pxi and
src/ray/core_worker/reference_count.h for the borrowing semantics):
a handle to an eventually-available immutable object, picklable (pickling
inside task args registers a borrow with the owner), awaitable via
``get``/``wait``, and carrying its lineage in the ID itself.
"""

from __future__ import annotations

from typing import Any, Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "__weakref__")

    def __init__(self, object_id: ObjectID):
        self._id = object_id

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    # -- identity ---------------------------------------------------------
    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # -- convenience ------------------------------------------------------
    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime as _rt
        return _rt.global_runtime().as_future(self)

    def __await__(self):
        """Allow ``await ref`` inside async actors / drivers."""
        import asyncio

        async def _aget():
            loop = asyncio.get_running_loop()
            from . import runtime as _rt
            rt = _rt.global_runtime()
            return await loop.run_in_executor(None, rt.get, [self], None)

        async def _first():
            return (await _aget())[0]

        return _first().__await__()

    # -- pickling: register a borrow and re-attach on the far side -------
    def __reduce__(self):
        from . import runtime as _rt
        rt = _rt.global_runtime_or_none()
        if rt is not None:
            rt.reference_counter.add_borrow(self._id)
            rt.serialization_noted_ref(self)
        return (_deserialize_ref, (self._id.binary(),))


def _deserialize_ref(id_bytes: bytes) -> "ObjectRef":
    ref = ObjectRef(ObjectID(id_bytes))
    from . import runtime as _rt
    rt = _rt.global_runtime_or_none()
    if rt is not None:
        # Registers a local ref WITH a finalizer so deserialized copies
        # participate in refcounting/GC like driver-created refs.
        rt.register_ref(ref)
    return ref
