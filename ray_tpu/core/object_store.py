"""In-process object store (memory store).

Capability-equivalent to the reference's CoreWorker memory store
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h)
— holds serialized objects keyed by ObjectID, supports blocking gets with
timeouts, async ready-callbacks (used by the scheduler's dependency
resolver), error objects, deletion/loss, and simple accounting. The
shared-memory (plasma-equivalent) store plugs in behind the same interface
for the multiprocess runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from .exceptions import GetTimeoutError
from .ids import ObjectID
from .serialization import SerializedObject


class StoredObject:
    __slots__ = ("data", "is_error", "created_at", "nbytes",
                 "spill_path")

    def __init__(self, data: SerializedObject, is_error: bool):
        self.data = data
        self.is_error = is_error
        self.created_at = time.monotonic()
        self.nbytes = data.total_bytes()
        self.spill_path: Optional[str] = None  # set while on disk


class MemoryStore:
    """spiller + high_watermark_bytes enable disk overflow (reference:
    local_object_manager spilling — see spilling.py): objects past the
    watermark move to disk oldest-first and restore on access."""

    def __init__(self, spiller=None, high_watermark_bytes: int = 0):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._objects: Dict[ObjectID, StoredObject] = {}
        self._waiter_cbs: Dict[ObjectID, List[Callable[[ObjectID], None]]] = {}
        self.total_bytes = 0  # in-MEMORY bytes (spilled don't count)
        self._spiller = spiller
        self._high = high_watermark_bytes
        self._spill_lock = threading.Lock()  # one spill pass at a time

    # -- write ------------------------------------------------------------
    def put(self, object_id: ObjectID, data: SerializedObject,
            is_error: bool = False) -> None:
        with self._lock:
            prev = self._objects.get(object_id)
            if prev is not None and prev.spill_path is None:
                self.total_bytes -= prev.nbytes
            if prev is not None and prev.spill_path is not None \
                    and self._spiller is not None:
                self._spiller.delete(prev.spill_path)
            obj = StoredObject(data, is_error)
            self._objects[object_id] = obj
            self.total_bytes += obj.nbytes
            cbs = self._waiter_cbs.pop(object_id, [])
            self._cv.notify_all()
        for cb in cbs:
            cb(object_id)
        self._maybe_spill()

    def delete(self, object_ids: Sequence[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                obj = self._objects.pop(oid, None)
                if obj is not None:
                    if obj.spill_path is not None:
                        if self._spiller is not None:
                            self._spiller.delete(obj.spill_path)
                    else:
                        self.total_bytes -= obj.nbytes

    # -- spilling ---------------------------------------------------------
    @staticmethod
    def _spillable(obj: StoredObject) -> bool:
        # Only real serialized frames spill: shm markers / error stubs
        # have no meaningful to_bytes round-trip.
        return (obj.spill_path is None and not obj.is_error
                and isinstance(obj.data, SerializedObject))

    def _maybe_spill(self) -> None:
        """Move oldest in-memory objects to disk until below the high
        watermark. File IO happens OUTSIDE the store lock; the entry
        swaps to a stub only after the write completes. Readers are
        never affected: get() hands out snapshots whose data reference
        keeps the bytes alive regardless of the canonical entry."""
        if self._spiller is None or not self._high:
            return
        if self.total_bytes <= self._high:
            return
        with self._spill_lock:
            with self._lock:
                excess = self.total_bytes - self._high
                if excess <= 0:
                    return
                # One sort per pass (not per victim).
                victims = sorted(
                    ((oid, o) for oid, o in self._objects.items()
                     if self._spillable(o)),
                    key=lambda kv: kv[1].created_at)
                plan = []
                for oid, o in victims:
                    if excess <= 0:
                        break
                    plan.append((oid, o, o.data))
                    excess -= o.nbytes
            for oid, obj, data in plan:
                path = self._spiller.spill(oid, data)
                with self._lock:
                    cur = self._objects.get(oid)
                    if cur is obj and cur.spill_path is None:
                        cur.spill_path = path
                        cur.data = None
                        self.total_bytes -= cur.nbytes
                    else:
                        # Replaced/deleted mid-spill — drop the file.
                        self._spiller.delete(path)

    def _restore(self, object_id: ObjectID) -> Optional[StoredObject]:
        """Bring a spilled object back; file IO outside the lock.
        Returns a SNAPSHOT safe against concurrent re-spills (or None
        if the object vanished)."""
        while True:
            with self._lock:
                obj = self._objects.get(object_id)
                if obj is None:
                    return None
                if obj.spill_path is None:
                    return self._snapshot(obj)
                path = obj.spill_path
            try:
                data = self._spiller.restore(path)
            except FileNotFoundError:
                # Concurrent restore/delete — loop to re-observe state.
                continue
            with self._lock:
                cur = self._objects.get(object_id)
                if cur is None:
                    return None
                if cur.spill_path == path:
                    cur.data = data
                    cur.spill_path = None
                    cur.created_at = time.monotonic()
                    self.total_bytes += cur.nbytes
                    self._spiller.delete(path)
                    return self._snapshot(cur)
                # Someone else finished first; use their result.

    @staticmethod
    def _snapshot(obj: StoredObject) -> StoredObject:
        """Reader-held view: shares the data reference so a later spill
        pass nulling the canonical entry can't affect the reader."""
        snap = StoredObject.__new__(StoredObject)
        snap.data = obj.data
        snap.is_error = obj.is_error
        snap.created_at = obj.created_at
        snap.nbytes = obj.nbytes
        snap.spill_path = None
        return snap

    # -- read -------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def nbytes_if_exists(self, object_id: ObjectID) -> Optional[int]:
        """Size of a stored object without materializing it (spilled
        objects are NOT restored — their recorded size is returned).
        Used by Data's byte-budget backpressure to cost completed
        blocks."""
        with self._lock:
            obj = self._objects.get(object_id)
            return None if obj is None else obj.nbytes

    def get_if_exists(self, object_id: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                return None
            if obj.spill_path is None:
                return self._snapshot(obj)
        out = self._restore(object_id)  # file IO outside the lock
        self._maybe_spill()
        return out

    def get(self, object_ids: Sequence[ObjectID],
            timeout: Optional[float] = None) -> List[StoredObject]:
        """Blocking get of all ids (restoring spilled ones). Raises
        GetTimeoutError on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            spilled: List[ObjectID] = []
            with self._lock:
                while True:
                    missing = [o for o in object_ids
                               if o not in self._objects]
                    if not missing:
                        break
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise GetTimeoutError(
                                f"Timed out waiting for {len(missing)} "
                                f"object(s); first missing: "
                                f"{missing[0].hex()}")
                        self._cv.wait(remaining)
                    else:
                        self._cv.wait()
                out: List[Optional[StoredObject]] = []
                for o in object_ids:
                    obj = self._objects[o]
                    if obj.spill_path is not None:
                        spilled.append(o)
                        out.append(None)
                    else:
                        out.append(self._snapshot(obj))
            if not spilled:
                return out
            # Restore outside the lock; a vanished object (deleted
            # mid-restore) restarts the wait loop.
            ok = True
            restored: Dict[ObjectID, StoredObject] = {}
            for oid in spilled:
                snap = self._restore(oid)
                if snap is None:
                    ok = False
                    break
                restored[oid] = snap
            self._maybe_spill()
            if not ok:
                continue
            return [restored.get(o) or out[i]
                    for i, o in enumerate(object_ids)]

    def wait(self, object_ids: Sequence[ObjectID], num_returns: int,
             timeout: Optional[float]) -> tuple[List[ObjectID], List[ObjectID]]:
        """Ray-style wait: (ready, not_ready) preserving input order."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            ready_set = set(o for o in object_ids if o in self._objects)
        ready_list, not_ready = [], []
        for o in object_ids:
            (ready_list if o in ready_set and len(ready_list) < num_returns
             else not_ready).append(o)
        return ready_list, not_ready

    # -- async ------------------------------------------------------------
    def on_ready(self, object_id: ObjectID,
                 callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when object_id becomes available (maybe now)."""
        fire = False
        with self._lock:
            if object_id in self._objects:
                fire = True
            else:
                self._waiter_cbs.setdefault(object_id, []).append(callback)
        if fire:
            callback(object_id)

    # -- stats ------------------------------------------------------------
    def num_objects(self) -> int:
        with self._lock:
            return len(self._objects)
