"""In-process object store (memory store).

Capability-equivalent to the reference's CoreWorker memory store
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h)
— holds serialized objects keyed by ObjectID, supports blocking gets with
timeouts, async ready-callbacks (used by the scheduler's dependency
resolver), error objects, deletion/loss, and simple accounting. The
shared-memory (plasma-equivalent) store plugs in behind the same interface
for the multiprocess runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from .exceptions import GetTimeoutError
from .ids import ObjectID
from .serialization import SerializedObject


class StoredObject:
    __slots__ = ("data", "is_error", "created_at", "nbytes")

    def __init__(self, data: SerializedObject, is_error: bool):
        self.data = data
        self.is_error = is_error
        self.created_at = time.monotonic()
        self.nbytes = data.total_bytes()


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._objects: Dict[ObjectID, StoredObject] = {}
        self._waiter_cbs: Dict[ObjectID, List[Callable[[ObjectID], None]]] = {}
        self.total_bytes = 0

    # -- write ------------------------------------------------------------
    def put(self, object_id: ObjectID, data: SerializedObject,
            is_error: bool = False) -> None:
        with self._lock:
            prev = self._objects.get(object_id)
            if prev is not None:
                self.total_bytes -= prev.nbytes
            obj = StoredObject(data, is_error)
            self._objects[object_id] = obj
            self.total_bytes += obj.nbytes
            cbs = self._waiter_cbs.pop(object_id, [])
            self._cv.notify_all()
        for cb in cbs:
            cb(object_id)

    def delete(self, object_ids: Sequence[ObjectID]) -> None:
        with self._lock:
            for oid in object_ids:
                obj = self._objects.pop(oid, None)
                if obj is not None:
                    self.total_bytes -= obj.nbytes

    # -- read -------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            return self._objects.get(object_id)

    def get(self, object_ids: Sequence[ObjectID],
            timeout: Optional[float] = None) -> List[StoredObject]:
        """Blocking get of all ids. Raises GetTimeoutError on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                missing = [o for o in object_ids if o not in self._objects]
                if not missing:
                    return [self._objects[o] for o in object_ids]
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise GetTimeoutError(
                            f"Timed out waiting for {len(missing)} object(s); "
                            f"first missing: {missing[0].hex()}"
                        )
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()

    def wait(self, object_ids: Sequence[ObjectID], num_returns: int,
             timeout: Optional[float]) -> tuple[List[ObjectID], List[ObjectID]]:
        """Ray-style wait: (ready, not_ready) preserving input order."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            ready_set = set(o for o in object_ids if o in self._objects)
        ready_list, not_ready = [], []
        for o in object_ids:
            (ready_list if o in ready_set and len(ready_list) < num_returns
             else not_ready).append(o)
        return ready_list, not_ready

    # -- async ------------------------------------------------------------
    def on_ready(self, object_id: ObjectID,
                 callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when object_id becomes available (maybe now)."""
        fire = False
        with self._lock:
            if object_id in self._objects:
                fire = True
            else:
                self._waiter_cbs.setdefault(object_id, []).append(callback)
        if fire:
            callback(object_id)

    # -- stats ------------------------------------------------------------
    def num_objects(self) -> int:
        with self._lock:
            return len(self._objects)
