"""RLHFPipeline — the three-plane GRPO loop (north-star config 5).

    rollout plane      N generator actors, each an LLMEngine with
                       continuous batching + shared-system-prompt
                       prefix cache + sampling-time logp capture
    learner plane      GRPOLearner over a ParallelPlan mesh (dp/fsdp):
                       in-jit advantage normalization + clipped update
    refresh plane      learner put()s byte-balanced param blocks; the
                       generators' arg-plane pulls ride the relay
                       broadcast tree (~O(log N) producer copies)

One `train_iteration()` = rollout → reward → update → refresh, each
phase a flight-recorder event and a chrome-trace span (`ray_tpu
timeline`), with generator death survived at any point — including
mid-refresh — by respawn + re-refresh + re-issue.

Reference capability: RLlib's learner/rollout-worker split
(rllib/core/learner/learner_group.py) wired around external LLM
trainers; here the whole loop is in-framework on the TPU-native stack.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.exceptions import ActorDiedError, ActorError, RayTpuError
from ..core.graphable import graphable
from ..models.transformer import TransformerConfig
from ..observability import get_recorder
from ..observability import tsdb as _tsdb
from ..parallel.plan import ParallelPlan
from ..util import tracing as _tracing
from .learner import GRPOLearner, GRPOLearnerConfig
from .rollout import RolloutWorker


@dataclass(frozen=True)
class RLHFConfig:
    model: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=4, d_ff=128, max_seq_len=64,
            dtype=jnp.float32, param_dtype=jnp.float32, remat=False))
    plan: ParallelPlan = field(default_factory=ParallelPlan)
    num_generators: int = 4
    # Per iteration, across all generators; must divide evenly.
    num_prompts: int = 8
    prompt_len: int = 8
    group_size: int = 4
    max_new_tokens: int = 16
    temperature: float = 1.0
    eos_token: Optional[int] = None
    # reward_fn: completions (N, max_new) int32 -> (N,) float. Ignored
    # when reward_model is set — any object (or actor handle) with
    # .score(completions, lengths) -> (N,) float, the scored-reward /
    # reward-model hook.
    reward_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None
    reward_model: Optional[Any] = None
    # Tokens every prompt starts with; registered as an engine prefix
    # so its KV prefills once per generator, not once per request.
    system_prompt: Optional[Sequence[int]] = None
    clip_eps: float = 0.2
    kl_coef: float = 0.02
    lr: float = 1e-4
    warmup_steps: int = 5
    total_steps: int = 1000
    refresh_blocks: int = 8
    num_slots: int = 4
    decode_block: int = 16
    seed: int = 0
    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 0
    num_to_keep: int = 2


_ITER_GAUGE = None
_REFRESH_BYTES = None


def _metrics():
    """Lazy singletons: the metric registry rejects re-registration,
    and two pipelines in one process should share the series."""
    global _ITER_GAUGE, _REFRESH_BYTES
    if _ITER_GAUGE is None:
        from ..util import metrics as mm

        _ITER_GAUGE = mm.Gauge(
            "ray_tpu_rlhf_iteration_seconds",
            "Wall-clock seconds of the last RLHF train iteration",
            tag_keys=("phase",))
        _REFRESH_BYTES = mm.Counter(
            "ray_tpu_rlhf_refresh_bytes_total",
            "Total param bytes shipped through weight refresh")
    return _ITER_GAUGE, _REFRESH_BYTES


class RLHFPipeline:
    def __init__(self, cfg: RLHFConfig, *,
                 generator_options: Optional[Dict[str, Any]] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.cfg = cfg
        if cfg.num_prompts % cfg.num_generators:
            raise ValueError(
                f"num_prompts={cfg.num_prompts} must divide across "
                f"{cfg.num_generators} generators")
        if cfg.reward_fn is None and cfg.reward_model is None:
            raise ValueError("need reward_fn or reward_model")
        self.learner = GRPOLearner(
            GRPOLearnerConfig(
                model=cfg.model, group_size=cfg.group_size,
                clip_eps=cfg.clip_eps, kl_coef=cfg.kl_coef, lr=cfg.lr,
                warmup_steps=cfg.warmup_steps,
                total_steps=cfg.total_steps, seed=cfg.seed),
            cfg.plan)
        self._rng = np.random.default_rng(cfg.seed)
        from ..core.task import SpreadSchedulingStrategy

        self._gen_opts = dict(generator_options or {})
        # Generators default to SPREAD (same default as serve
        # replicas): one node death costs a fraction of the rollout
        # fleet, and the weight-refresh relay gets >1 pulling node.
        self._gen_opts.setdefault(
            "scheduling_strategy", SpreadSchedulingStrategy())
        self._gen_cls = ray_tpu.remote(**self._gen_opts)(RolloutWorker)
        self.generators: List[Any] = [
            self._spawn_generator(i) for i in range(cfg.num_generators)]
        self.iteration = 0
        self._version = -1
        self._last_refresh: List[Any] = []  # refs, for respawn catch-up
        self.respawns = 0
        # Per-generator tok/s EWMA across iterations — straggler
        # detection compares each against the fleet (MAD cohort test).
        self._gen_tps: List[Optional[float]] = (
            [None] * cfg.num_generators)
        self._ckpt = None
        if cfg.checkpoint_path:
            from ..train.checkpoint import CheckpointManager

            self._ckpt = CheckpointManager(
                cfg.checkpoint_path, num_to_keep=cfg.num_to_keep)
        # Generators start on seed weights: publish version 0 first so
        # the first rollout already samples the learner's policy.
        self.refresh_weights()

    # -- generator lifecycle -------------------------------------------

    def _spawn_generator(self, i: int):
        return self._gen_cls.remote(
            self.cfg.model, num_slots=self.cfg.num_slots,
            seed=self.cfg.seed + 1000 + i,
            decode_block=self.cfg.decode_block,
            system_prompt=self.cfg.system_prompt)

    def _revive_generator(self, i: int) -> None:
        """Replace a dead generator and bring it to the current policy
        version before it serves anything (a revived generator on seed
        weights would silently poison the next batch's logps)."""
        import ray_tpu

        self.respawns += 1
        get_recorder().record("rlhf", "generator_respawn", index=i,
                              version=self._version)
        self.generators[i] = self._spawn_generator(i)
        self._gen_tps[i] = None  # fresh actor, fresh throughput history
        if self._last_refresh:
            ray_tpu.get(self.generators[i].refresh_weights.remote(
                self._version, *self._last_refresh))

    def _detect_stragglers(self) -> List[int]:
        """Generators whose tok/s EWMA sits k MADs below the fleet —
        the slow-node signal (thermal throttle, noisy neighbor, bad
        HBM) that per-iteration totals average away."""
        from .._private.config import config

        if not config.anomaly_detection_enabled:
            return []
        fleet = {str(i): tps for i, tps in enumerate(self._gen_tps)
                 if tps is not None}
        out = _tsdb.mad_outliers(fleet, side="low")
        stragglers = sorted(int(i) for i in out)
        reg = _tsdb.get_anomaly_registry()
        for i in stragglers:
            reg.flag("rlhf", "straggler", f"generator:{i}",
                     tokens_per_s=round(self._gen_tps[i], 3),
                     deviation=round(out[str(i)], 3),
                     iteration=self.iteration)
        return stragglers

    def _get_with_revival(self, i: int, submit: Callable[[], Any]):
        """ray_tpu.get(submit()) with one respawn-and-retry on actor
        death — the chaos contract: a generator killed at ANY phase
        costs one retry of its own work, never the iteration."""
        import ray_tpu

        try:
            return ray_tpu.get(submit())
        except (ActorDiedError, ActorError, RayTpuError):
            self._revive_generator(i)
            return ray_tpu.get(submit())

    # -- weight refresh ------------------------------------------------

    def refresh_weights(self) -> Dict[str, float]:
        """Publish the learner's params as block objects and fan them
        to every generator. The blocks go through put() once; each
        generator's refresh call carries the refs, so on a daemon
        cluster the pulls form the relay broadcast tree."""
        import ray_tpu

        _, refresh_counter = _metrics()
        t0 = time.perf_counter()
        version = self._version + 1
        with _tracing.span("rlhf.refresh", version=version):
            blocks = self.learner.param_blocks(self.cfg.refresh_blocks)
            refs = [ray_tpu.put(b) for b in blocks]
            self._last_refresh = refs
            self._version = version
            self._prefetch_to_generator_nodes(refs)
            # An already-dead generator raises at SUBMIT, one that dies
            # mid-refresh raises at get — both cost a revive (which
            # re-refreshes from the same refs), never the fleet.
            futures = []
            for g in self.generators:
                try:
                    futures.append(
                        g.refresh_weights.remote(version, *refs))
                except (ActorDiedError, ActorError, RayTpuError):
                    futures.append(None)
            total_bytes = 0
            for i, fut in enumerate(futures):
                try:
                    if fut is None:
                        raise ActorDiedError(
                            f"generator {i} dead at refresh submit")
                    res = ray_tpu.get(fut)
                except (ActorDiedError, ActorError, RayTpuError):
                    self._revive_generator(i)
                    res = ray_tpu.get(
                        self.generators[i].weight_version.remote())
                    res = {"version": res, "bytes": 0}
                total_bytes += int(res.get("bytes", 0))
        dt = time.perf_counter() - t0
        refresh_counter.inc(total_bytes)
        get_recorder().record("rlhf", "refresh", version=version,
                              bytes=total_bytes, seconds=dt,
                              generators=len(self.generators))
        return {"seconds": dt, "bytes": total_bytes,
                "version": version}

    def _prefetch_to_generator_nodes(self, refs) -> None:
        """On a daemon cluster, pre-stage the published blocks on every
        generator's node via the control plane's `weight_refresh`
        prefetch — the pulls (relay-tree shaped) start before the
        actors' refresh calls even dispatch. No-op single-node."""
        import ray_tpu

        from ..core import runtime as _rtmod

        rt = _rtmod.global_runtime()
        if rt.remote_plane is None:
            return
        try:
            nids = ray_tpu.get(
                [g.node_id.remote() for g in self.generators],
                timeout=30)
        except Exception:  # noqa: BLE001 — prefetch is advisory
            return
        nids = list(dict.fromkeys(n for n in nids if n))
        if nids:
            with _tracing.span("rlhf.refresh_prefetch", nodes=len(nids)):
                rt.remote_plane.prefetch_objects(refs, nids)

    # -- reward hook ---------------------------------------------------

    def _score(self, completions: np.ndarray,
               lengths: np.ndarray) -> np.ndarray:
        import ray_tpu

        rm = self.cfg.reward_model
        if rm is not None:
            score = getattr(rm, "score", None)
            if score is not None and hasattr(score, "remote"):
                rewards = ray_tpu.get(score.remote(completions, lengths))
            elif score is not None:
                rewards = score(completions, lengths)
            else:
                raise TypeError(
                    f"reward_model {type(rm).__name__} has no .score")
        else:
            rewards = self.cfg.reward_fn(completions)
        rewards = np.asarray(rewards, np.float32).reshape(-1)
        if rewards.shape[0] != completions.shape[0]:
            raise ValueError(
                f"reward hook returned {rewards.shape[0]} scores for "
                f"{completions.shape[0]} completions")
        return rewards

    # -- the loop ------------------------------------------------------

    def sample_prompts(self) -> np.ndarray:
        cfg = self.cfg
        base = self._rng.integers(
            0, cfg.model.vocab_size,
            size=(cfg.num_prompts, cfg.prompt_len), dtype=np.int64)
        if cfg.system_prompt:
            sys_row = np.asarray(list(cfg.system_prompt), np.int64)
            base = np.concatenate(
                [np.tile(sys_row, (cfg.num_prompts, 1)), base], axis=1)
        return base.astype(np.int32)

    @graphable(name="rlhf.train_iteration")
    def train_iteration(self) -> Dict[str, Any]:
        cfg = self.cfg
        iter_gauge, _ = _metrics()
        t0 = time.perf_counter()
        with _tracing.span("rlhf.iteration", iteration=self.iteration):
            # -- rollout: contiguous prompt chunks, one per generator
            prompts = self.sample_prompts()
            per_gen = cfg.num_prompts // cfg.num_generators
            with _tracing.span("rlhf.rollout_fanout"):
                t_roll = time.perf_counter()
                chunks = [prompts[i * per_gen:(i + 1) * per_gen]
                          for i in range(cfg.num_generators)]

                def _roll(i):
                    return self.generators[i].rollout.remote(
                        chunks[i], group_size=cfg.group_size,
                        max_new_tokens=cfg.max_new_tokens,
                        temperature=cfg.temperature,
                        eos_token=cfg.eos_token)

                results = []
                for i in range(cfg.num_generators):
                    t_gen = time.perf_counter()
                    r = self._get_with_revival(i, lambda i=i: _roll(i))
                    gen_s = time.perf_counter() - t_gen
                    results.append(r)
                    gen_tok = int(r["lengths"].sum())
                    self._gen_tps[i] = _tsdb.ewma_update(
                        self._gen_tps[i], gen_tok / max(gen_s, 1e-9))
                rollout_s = time.perf_counter() - t_roll
            stragglers = self._detect_stragglers()
            seqs = np.concatenate([r["seqs"] for r in results])
            logprobs = np.concatenate([r["logprobs"] for r in results])
            lengths = np.concatenate([r["lengths"] for r in results])
            P = results[0]["prompt_len"]
            tokens_out = int(lengths.sum())

            # -- reward
            completions = seqs[:, P:]
            rewards = self._score(completions, lengths)

            # -- learn: logps/mask land on the shifted (S-1) axis —
            # generated token t sits at sequence position P + t, so its
            # logp/mask index is P + t - 1.
            N, S = seqs.shape
            T = S - P
            old_logp = np.zeros((N, S - 1), np.float32)
            comp_mask = np.zeros((N, S - 1), np.float32)
            old_logp[:, P - 1:P - 1 + T] = logprobs
            comp_mask[:, P - 1:P - 1 + T] = (
                np.arange(T)[None, :] < lengths[:, None])
            with _tracing.span("rlhf.learn"):
                t_learn = time.perf_counter()
                metrics = self.learner.update(
                    seqs, old_logp, rewards, comp_mask)
                learn_s = time.perf_counter() - t_learn
            get_recorder().record("rlhf", "learn",
                                  iteration=self.iteration,
                                  loss=metrics["loss"],
                                  seconds=learn_s)

            # -- refresh
            refresh = self.refresh_weights()

        self.iteration += 1
        dt = time.perf_counter() - t0
        iter_gauge.set(dt, tags={"phase": "total"})
        iter_gauge.set(rollout_s, tags={"phase": "rollout"})
        iter_gauge.set(learn_s, tags={"phase": "learn"})
        iter_gauge.set(refresh["seconds"], tags={"phase": "refresh"})
        get_recorder().record("rlhf", "iteration",
                              iteration=self.iteration, seconds=dt,
                              tokens=tokens_out)
        out = {
            "iteration": self.iteration,
            "reward_mean": float(rewards.mean()),
            "reward_std": float(rewards.std()),
            "tokens": tokens_out,
            "rollout_s": rollout_s,
            "learn_s": learn_s,
            "refresh_s": refresh["seconds"],
            "refresh_bytes": refresh["bytes"],
            "iteration_s": dt,
            "tokens_per_s": tokens_out / max(rollout_s, 1e-9),
            "stragglers": stragglers,
            **metrics,
        }
        if (self._ckpt is not None and cfg.checkpoint_every
                and self.iteration % cfg.checkpoint_every == 0):
            self.save_checkpoint(out)
        return out

    def train(self, iterations: int) -> List[Dict[str, Any]]:
        return [self.train_iteration() for _ in range(iterations)]

    # -- checkpointing -------------------------------------------------

    def save_checkpoint(self,
                        metrics: Optional[Dict[str, Any]] = None):
        if self._ckpt is None:
            raise RuntimeError("no checkpoint_path configured")
        from ..train.checkpoint import Checkpoint

        state = self.learner.get_state()
        state["iteration"] = self.iteration
        state["version"] = self._version
        return self._ckpt.register(Checkpoint.from_pytree(state),
                                   dict(metrics or {}))

    def restore_latest(self) -> bool:
        """Restore learner state from the newest checkpoint and push
        it to the generators. → False when none exists."""
        if self._ckpt is None:
            raise RuntimeError("no checkpoint_path configured")
        ckpt = self._ckpt.latest()
        if ckpt is None:
            return False
        state = ckpt.to_pytree()
        self.iteration = int(state.pop("iteration"))
        state.pop("version", None)
        self.learner.set_state(state)
        self.refresh_weights()
        return True

    def shutdown(self) -> None:
        import ray_tpu

        for g in self.generators:
            try:
                ray_tpu.kill(g)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        self.generators = []
