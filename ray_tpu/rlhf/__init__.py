"""End-to-end RLHF on the TPU-native stack (north-star config 5).

Three planes wired into one loop: generator actors rolling out through
the continuous-batching serve engine with sampling-time logp capture
(`rollout.RolloutWorker`), a ParallelPlan-sharded GRPO learner
(`learner.GRPOLearner`), and learner→generator weight refresh through
the relay-broadcast object plane (`pipeline.RLHFPipeline`).
"""

from .learner import (
    GRPOLearner,
    GRPOLearnerConfig,
    aot_compile_grpo_step,
    make_grpo_step,
)
from .pipeline import RLHFConfig, RLHFPipeline
from .rollout import RolloutWorker

__all__ = [
    "GRPOLearner", "GRPOLearnerConfig", "make_grpo_step",
    "aot_compile_grpo_step", "RLHFConfig", "RLHFPipeline",
    "RolloutWorker",
]
