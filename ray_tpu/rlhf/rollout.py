"""Rollout plane: generator actors hosting a logprob-capturing engine.

Each `RolloutWorker` owns one `LLMEngine(capture_logprobs=True)` —
continuous batching, registered-prefix KV reuse for the shared system
prompt, and per-token logp capture at sampling time (the GRPO ratio
term's old-policy logps, recorded for free instead of recomputed with
a second forward). `rollout()` fans a prompt batch through the engine
and returns fixed-shape numpy buffers the learner shards directly;
`refresh_weights()` swaps in a new policy from relay-broadcast param
blocks.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..models.transformer import TransformerConfig, init_params
from ..observability import get_recorder
from ..util import tracing as _tracing


class RolloutWorker:
    """Generator actor for the RLHF pipeline (run via ray_tpu.remote).

    Starts from a seed-initialized policy; the pipeline's first weight
    refresh overwrites it with the learner's, so generation and update
    always run the same weights (versioned — every rollout result
    carries the policy version it sampled from).
    """

    def __init__(self, cfg: TransformerConfig, *, num_slots: int = 4,
                 seed: int = 0, decode_block: int = 16,
                 system_prompt: Optional[Sequence[int]] = None):
        import jax

        from ..serve.llm import LLMEngine

        self.cfg = cfg
        params = init_params(cfg, jax.random.key(seed))
        self.engine = LLMEngine(cfg, params, num_slots=num_slots,
                                seed=seed, decode_block=decode_block,
                                capture_logprobs=True)
        self._version = -1  # seed weights; refresh installs version >= 0
        self._refresh_bytes = 0
        self._inject_delay_s = 0.0
        if system_prompt:
            self.engine.register_prefix(list(system_prompt))

    def inject_fault(self, kind: str, value) -> None:
        """Chaos hook (same contract as serve Replica.inject_fault):
        `rollout_delay_s` makes this generator a deterministic
        straggler — every rollout sleeps first, the slow-node shape
        the anomaly watchdog must flag."""
        if kind == "rollout_delay_s":
            self._inject_delay_s = float(value)
        else:
            raise ValueError(f"unknown fault kind: {kind}")

    # -- weight refresh ------------------------------------------------

    def refresh_weights(self, version: int, *blocks) -> Dict[str, Any]:
        """Install policy `version` from param blocks ((leaf index,
        array) pairs, any split). Blocks arrive as VALUES — the caller
        passes ObjectRefs and the runtime's arg plane resolves them,
        which on a daemon cluster is exactly the relay-broadcast pull
        path (each node fetches from its tree parent, not the
        producer)."""
        import jax

        t0 = time.perf_counter()
        pairs: List = []
        for block in blocks:
            pairs.extend(block)
        leaves = jax.tree.leaves(self.engine.params)
        if len(pairs) != len(leaves):
            raise ValueError(
                f"weight refresh v{version}: got {len(pairs)} leaves, "
                f"policy has {len(leaves)}")
        by_idx = dict(pairs)
        treedef = jax.tree.structure(self.engine.params)
        new_params = jax.tree.unflatten(
            treedef, [by_idx[i] for i in range(len(leaves))])
        self.engine.set_params(new_params)
        self._version = int(version)
        nbytes = sum(np.asarray(a).nbytes for _, a in pairs)
        self._refresh_bytes += nbytes
        dt = time.perf_counter() - t0
        get_recorder().record("rlhf", "weight_refresh",
                              version=int(version), bytes=nbytes,
                              seconds=dt)
        return {"version": self._version, "bytes": nbytes,
                "seconds": dt}

    def weight_version(self) -> int:
        return self._version

    # -- generation ----------------------------------------------------

    def rollout(self, prompts: np.ndarray, *, group_size: int = 1,
                max_new_tokens: int = 16, temperature: float = 1.0,
                eos_token: Optional[int] = None,
                seed: Optional[int] = None) -> Dict[str, Any]:
        """prompts (n, P) int32 → G completions per prompt.

        Returns fixed-shape buffers (N = n * group_size, S = P +
        max_new_tokens, group-major order): "seqs" (N, S) full
        sequences zero-padded past each completion, "logprobs" (N,
        max_new) sampling-time logp per generated token, "lengths"
        (N,) completion lengths, and the policy "version" sampled
        from."""
        if self._inject_delay_s > 0:
            time.sleep(self._inject_delay_s)
        prompts = np.asarray(prompts, np.int32)
        n, P = prompts.shape
        grouped = np.repeat(prompts, group_size, axis=0)
        N = n * group_size
        S = P + max_new_tokens

        with _tracing.span("rlhf.rollout", prompts=n,
                           group_size=group_size):
            t0 = time.perf_counter()
            reqs = [self.engine.submit(
                grouped[i].tolist(), max_new_tokens=max_new_tokens,
                temperature=temperature, eos_token=eos_token)
                for i in range(N)]
            while any(r.finish_ts == 0.0 for r in reqs):
                self.engine.step()
            gen_s = time.perf_counter() - t0

        seqs = np.zeros((N, S), np.int32)
        seqs[:, :P] = grouped
        logprobs = np.zeros((N, max_new_tokens), np.float32)
        lengths = np.zeros((N,), np.int32)
        for i, r in enumerate(reqs):
            toks = r.tokens
            L = len(toks)
            seqs[i, P:P + L] = toks
            logprobs[i, :L] = r.logprobs
            lengths[i] = L
        tokens_out = int(lengths.sum())
        get_recorder().record("rlhf", "rollout", sequences=N,
                              tokens=tokens_out, seconds=gen_s,
                              version=self._version)
        return {"seqs": seqs, "logprobs": logprobs, "lengths": lengths,
                "prompt_len": P, "tokens": tokens_out,
                "gen_s": gen_s, "version": self._version}

    def stats(self) -> Dict[str, Any]:
        return {"version": self._version,
                "refresh_bytes": self._refresh_bytes,
                "tokens_out": self.engine.tokens_out,
                "prefix_hits": self.engine.prefix_hits}

    def node_id(self) -> str:
        """Scheduling evidence for the cluster tests."""
        from .. import get_runtime_context

        return get_runtime_context().get_node_id()
