"""Mesh-sharded GRPO learner — the RLHF pipeline's learner plane.

`rl/grpo.py` runs GRPO single-chip with its own adam state; this module
is the model-scale variant: the learner takes a `ParallelPlan`, holds a
`train.step.TrainState` initialized directly into its target shardings
(dp/fsdp/tp — same `init_state` path the trainer uses), and runs
advantage normalization + the clipped update inside ONE jitted SPMD
program over the mesh. Rollout data arrives from the serve engine's
logprob capture (`LLMEngine(capture_logprobs=True)`) — the ratio term's
old-policy logps are recorded at sampling time, never recomputed with a
second forward.

Reference capability: RLlib's LearnerGroup sharding a learner across
GPUs (rllib/core/learner/learner_group.py:71); here the "group" is one
SPMD program and XLA inserts the gradient collectives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.transformer import (
    TransformerConfig,
    forward,
    param_logical_axes,
)
from ..parallel.mesh import make_mesh
from ..parallel.plan import ParallelPlan
from ..parallel.sharding import logical_to_sharding, tree_shardings
from ..train.step import TrainState, init_state, make_optimizer


@dataclass(frozen=True)
class GRPOLearnerConfig:
    model: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=4, d_ff=128, max_seq_len=64,
            dtype=jnp.float32, param_dtype=jnp.float32, remat=False))
    group_size: int = 4
    clip_eps: float = 0.2
    kl_coef: float = 0.02
    lr: float = 1e-4
    warmup_steps: int = 5
    total_steps: int = 1000
    grad_clip: float = 1.0
    weight_decay: float = 0.0
    seed: int = 0


def make_grpo_step(cfg: GRPOLearnerConfig, optimizer, *,
                   param_pspecs=None):
    """→ jitted step(state, tokens, old_logp, rewards, comp_mask) →
    (state, metrics), call under `jax.sharding.set_mesh(mesh)`.

    Advantage normalization happens IN-JIT from the raw rewards —
    rewards arrive batch-sharded like everything else and the group
    mean/std reductions run on-device, so the whole iteration is one
    SPMD program. `param_pspecs` pins the updated params' at-rest
    shardings (same ZeRO-drift hazard make_train_step documents).
    """
    mcfg = cfg.model
    G = cfg.group_size

    def _loss(params, tokens, old_logp, advantages, comp_mask):
        logits, _ = forward(mcfg, params, tokens)
        lp_all = jax.nn.log_softmax(
            logits[:, :-1, :].astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(
            lp_all, tokens[:, 1:, None], axis=-1)[..., 0]
        ratio = jnp.exp(lp - old_logp)
        adv = advantages[:, None]
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - cfg.clip_eps,
                           1 + cfg.clip_eps) * adv
        pg = jnp.minimum(unclipped, clipped)
        # k3 KL estimator against the sampling policy.
        log_r = old_logp - lp
        kl = jnp.exp(log_r) - log_r - 1.0
        per_tok = -(pg - cfg.kl_coef * kl) * comp_mask
        denom = jnp.maximum(comp_mask.sum(), 1.0)
        loss = per_tok.sum() / denom
        return loss, {"pg_loss": -(pg * comp_mask).sum() / denom,
                      "kl": (kl * comp_mask).sum() / denom}

    @partial(jax.jit, donate_argnums=(0,))
    def grpo_step(state: TrainState, tokens, old_logp, rewards,
                  comp_mask) -> Tuple[TrainState, Dict[str, jax.Array]]:
        groups = rewards.reshape(-1, G)
        mean = groups.mean(axis=1, keepdims=True)
        std = groups.std(axis=1, keepdims=True) + 1e-6
        advantages = ((groups - mean) / std).reshape(-1)
        (loss, metrics), grads = jax.value_and_grad(
            _loss, has_aux=True)(state.params, tokens, old_logp,
                                 advantages, comp_mask)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if param_pspecs is not None:
            params = jax.lax.with_sharding_constraint(
                params, param_pspecs)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state)
        return new_state, {"loss": loss,
                           "reward_mean": rewards.mean(),
                           "grad_norm": optax.global_norm(grads),
                           **metrics}

    return grpo_step


class GRPOLearner:
    """GRPO update plane over a `ParallelPlan` mesh.

    `update()` takes one rollout batch (host numpy), shards it onto the
    mesh, and runs the jitted sharded step; `param_blocks()` exposes
    the current policy as size-balanced leaf blocks for the relay
    weight refresh; get_state/set_state round-trip through host arrays
    while PRESERVING the live sharding layout on restore.
    """

    def __init__(self, cfg: GRPOLearnerConfig,
                 plan: Optional[ParallelPlan] = None, *, devices=None):
        self.cfg = cfg
        self.plan = plan or ParallelPlan()
        self.mesh = make_mesh(self.plan, devices=devices)
        self.optimizer = make_optimizer(
            cfg.lr, warmup_steps=cfg.warmup_steps,
            total_steps=cfg.total_steps, weight_decay=cfg.weight_decay,
            grad_clip=cfg.grad_clip)
        self.state = init_state(cfg.model, self.mesh, self.optimizer,
                                seed=cfg.seed)
        p_pspecs = jax.tree.map(
            lambda s: s.spec,
            tree_shardings(param_logical_axes(cfg.model), self.mesh))
        self._step = make_grpo_step(cfg, self.optimizer,
                                    param_pspecs=p_pspecs)
        # Leaf order is the weight-refresh wire contract: param_blocks
        # ships (leaf index, array) pairs and the rollout side
        # reassembles against its own flatten of the same model config.
        self._treedef = jax.tree.structure(self.state.params)

    @property
    def step_count(self) -> int:
        return int(jax.device_get(self.state.step))

    # -- update -------------------------------------------------------

    def _place(self, arr: np.ndarray, axes) -> jax.Array:
        return jax.device_put(
            jnp.asarray(arr), logical_to_sharding(axes, self.mesh))

    def update(self, tokens: np.ndarray, old_logp: np.ndarray,
               rewards: np.ndarray,
               comp_mask: np.ndarray) -> Dict[str, float]:
        """One GRPO update from a rollout batch.

        tokens (N, S) int32 full sequences (prompt + completion);
        old_logp (N, S-1) f32 sampling-time logp of tokens[:, 1:]
        (zeros where comp_mask is zero); rewards (N,) raw sequence
        rewards, N = num_groups * group_size ordered group-major;
        comp_mask (N, S-1) f32 completion mask over the shifted axis.
        """
        N = tokens.shape[0]
        if N % self.cfg.group_size:
            raise ValueError(
                f"batch of {N} sequences is not a multiple of "
                f"group_size={self.cfg.group_size}")
        with jax.sharding.set_mesh(self.mesh):
            self.state, metrics = self._step(
                self.state,
                self._place(np.asarray(tokens, np.int32),
                            ("batch", "seq")),
                self._place(np.asarray(old_logp, np.float32),
                            ("batch", "seq")),
                self._place(np.asarray(rewards, np.float32),
                            ("batch",)),
                self._place(np.asarray(comp_mask, np.float32),
                            ("batch", "seq")))
        return {k: float(v) for k, v in metrics.items()}

    # -- weight publication -------------------------------------------

    def param_blocks(self, num_blocks: int = 8):
        """Current policy as `num_blocks` contiguous, byte-balanced
        blocks of (leaf index, host array) pairs — the unit the
        pipeline `put()`s so the relay broadcast pipelines block-sized
        transfers instead of one monolithic object. Sharded leaves
        gather to host here (the producer pays one device→host copy
        per refresh; the object plane owns all further fan-out)."""
        leaves = jax.tree.leaves(self.state.params)
        host = jax.device_get(leaves)
        sizes = [x.nbytes for x in host]
        total = max(sum(sizes), 1)
        num_blocks = max(1, min(num_blocks, len(host)))
        per_block = total / num_blocks
        blocks, cur, acc = [], [], 0
        for i, x in enumerate(host):
            cur.append((i, np.asarray(x)))
            acc += sizes[i]
            if acc >= per_block * (len(blocks) + 1) \
                    and len(blocks) < num_blocks - 1:
                blocks.append(cur)
                cur = []
        if cur:
            blocks.append(cur)
        return blocks

    def params_host(self):
        """Full policy pytree on host (tiny-model tests/checkpoints)."""
        return jax.device_get(self.state.params)

    # -- state round-trip ---------------------------------------------

    def get_state(self) -> Dict[str, Any]:
        return {"step": int(jax.device_get(self.state.step)),
                "params": jax.device_get(self.state.params),
                "opt_state": jax.device_get(self.state.opt_state)}

    def set_state(self, state: Dict[str, Any]) -> None:
        """Restore from host arrays, re-placing every leaf into the
        sharding the LIVE state uses — a restored learner must hold
        the same dp/fsdp layout it trains with, not silently-replicated
        host uploads (that would double memory under fsdp and recompile
        the step)."""
        live = (self.state.params, self.state.opt_state)
        shardings = jax.tree.map(lambda x: x.sharding, live)
        # Checkpoint IO rewrites containers (optax namedtuples come
        # back as dicts, EmptyState as None) — rebuild against the
        # live treedef by leaf order before placing.
        restored = jax.tree.unflatten(
            jax.tree.structure(live),
            jax.tree.leaves((state["params"], state["opt_state"])))
        params, opt_state = jax.device_put(restored, shardings)
        self.state = TrainState(
            step=jnp.asarray(int(state["step"]), jnp.int32),
            params=params, opt_state=opt_state)


def aot_compile_grpo_step(cfg: GRPOLearnerConfig, plan: ParallelPlan,
                          *, batch: int, seq: int, devices) -> None:
    """XLA-compile the sharded GRPO update from abstract inputs — the
    8B dryrun path: proves the learner's shardings/collectives/memory
    plan at north-star scale without materializing the weights."""
    import jax.tree_util as jtu

    from ..models.transformer import init_params

    mesh = make_mesh(plan, devices=devices)
    optimizer = make_optimizer(
        cfg.lr, warmup_steps=cfg.warmup_steps,
        total_steps=cfg.total_steps, weight_decay=cfg.weight_decay,
        grad_clip=cfg.grad_clip)
    with jax.sharding.set_mesh(mesh):
        p_shardings = tree_shardings(param_logical_axes(cfg.model),
                                     mesh)
        p_struct = jtu.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            jax.eval_shape(lambda k: init_params(cfg.model, k),
                           jax.random.key(0)),
            p_shardings)
        state = TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            params=p_struct,
            opt_state=jax.eval_shape(optimizer.init, p_struct))
        bsh = logical_to_sharding(("batch", "seq"), mesh)
        rsh = logical_to_sharding(("batch",), mesh)
        tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                   sharding=bsh)
        lp = jax.ShapeDtypeStruct((batch, seq - 1), jnp.float32,
                                  sharding=bsh)
        rew = jax.ShapeDtypeStruct((batch,), jnp.float32, sharding=rsh)
        msk = jax.ShapeDtypeStruct((batch, seq - 1), jnp.float32,
                                   sharding=bsh)
        p_pspecs = jtu.tree_map(lambda s: s.spec, p_shardings)
        make_grpo_step(cfg, optimizer, param_pspecs=p_pspecs).lower(
            state, tok, lp, rew, msk).compile()
