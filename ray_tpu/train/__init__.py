from .step import TrainState, init_state, make_optimizer, make_train_step

__all__ = ["TrainState", "init_state", "make_optimizer", "make_train_step"]
