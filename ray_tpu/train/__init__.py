from .checkpoint import Checkpoint, CheckpointManager, load_pytree, save_pytree
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .session import (get_checkpoint, get_context, get_dataset_shard,
                      get_mesh, report)
from .step import TrainState, init_state, make_optimizer, make_train_step
from .trainer import Result, TpuTrainer

__all__ = [
    "TpuTrainer", "TorchTrainer", "TensorflowTrainer",
    "TransformersTrainer", "XGBoostTrainer", "LightGBMTrainer",
    "GBDTTrainer", "HorovodTrainer", "HorovodConfig", "Result",
    "ZeROTranslation", "translate_deepspeed_config", "init_zero_state",
    "zero_param_rules", "make_zero_train_step",
    # NOTE: the Lightning helpers (RayDDPStrategy & co., .lightning) are
    # reachable via attribute access but deliberately NOT in __all__ —
    # they raise ImportError without pytorch-lightning installed, which
    # would break `import *` in this image.
    "ScalingConfig", "RunConfig", "FailureConfig",
    "CheckpointConfig", "Checkpoint", "CheckpointManager", "save_pytree",
    "load_pytree", "report", "get_checkpoint", "get_context",
    "get_dataset_shard", "get_mesh",
    "TrainState", "init_state", "make_optimizer", "make_train_step",
]


def __getattr__(name):
    # TorchTrainer imports torch, TransformersTrainer also transformers
    # (heavy) — load lazily.
    if name == "TorchTrainer":
        from .torch import TorchTrainer

        return TorchTrainer
    if name == "TensorflowTrainer":
        from .tensorflow import TensorflowTrainer

        return TensorflowTrainer
    if name == "TransformersTrainer":
        from .huggingface import TransformersTrainer

        return TransformersTrainer
    if name in ("XGBoostTrainer", "LightGBMTrainer", "GBDTTrainer"):
        from . import gbdt

        return getattr(gbdt, name)
    if name in ("HorovodTrainer", "HorovodConfig"):
        from . import horovod

        return getattr(horovod, name)
    if name in ("ZeROTranslation", "translate_deepspeed_config",
                "init_zero_state", "zero_param_rules",
                "make_zero_train_step"):
        from . import zero

        return getattr(zero, name)
    if name in ("RayDDPStrategy", "RayLightningEnvironment",
                "RayTrainReportCallback", "prepare_trainer"):
        from . import lightning

        return getattr(lightning, name)
    raise AttributeError(name)
