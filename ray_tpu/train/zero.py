"""ZeRO-style optimizer/parameter sharding + DeepSpeed-config translation.

Capability-equivalent to the reference's DeepSpeed integrations
(reference: python/ray/train/lightning/_lightning_utils.py
RayDeepSpeedStrategy, the deepspeed train loops in
doc/source/train/deepspeed.rst, and the accelerate integration's
deepspeed_plugin in python/ray/train/huggingface/accelerate/) —
re-designed TPU-native: there is no DeepSpeed runtime to wrap, because
on XLA the ZeRO stages are *sharding declarations*:

- **stage 0**  — pure data parallel: params + optimizer replicated,
  gradients psum'd (plan ``dp=n``).
- **stage 1/2** — optimizer-state sharding: params stay replicated over
  the ``fsdp`` mesh axis (which still shards the batch — it acts as a
  data axis), while Adam's m/v shard over ``fsdp``; XLA reduce-scatters
  gradients into the shard each device owns and all-gathers updated
  params at apply time. (Stages 1 and 2 differ only in torch-runtime
  gradient bucketing mechanics, which have no XLA analog — both map to
  the same sharding here.)
- **stage 3**  — parameter + optimizer sharding over ``fsdp``: the
  framework's existing FSDP path (``parallel/sharding.py`` rules,
  ``embed -> fsdp``), XLA all-gathering params per layer.

``translate_deepspeed_config`` maps a DeepSpeed JSON config (the file
users already have) onto a ParallelPlan + optimizer + batch schedule so
a reference user's ds_config carries over unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.transformer import (
    TransformerConfig,
    init_params,
    param_logical_axes,
)
from ..parallel.plan import ParallelPlan
from ..parallel.sharding import (
    DEFAULT_RULES,
    Rules,
    logical_to_sharding,
    tree_shardings,
)
from .step import TrainState, make_optimizer

__all__ = [
    "ZeROTranslation",
    "translate_deepspeed_config",
    "zero_param_rules",
    "init_zero_state",
    "make_zero_train_step",
]


def zero_param_rules(stage: int) -> Rules:
    """Sharding rules for PARAMETERS at a given ZeRO stage. Stage < 3
    keeps params replicated across the fsdp axis (only optimizer state
    shards); stage 3 is the default rule table (params shard too)."""
    if stage >= 3:
        return DEFAULT_RULES
    return tuple(("embed", None) if name == "embed" else (name, axes)
                 for name, axes in DEFAULT_RULES)


def init_zero_state(cfg: TransformerConfig, mesh, optimizer,
                    *, stage: int, seed: int = 0) -> TrainState:
    """``init_state`` with ZeRO-stage-aware shardings: params follow
    ``zero_param_rules(stage)``, optimizer state ALWAYS follows the
    default rules (m/v shard over fsdp — the whole point of ZeRO-1/2).
    The returned state drops into the unmodified ``make_train_step``:
    the stage lives entirely in the state's shardings, and GSPMD
    propagates them through the update math (reduce-scatter grads,
    shard-local Adam, all-gather at apply)."""
    p_rules = zero_param_rules(stage)
    axes = param_logical_axes(cfg)
    p_shardings = tree_shardings(axes, mesh, p_rules)

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(key):
        return init_params(cfg, key)

    with jax.sharding.set_mesh(mesh):
        params = _init(jax.random.key(seed))
        # Optimizer-state shardings: param-like leaves (mu/nu) take the
        # DEFAULT rules; scalar bookkeeping (count) is replicated.
        opt_shardings = optax.tree_map_params(
            optimizer,
            lambda _, ax: logical_to_sharding(ax, mesh),
            jax.eval_shape(optimizer.init, params),
            axes,
            transform_non_params=lambda _: NamedSharding(mesh, P()))
        opt_state = jax.jit(
            optimizer.init, out_shardings=opt_shardings)(params)
        step = jnp.zeros((), jnp.int32)
    return TrainState(step=step, params=params, opt_state=opt_state)


def make_zero_train_step(cfg: TransformerConfig, optimizer, mesh,
                         *, stage: int, loss=None):
    """``make_train_step`` with the stage's param shardings pinned on the
    OUTPUT. Without the pin, GSPMD keeps stage-1/2 params in the
    fsdp-sharded layout the update math used — silently drifting the
    state to stage-3 sharding and forcing a recompile on the next call."""
    from ..parallel.sharding import logical_to_mesh_axes
    from .step import make_train_step

    rules = zero_param_rules(stage)
    pspecs = jax.tree.map(
        lambda ax: logical_to_mesh_axes(ax, rules, mesh),
        param_logical_axes(cfg),
        is_leaf=lambda x: x is None or (
            isinstance(x, tuple)
            and all(isinstance(e, (str, type(None))) for e in x)))
    return make_train_step(cfg, optimizer, loss=loss, param_pspecs=pspecs)


# ---------------------------------------------------------------------------
# DeepSpeed config translation
# ---------------------------------------------------------------------------

@dataclass
class ZeROTranslation:
    """A DeepSpeed JSON config mapped onto this framework's terms."""

    stage: int
    plan: ParallelPlan
    micro_batch_per_device: int
    gradient_accumulation_steps: int
    global_batch: int
    dtype: Any                      # jnp.bfloat16 / jnp.float32
    grad_clip: float
    optimizer_kwargs: Dict[str, Any] = field(default_factory=dict)
    unsupported: Dict[str, Any] = field(default_factory=dict)

    def make_optimizer(self, **overrides) -> optax.GradientTransformation:
        kw = {**self.optimizer_kwargs, "grad_clip": self.grad_clip,
              **overrides}
        return make_optimizer(**kw)


_AUTO = "auto"


def _resolve(v, default):
    return default if v in (None, _AUTO) else v


def translate_deepspeed_config(ds_config: Dict[str, Any],
                               n_devices: int) -> ZeROTranslation:
    """Map a DeepSpeed JSON config dict onto (ParallelPlan, optimizer,
    batch schedule) — capability of the reference's deepspeed plugin
    surface: the same ds_config keys users pass to
    TorchTrainer+deepspeed / RayDeepSpeedStrategy
    (train/lightning/_lightning_utils.py) drive the TPU-native stages.

    Enforces DeepSpeed's own batch-size invariant:
    train_batch_size == micro_batch_per_gpu * grad_accum * n_devices.
    Keys with no TPU analog (offload, overlap_comm, bucket sizes, fused
    kernels) are collected in ``unsupported`` rather than silently
    dropped."""
    ds = dict(ds_config or {})
    zero = dict(ds.pop("zero_optimization", {}) or {})
    stage = int(_resolve(zero.pop("stage", 0), 0))
    if stage not in (0, 1, 2, 3):
        raise ValueError(f"zero_optimization.stage must be 0-3, got {stage}")

    micro = ds.pop("train_micro_batch_size_per_gpu", None)
    accum = ds.pop("gradient_accumulation_steps", None)
    global_b = ds.pop("train_batch_size", None)
    micro = _resolve(micro, None)
    accum = _resolve(accum, None)
    global_b = _resolve(global_b, None)
    # DeepSpeed derivation rules: any two determine the third.
    if global_b is None:
        micro = micro or 1
        accum = accum or 1
        global_b = micro * accum * n_devices
    elif micro is None:
        accum = accum or 1
        if global_b % (accum * n_devices):
            raise ValueError(
                f"train_batch_size {global_b} not divisible by "
                f"gradient_accumulation_steps*n_devices "
                f"({accum}*{n_devices})")
        micro = global_b // (accum * n_devices)
    elif accum is None:
        if global_b % (micro * n_devices):
            raise ValueError(
                f"train_batch_size {global_b} not divisible by "
                f"micro*n_devices ({micro}*{n_devices})")
        accum = global_b // (micro * n_devices)
    if global_b != micro * accum * n_devices:
        raise ValueError(
            f"inconsistent batch config: train_batch_size {global_b} != "
            f"micro {micro} * accum {accum} * n_devices {n_devices}")

    bf16 = bool((ds.pop("bf16", {}) or {}).get("enabled", False))
    fp16 = bool((ds.pop("fp16", {}) or {}).get("enabled", False))
    # TPU has no fp16 ALU advantage; fp16 configs run as bf16 (wider
    # exponent, no loss-scaling needed — strictly safer numerics).
    dtype = jnp.bfloat16 if (bf16 or fp16) else jnp.float32

    grad_clip = float(_resolve(ds.pop("gradient_clipping", None), 1.0))

    opt = dict(ds.pop("optimizer", {}) or {})
    opt_kwargs: Dict[str, Any] = {}
    if opt:
        typ = str(opt.get("type", "AdamW")).lower()
        if typ not in ("adam", "adamw"):
            raise ValueError(
                f"optimizer.type {opt.get('type')!r} has no native "
                "analog; supported: Adam/AdamW")
        p = dict(opt.get("params", {}) or {})
        if "lr" in p and p["lr"] != _AUTO:
            opt_kwargs["lr"] = float(p["lr"])
        betas = p.get("betas")
        if betas and betas != _AUTO:
            opt_kwargs["b1"], opt_kwargs["b2"] = (float(betas[0]),
                                                  float(betas[1]))
        if "weight_decay" in p and p["weight_decay"] != _AUTO:
            opt_kwargs["weight_decay"] = float(p["weight_decay"])

    sched = dict(ds.pop("scheduler", {}) or {})
    sched_unsupported = None
    if sched:
        sp = dict(sched.get("params", {}) or {})
        if "warmup_num_steps" in sp and sp["warmup_num_steps"] != _AUTO:
            opt_kwargs["warmup_steps"] = int(sp["warmup_num_steps"])
        if "total_num_steps" in sp and sp["total_num_steps"] != _AUTO:
            opt_kwargs["total_steps"] = int(sp["total_num_steps"])
        # Only WarmupLR/WarmupDecayLR map onto the native warmup-cosine
        # schedule; any other scheduler type is replaced by it — record
        # the substitution (same 'recorded, not dropped' policy as the
        # other no-analog keys).
        styp = str(sched.get("type", ""))
        if styp and styp not in ("WarmupLR", "WarmupDecayLR"):
            sched_unsupported = {
                "type": styp,
                "replaced_with": "native warmup-cosine"}

    # Everything else (offload_param, offload_optimizer, overlap_comm,
    # allgather_bucket_size, aio, ...) has no XLA analog: XLA manages
    # HBM and overlaps collectives itself. Recorded, not dropped.
    unsupported = {}
    if sched_unsupported is not None:
        unsupported["scheduler"] = sched_unsupported
    if zero:
        unsupported["zero_optimization"] = zero
    unsupported.update({k: ds[k] for k in list(ds)})

    plan = (ParallelPlan(dp=n_devices) if stage == 0
            else ParallelPlan(fsdp=n_devices))
    return ZeROTranslation(
        stage=stage, plan=plan, micro_batch_per_device=int(micro),
        gradient_accumulation_steps=int(accum), global_batch=int(global_b),
        dtype=dtype, grad_clip=grad_clip, optimizer_kwargs=opt_kwargs,
        unsupported=unsupported)
