"""Shared training configs.

Capability-equivalent to the reference's AIR configs
(reference: python/ray/air/config.py — ScalingConfig :101,
FailureConfig :377, CheckpointConfig :427, RunConfig :576), extended
TPU-first: ScalingConfig carries a ParallelPlan and slice topology rather
than GPU counts.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..parallel.plan import ParallelPlan


@dataclass
class ScalingConfig:
    """How many workers and how the model is parallelized.

    num_workers   — SPMD worker processes (hosts on a pod; actors locally)
    tpus_per_worker — chips each worker drives (0 = CPU worker)
    plan          — in-framework parallelism declaration (dp/fsdp/tp/sp/ep);
                    replaces the reference's use_gpu/NCCL wiring
    slice_id      — gang-schedule all workers onto one ICI slice
    multihost     — rendezvous jax.distributed across the worker gang
                    before the loop runs: every worker's jax.devices()
                    then spans all workers' chips, and the SAME
                    pjit/mesh code runs pod-wide (reference capability:
                    train/torch/config.py:62 _setup_torch_process_group
                    — a rank-0 store every worker joins; here the
                    coordinator address travels through the control
                    plane's KV).
    """

    num_workers: int = 1
    tpus_per_worker: float = 0
    cpus_per_worker: float = 1
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    plan: Optional[ParallelPlan] = None
    slice_id: Optional[str] = None
    placement_strategy: str = "PACK"
    multihost: bool = False

    def worker_resources(self) -> Dict[str, float]:
        r = {"CPU": self.cpus_per_worker}
        if self.tpus_per_worker:
            r["TPU"] = self.tpus_per_worker
        r.update(self.resources_per_worker)
        return r


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)

    def resolve_storage(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        name = self.name or "run"
        if "://" in base:
            # Storage URL (cp://host:port/prefix, mem://bucket/...):
            # checkpoints persist through the external-storage plane
            # and survive the writing host.
            return base.rstrip("/") + "/" + name
        return os.path.join(base, name)
