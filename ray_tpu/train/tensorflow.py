"""TensorflowTrainer — distributed TF/Keras training over worker
processes.

Capability-equivalent of the reference's TensorFlow Train path
(reference: python/ray/train/tensorflow/tensorflow_trainer.py;
tensorflow/config.py _setup_tensorflow_environment — each worker gets a
TF_CONFIG env describing the whole cluster so
MultiWorkerMirroredStrategy can rendezvous; train_loop_utils.py
prepare_dataset_shard). Same worker-group shape as TorchTrainer: one OS
process per rank (TF's collective rendezvous binds a port per worker),
TF_CONFIG assembled from driver-assigned localhost ports, user loop
runs under the strategy and streams ray_tpu.train.report() back.

On this framework TF runs CPU (the TPU compute path is jax); the
capability carried over is the reference's TF_CONFIG rendezvous +
MultiWorkerMirroredStrategy data parallelism for TF workloads.
"""

from __future__ import annotations

import inspect
import json
import socket
from typing import Any, Callable, Dict, Optional

from .config import RunConfig, ScalingConfig
from .trainer import ProcessPlaneTrainerMixin, Result, TpuTrainer


class TensorflowConfig:
    """(reference: train/tensorflow/config.py TensorflowConfig).

    Deliberately empty: MWMS exposes no rendezvous-timeout knob to
    thread through (unlike torch's init_process_group timeout) — an
    accepted-but-unenforced option here would be a silent no-op."""


def _free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _make_tf_loop(user_fn: Callable, workers: list) -> Callable:
    """Wrap the user loop with TF_CONFIG setup (reference:
    _setup_tensorflow_environment: TF_CONFIG = {cluster, task})."""
    takes_config = len(inspect.signature(user_fn).parameters) >= 1

    def loop(config: Optional[Dict[str, Any]] = None) -> None:
        import os

        from .session import get_context

        ctx = get_context()
        os.environ["TF_CONFIG"] = json.dumps({
            "cluster": {"worker": workers},
            "task": {"type": "worker", "index": ctx.get_world_rank()},
        })
        try:
            if takes_config and config is not None:
                user_fn(config)
            else:
                user_fn()
        finally:
            os.environ.pop("TF_CONFIG", None)

    return loop


class TensorflowTrainer(ProcessPlaneTrainerMixin, TpuTrainer):
    """TensorflowTrainer(train_loop_per_worker, scaling_config=
    ScalingConfig(num_workers=N)).fit() — the reference surface.

    Inside the loop, build the model under
    ``tf.distribute.MultiWorkerMirroredStrategy()`` (TF reads the
    TF_CONFIG this trainer set). Requires the out-of-process execution
    plane: ``ray_tpu.init(num_worker_procs=N)``. Each fit attempt's
    ranks are FRESH dedicated processes (see ProcessPlaneTrainerMixin)
    — TF has no in-process collective teardown, so persistent-process
    reuse could never re-rendezvous."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 tensorflow_config: Optional[TensorflowConfig] = None):
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets)
        self.tensorflow_config = tensorflow_config or TensorflowConfig()
        self._user_loop = train_loop_per_worker
        self._init_process_plane()

    def fit(self) -> Result:
        self._require_worker_procs("TensorflowTrainer")
        return super().fit()

    def _fit_once(self, manager) -> Result:
        # Fresh cluster spec per attempt (ports could be dead after a
        # FailureConfig retry).
        n = self.scaling_config.num_workers
        workers = [f"127.0.0.1:{p}" for p in _free_ports(n)]
        self.train_loop = _make_tf_loop(self._user_loop, workers)
        return super()._fit_once(manager)


def prepare_dataset_shard(dataset):
    """Disable TF's automatic data sharding for a dataset the caller
    already sharded per worker (reference:
    train/tensorflow/train_loop_utils.py prepare_dataset_shard)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = \
        tf.data.experimental.AutoShardPolicy.OFF
    return dataset.with_options(options)
