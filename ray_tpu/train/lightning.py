"""PyTorch Lightning integration (gated on the package being installed).

Capability-equivalent to the reference's Lightning utilities
(reference: python/ray/train/lightning/_lightning_utils.py —
RayDDPStrategy :87, RayLightningEnvironment :132, RayTrainReportCallback
:186, prepare_trainer :238): Lightning runs INSIDE a TorchTrainer worker
loop; these helpers make a ``pl.Trainer`` cooperate with the already-
initialized torch process group and stream report()/checkpoints back.

This image does not ship pytorch-lightning, so every entry point raises
a clear ImportError until the package is installed (the classes are
built lazily on first attribute access — they need Lightning base
classes to exist). The distributed substrate they attach to
(TorchTrainer's per-process gloo rendezvous, train/torch.py) is fully
implemented and tested without Lightning.
"""

from __future__ import annotations

from typing import Any

_PL_ERROR = (
    "pytorch-lightning is not installed in this environment. "
    "LightningTrainer-style training runs as: TorchTrainer(loop) where "
    "the loop builds a pl.Trainer with RayDDPStrategy + "
    "RayLightningEnvironment + RayTrainReportCallback (this module), "
    "mirroring the reference's train.lightning utilities. Install "
    "pytorch-lightning (or lightning) to use it; for native training "
    "use TpuTrainer, for plain torch use TorchTrainer."
)

_LAZY = ("RayDDPStrategy", "RayLightningEnvironment",
         "RayTrainReportCallback", "prepare_trainer")

__all__ = list(_LAZY)


def _import_pl():
    try:
        import pytorch_lightning as pl  # noqa: F401

        return pl
    except ImportError:
        try:
            from lightning import pytorch as pl  # noqa: F401

            return pl
        except ImportError:
            raise ImportError(_PL_ERROR) from None


def _build(pl) -> dict:
    import ray_tpu.train as train

    class RayLightningEnvironment(pl.plugins.environments.LightningEnvironment):
        """Rank/world topology from the train session (reference:
        _lightning_utils.py:132)."""

        @property
        def creates_processes_externally(self) -> bool:
            # The TorchTrainer worker IS the rank process; Lightning must
            # never fork its own local ranks (reference:
            # _lightning_utils.py RayLightningEnvironment pins this).
            return True

        def world_size(self) -> int:
            return train.get_context().get_world_size()

        def global_rank(self) -> int:
            return train.get_context().get_world_rank()

        def local_rank(self) -> int:
            return train.get_context().get_world_rank()

        def node_rank(self) -> int:
            return 0

        def set_world_size(self, size: int) -> None:
            pass

        def set_global_rank(self, rank: int) -> None:
            pass

    class RayDDPStrategy(pl.strategies.DDPStrategy):
        """DDP over the process group TorchTrainer already initialized
        (reference: _lightning_utils.py:87)."""

        @property
        def root_device(self):
            import torch

            return torch.device("cpu")

        @property
        def distributed_sampler_kwargs(self) -> dict:
            ctx = train.get_context()
            return dict(num_replicas=ctx.get_world_size(),
                        rank=ctx.get_world_rank())

    class RayTrainReportCallback(pl.callbacks.Callback):
        """Streams metrics (and rank-0 checkpoints) to
        ray_tpu.train.report at each epoch end (reference:
        _lightning_utils.py:186)."""

        def on_train_epoch_end(self, trainer, pl_module) -> None:
            metrics = {k: (v.item() if hasattr(v, "item") else v)
                       for k, v in trainer.callback_metrics.items()}
            metrics["epoch"] = trainer.current_epoch
            metrics["step"] = trainer.global_step
            ckpt = None
            if train.get_context().get_world_rank() == 0:
                import os
                import tempfile

                d = tempfile.mkdtemp(prefix="ray_tpu_pl_")
                trainer.save_checkpoint(os.path.join(d, "checkpoint.ckpt"))
                ckpt = train.Checkpoint(d, _ephemeral=True)
            train.report(metrics, checkpoint=ckpt)

    def prepare_trainer(trainer):
        """Validate a pl.Trainer is wired for this runtime (reference:
        prepare_trainer :238)."""
        if not isinstance(trainer.strategy, RayDDPStrategy):
            raise RuntimeError(
                "pl.Trainer must use strategy=RayDDPStrategy() inside a "
                "TorchTrainer worker loop")
        return trainer

    return {
        "RayLightningEnvironment": RayLightningEnvironment,
        "RayDDPStrategy": RayDDPStrategy,
        "RayTrainReportCallback": RayTrainReportCallback,
        "prepare_trainer": prepare_trainer,
    }


_cache: dict = {}


def __getattr__(name: str) -> Any:
    if name in _LAZY:
        if not _cache:
            _cache.update(_build(_import_pl()))
        return _cache[name]
    raise AttributeError(name)
