"""Functional SPMD training step.

TPU-native replacement for the reference's DDP/FSDP wrapping
(reference: python/ray/train/torch/train_loop_utils.py:74 prepare_model —
torch DDP/FSDP over NCCL): here a single jitted step over a Mesh; gradient
reduction, parameter sharding (FSDP) and tensor parallelism all come from
the shardings — XLA inserts psum/all-gather/reduce-scatter over ICI.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ..parallel.mesh import make_mesh
from ..parallel.plan import ParallelPlan
from ..parallel.sharding import logical_to_sharding, tree_shardings


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(lr: float = 3e-4, *, warmup_steps: int = 100,
                   total_steps: int = 10_000, weight_decay: float = 0.1,
                   b1: float = 0.9, b2: float = 0.95,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1), lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def opt_state_shardings(optimizer, params, p_shardings, mesh):
    """Target shardings for optimizer.init's output: leaves that mirror
    a param (adam mu/nu, ...) inherit that param's sharding, scalars
    (schedule/clip counts) replicate. Sharding CANNOT be left to GSPMD
    propagation here — optimizer.init is pure zeros_like with no data
    dependence on the params, so XLA drops the unused sharded inputs
    and the state comes back single-device (un-ZeRO'd, then relaid out
    + recompiled on the first step). Mirroring is keyed by tree-path
    suffix: the mu['layers']['wq'] leaf ends with the params'
    ['layers']['wq'] path; bracketed keys make suffix matches exact."""
    import jax.tree_util as jtu

    replicated = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec())
    p_leaves = jtu.tree_flatten_with_path(p_shardings)[0]
    p_map = sorted(((jtu.keystr(path), sh) for path, sh in p_leaves),
                   key=lambda kv: -len(kv[0]))
    struct = jax.eval_shape(optimizer.init, params)
    flat, treedef = jtu.tree_flatten_with_path(struct)
    out = []
    for path, leaf in flat:
        ks = jtu.keystr(path)
        sh = next((psh for pk, psh in p_map if ks.endswith(pk)),
                  replicated)
        out.append(sh if getattr(leaf, "ndim", 0) else replicated)
    return jtu.tree_unflatten(treedef, out)


def init_state(cfg: TransformerConfig, mesh, optimizer,
               seed: int = 0) -> TrainState:
    """Initialize params directly into their target shardings (no host
    round-trip; each device materializes only its shard)."""
    p_shardings = tree_shardings(param_logical_axes(cfg), mesh)

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(key):
        return init_params(cfg, key)

    with jax.sharding.set_mesh(mesh):
        params = _init(jax.random.key(seed))
        o_shardings = opt_state_shardings(
            optimizer, params, p_shardings, mesh)
        opt_state = jax.jit(
            optimizer.init, out_shardings=o_shardings)(params)
        step = jnp.zeros((), jnp.int32)
    return TrainState(step=step, params=params, opt_state=opt_state)


def make_train_step(cfg: TransformerConfig, optimizer, *, loss=None,
                    param_pspecs=None):
    """Returns step(state, tokens, targets, mask) -> (state, metrics),
    jit-compiled; call under `jax.sharding.set_mesh(mesh)`. `loss`
    overrides the loss closure (signature of loss_fn minus cfg).
    `param_pspecs` (pytree of PartitionSpecs matching params) pins the
    OUTPUT params' shardings — needed when params' at-rest sharding
    differs from what GSPMD would pick for the update math (ZeRO-1/2:
    updates compute fsdp-sharded, params must come back whole, or the
    state silently drifts to stage-3 sharding and recompiles)."""

    def _loss(params, tokens, targets, mask):
        if loss is not None:
            return loss(params, tokens, targets, mask)
        return loss_fn(cfg, params, tokens, targets, mask)

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens, targets, mask
                   ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        grad_fn = jax.value_and_grad(_loss, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, tokens, targets, mask)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if param_pspecs is not None:
            params = jax.lax.with_sharding_constraint(params, param_pspecs)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state)
        metrics = dict(metrics)
        metrics["grad_norm"] = gnorm
        return new_state, metrics

    return train_step


def shard_batch(batch: Dict[str, jax.Array], mesh) -> Dict[str, jax.Array]:
    """Place a host batch onto the mesh with (batch, seq) sharding."""
    sh = logical_to_sharding(("batch", "seq"), mesh)
    return {k: jax.device_put(v, sh) for k, v in batch.items()}


def init_pp_state(cfg: TransformerConfig, mesh, optimizer, *, pp: int,
                  seed: int = 0) -> TrainState:
    """init_state with the layer stack partitioned into pp stages, each
    leaf sharded (stage -> pp mesh axis) at init (no host round-trip)."""
    from ..parallel.pipeline import (
        partition_layer_params,
        pp_param_logical_axes,
    )

    p_shardings = tree_shardings(pp_param_logical_axes(cfg), mesh)

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(key):
        params = init_params(cfg, key)
        params["layers"] = partition_layer_params(params["layers"], pp)
        return params

    with jax.sharding.set_mesh(mesh):
        params = _init(jax.random.key(seed))
        o_shardings = opt_state_shardings(
            optimizer, params, p_shardings, mesh)
        opt_state = jax.jit(
            optimizer.init, out_shardings=o_shardings)(params)
        step = jnp.zeros((), jnp.int32)
    return TrainState(step=step, params=params, opt_state=opt_state)


def make_pp_train_step(cfg: TransformerConfig, optimizer, *, pp: int,
                       num_microbatches: Optional[int] = None,
                       schedule: str = "gpipe"):
    """Pipelined train step, compiled into one jit (parallel/pipeline.py).
    Same signature as make_train_step.

    schedule:
      "gpipe" — forward scan + autodiff backward; residuals for all M
                microbatches live at once (fine for modest M).
      "1f1b"  — interleaved forward/backward with O(pp) in-flight
                microbatches per stage (the schedule that matters at
                real pp depths / large M).
    """
    if schedule == "gpipe":
        from ..parallel.pipeline import pipeline_loss_fn

        def _loss(params, tokens, targets, mask):
            return pipeline_loss_fn(
                cfg, params, tokens, targets, mask,
                pp=pp, num_microbatches=num_microbatches)

        return make_train_step(cfg, optimizer, loss=_loss)
    if schedule != "1f1b":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    from ..parallel.pipeline import pipeline_1f1b_grads

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens, targets, mask
                   ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        grads, metrics = pipeline_1f1b_grads(
            cfg, state.params, tokens, targets, mask,
            pp=pp, num_microbatches=num_microbatches)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state)
        metrics = dict(metrics)
        metrics["grad_norm"] = gnorm
        return new_state, metrics

    return train_step


def make_eval_step(cfg: TransformerConfig):
    @jax.jit
    def eval_step(params, tokens, targets, mask):
        _, metrics = loss_fn(cfg, params, tokens, targets, mask)
        return metrics

    return eval_step
