"""Gradient-boosted decision trees on the distributed trainer plane.

Capability-equivalent to the reference's GBDT trainer family
(reference: python/ray/train/gbdt_trainer.py:76 GBDTTrainer,
train/xgboost/xgboost_trainer.py:11 XGBoostTrainer,
train/lightgbm/lightgbm_trainer.py:11 LightGBMTrainer — data-parallel
boosting where each worker holds a dataset shard and per-iteration
gradient/hessian histograms are allreduced across the gang, the
xgboost-ray/lightgbm-ray "rabit tracker" design), re-designed for this
runtime: the booster is implemented natively (no xgboost/lightgbm C
libraries — none exist in the image), histograms ride the host-side
collective plane (`ray_tpu.util.collective`), and the worker gang is the
same TpuTrainer actor gang every other trainer uses.

The engine is a histogram booster in vectorized numpy:

- features are quantile-binned to <=``max_bins`` bins once up front
  (bin edges agreed across the gang via an allgathered sample);
- each boosting round computes per-(node, feature, bin) gradient and
  hessian histograms with ``np.bincount`` and allreduces ONE array per
  growth step — level-wise growth (XGBoost dialect, ``_grow_depthwise``)
  batches a whole level's child histograms into a single allreduce,
  leaf-wise growth (LightGBM dialect, ``_grow_leafwise``) does one per
  split — both using the histogram-subtraction trick (sibling = parent
  - child) so only the smaller child's histogram crosses the wire;
- split gain, leaf weights, and regularisation follow the standard
  second-order formulation: gain = 1/2 [GL^2/(HL+l) + GR^2/(HR+l)
  - G^2/(H+l)] - gamma, leaf w = -lr * G/(H+l).

Because the reduced histograms are bit-identical on every rank, every
rank grows the same tree deterministically — there is no model
broadcast, exactly like xgboost-ray.
"""

from __future__ import annotations

import math
import os
import pickle
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .checkpoint import Checkpoint
from .config import RunConfig, ScalingConfig
from .trainer import Result, TpuTrainer

__all__ = [
    "Booster",
    "GBDTTrainer",
    "XGBoostTrainer",
    "LightGBMTrainer",
    "train",
]


# ---------------------------------------------------------------------------
# Config + param dialects
# ---------------------------------------------------------------------------

@dataclass
class _BoostConfig:
    objective: str = "regression"        # regression | binary | multiclass
    num_class: int = 1
    learning_rate: float = 0.3
    max_depth: int = 6
    max_leaves: int = 0                  # 0 = bound by depth only
    growth: str = "depthwise"            # depthwise | leafwise
    reg_lambda: float = 1.0
    gamma: float = 0.0                   # min split gain
    min_child_weight: float = 1.0
    subsample: float = 1.0
    colsample: float = 1.0
    max_bins: int = 256
    base_score: float = 0.0
    seed: int = 0
    eval_metric: Optional[str] = None

    def effective_max_leaves(self) -> int:
        by_depth = 1 << min(self.max_depth if self.max_depth > 0 else 31, 31)
        if self.max_leaves and self.max_leaves > 0:
            return min(self.max_leaves, by_depth)
        return by_depth


_XGB_OBJECTIVES = {
    "reg:squarederror": "regression",
    "reg:linear": "regression",
    "binary:logistic": "binary",
    "multi:softmax": "multiclass",
    "multi:softprob": "multiclass",
}

_LGBM_OBJECTIVES = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
}


def _normalize_params(params: Dict[str, Any], dialect: str) -> _BoostConfig:
    """Map an xgboost- or lightgbm-style param dict onto _BoostConfig
    (reference: the params dicts accepted by gbdt_trainer.py:120)."""
    p = dict(params or {})
    cfg = _BoostConfig()

    def pop(*names, default=None):
        for n in names:
            if n in p:
                return p.pop(n)
        return default

    if dialect == "xgboost":
        obj = pop("objective", default="reg:squarederror")
        if obj not in _XGB_OBJECTIVES:
            raise ValueError(f"unsupported xgboost objective {obj!r}; "
                             f"supported: {sorted(_XGB_OBJECTIVES)}")
        cfg.objective = _XGB_OBJECTIVES[obj]
        cfg.learning_rate = float(pop("eta", "learning_rate", default=0.3))
        cfg.max_depth = int(pop("max_depth", default=6))
        if cfg.max_depth <= 0:       # xgboost: 0 = no limit (lossguide)
            cfg.max_depth = 31
        cfg.max_leaves = int(pop("max_leaves", default=0))
        cfg.growth = ("leafwise"
                      if pop("grow_policy", default="depthwise")
                      == "lossguide" else "depthwise")
        cfg.reg_lambda = float(pop("lambda", "reg_lambda", default=1.0))
        cfg.gamma = float(pop("gamma", "min_split_loss", default=0.0))
        cfg.min_child_weight = float(pop("min_child_weight", default=1.0))
        cfg.subsample = float(pop("subsample", default=1.0))
        cfg.colsample = float(pop("colsample_bytree", default=1.0))
        cfg.max_bins = int(pop("max_bin", default=256))
        cfg.base_score = float(pop("base_score", default=0.0))
        cfg.num_class = int(pop("num_class", default=1))
        cfg.seed = int(pop("seed", "random_state", default=0))
        cfg.eval_metric = pop("eval_metric")
    elif dialect == "lightgbm":
        obj = pop("objective", default="regression")
        if obj not in _LGBM_OBJECTIVES:
            raise ValueError(f"unsupported lightgbm objective {obj!r}; "
                             f"supported: {sorted(_LGBM_OBJECTIVES)}")
        cfg.objective = _LGBM_OBJECTIVES[obj]
        cfg.learning_rate = float(pop("learning_rate", "eta", default=0.1))
        cfg.max_depth = int(pop("max_depth", default=-1))
        if cfg.max_depth <= 0:
            cfg.max_depth = 31
        cfg.max_leaves = int(pop("num_leaves", "max_leaves", default=31))
        cfg.growth = "leafwise"
        cfg.reg_lambda = float(pop("lambda_l2", "reg_lambda", default=0.0))
        cfg.gamma = float(pop("min_gain_to_split", "min_split_gain",
                              default=0.0))
        cfg.min_child_weight = float(
            pop("min_sum_hessian_in_leaf", "min_child_weight", default=1e-3))
        cfg.subsample = float(pop("bagging_fraction", "subsample",
                                  default=1.0))
        cfg.colsample = float(pop("feature_fraction", "colsample_bytree",
                                  default=1.0))
        cfg.max_bins = int(pop("max_bin", default=255))
        cfg.num_class = int(pop("num_class", default=1))
        cfg.seed = int(pop("seed", "random_state", default=0))
        cfg.eval_metric = pop("metric", "eval_metric")
    else:
        raise ValueError(f"unknown GBDT param dialect {dialect!r}")

    if isinstance(cfg.eval_metric, (list, tuple)):
        cfg.eval_metric = cfg.eval_metric[0] if cfg.eval_metric else None
    if cfg.eval_metric is not None:
        cfg.eval_metric = _canon_metric(cfg.eval_metric)
    if cfg.objective == "multiclass" and cfg.num_class < 2:
        raise ValueError("multiclass objective needs num_class >= 2")
    if not 2 <= cfg.max_bins <= 256:
        raise ValueError("max_bins must be in [2, 256]")
    # Unknown keys are tolerated (the reference forwards them to the C
    # library; here they have no analog) but recorded for debugging.
    cfg_extra = p
    cfg.extra = cfg_extra  # type: ignore[attr-defined]
    return cfg


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

def _propose_edges(X: np.ndarray, max_bins: int,
                   sample_rows: int = 100_000,
                   seed: int = 0) -> List[np.ndarray]:
    """Per-feature quantile split candidates (<= max_bins-1 edges)."""
    n = X.shape[0]
    if n > sample_rows:
        idx = np.random.default_rng(seed).choice(n, sample_rows,
                                                 replace=False)
        X = X[idx]
    edges = []
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    for f in range(X.shape[1]):
        col = X[:, f]
        col = col[~np.isnan(col)]
        if col.size == 0:
            edges.append(np.zeros(0, dtype=np.float64))
            continue
        e = np.unique(np.quantile(col, qs, method="linear"))
        edges.append(e.astype(np.float64))
    return edges


def _bin_data(X: np.ndarray, edges: Sequence[np.ndarray]) -> np.ndarray:
    """uint8 bins; bin b means x <= edges[b] (last bin = above all edges).
    NaNs map to bin 0 (documented limitation: no learned default
    direction)."""
    n, F = X.shape
    out = np.zeros((n, F), dtype=np.uint8)
    for f in range(F):
        col = np.nan_to_num(X[:, f], nan=-np.inf)
        out[:, f] = np.searchsorted(edges[f], col, side="left")
    return out


# ---------------------------------------------------------------------------
# Trees
# ---------------------------------------------------------------------------

@dataclass
class _Tree:
    """Flat array tree. Internal nodes: feature/threshold/children;
    leaves: value."""
    feature: np.ndarray
    threshold: np.ndarray
    left: np.ndarray
    right: np.ndarray
    value: np.ndarray
    is_leaf: np.ndarray
    gain: np.ndarray

    def predict(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        node = np.zeros(n, dtype=np.int32)
        # Bounded traversal: each iteration advances every non-leaf row
        # one level; tree depth <= number of nodes.
        for _ in range(int(self.feature.shape[0]) + 1):
            live = ~self.is_leaf[node]
            if not live.any():
                break
            idx = np.nonzero(live)[0]
            nd = node[idx]
            f = self.feature[nd]
            x = np.nan_to_num(X[idx, f], nan=-np.inf)
            go_left = x <= self.threshold[nd]
            node[idx] = np.where(go_left, self.left[nd], self.right[nd])
        return self.value[node]

    def num_leaves(self) -> int:
        return int(self.is_leaf.sum())


class _TreeBuilder:
    """Accumulates nodes during growth, emits a _Tree."""

    def __init__(self):
        self.feature: List[int] = []
        self.threshold: List[float] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.value: List[float] = []
        self.is_leaf: List[bool] = []
        self.gain: List[float] = []

    def add(self, *, leaf: bool, feature: int = -1, threshold: float = 0.0,
            value: float = 0.0, gain: float = 0.0) -> int:
        nid = len(self.feature)
        self.feature.append(feature)
        self.threshold.append(threshold)
        self.left.append(-1)
        self.right.append(-1)
        self.value.append(value)
        self.is_leaf.append(leaf)
        self.gain.append(gain)
        return nid

    def link(self, parent: int, left: int, right: int) -> None:
        self.left[parent] = left
        self.right[parent] = right

    def build(self) -> _Tree:
        return _Tree(
            feature=np.asarray(self.feature, dtype=np.int32),
            threshold=np.asarray(self.threshold, dtype=np.float64),
            left=np.asarray(self.left, dtype=np.int32),
            right=np.asarray(self.right, dtype=np.int32),
            value=np.asarray(self.value, dtype=np.float64),
            is_leaf=np.asarray(self.is_leaf, dtype=bool),
            gain=np.asarray(self.gain, dtype=np.float64),
        )


# ---------------------------------------------------------------------------
# Histograms + split search
# ---------------------------------------------------------------------------

def _node_hist(binned: np.ndarray, rows: np.ndarray, grad: np.ndarray,
               hess: np.ndarray, features: np.ndarray,
               n_bins: int) -> np.ndarray:
    """(3, n_features_active, n_bins) grad/hess/count histogram for one
    node. The count channel makes global row counts available to every
    rank, so growth decisions (which child is smaller, min-data checks)
    are functions of REDUCED state only — the property that keeps ranks
    in allreduce lockstep."""
    out = np.zeros((3, features.size, n_bins), dtype=np.float64)
    g = grad[rows]
    h = hess[rows]
    for j, f in enumerate(features):
        b = binned[rows, f]
        out[0, j] = np.bincount(b, weights=g, minlength=n_bins)
        out[1, j] = np.bincount(b, weights=h, minlength=n_bins)
        out[2, j] = np.bincount(b, minlength=n_bins)
    return out


def _best_split(hist: np.ndarray, cfg: _BoostConfig
                ) -> Tuple[float, int, int]:
    """Best (gain, feature_slot, bin) for one node's reduced histogram.
    Split at bin b sends bins <= b left."""
    G = hist[0].sum(axis=1)            # (F,)
    H = hist[1].sum(axis=1)
    GL = np.cumsum(hist[0], axis=1)[:, :-1]   # (F, B-1)
    HL = np.cumsum(hist[1], axis=1)[:, :-1]
    GR = G[:, None] - GL
    HR = H[:, None] - HL
    lam = cfg.reg_lambda
    with np.errstate(divide="ignore", invalid="ignore"):
        parent = (G ** 2) / (H + lam)
        gain = 0.5 * (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
                      - parent[:, None]) - cfg.gamma
    ok = (HL >= cfg.min_child_weight) & (HR >= cfg.min_child_weight)
    gain = np.where(ok, np.nan_to_num(gain, nan=-np.inf), -np.inf)
    flat = int(np.argmax(gain))
    f, b = divmod(flat, gain.shape[1])
    return float(gain[f, b]), f, b


def _leaf_value(G: float, H: float, cfg: _BoostConfig) -> float:
    return float(-cfg.learning_rate * G / (H + cfg.reg_lambda))


class _Comm:
    """Allreduce hook: identity locally, collective-plane in a gang."""

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        return arr


class _CollectiveComm(_Comm):
    def __init__(self, group_name: str):
        self.group = group_name

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        from ..util import collective

        return collective.allreduce(arr, group_name=self.group)


def _grow_tree(binned: np.ndarray, edges: Sequence[np.ndarray],
               grad: np.ndarray, hess: np.ndarray, rows: np.ndarray,
               features: np.ndarray, cfg: _BoostConfig,
               comm: _Comm) -> _Tree:
    """Dispatch on growth policy. Both engines keep ranks in allreduce
    lockstep because every growth decision is a pure function of reduced
    histograms (reference capability: xgboost hist tree_method depthwise
    + lightgbm leaf-wise)."""
    if cfg.growth == "depthwise":
        return _grow_depthwise(binned, edges, grad, hess, rows, features,
                               cfg, comm)
    return _grow_leafwise(binned, edges, grad, hess, rows, features,
                          cfg, comm)


def _split_node(tb: "_TreeBuilder", nid: int, feat: int, thresh: float,
                gain: float, GL: float, HL: float, GR: float, HR: float,
                cfg: _BoostConfig) -> Tuple[int, int]:
    tb.is_leaf[nid] = False
    tb.feature[nid] = feat
    tb.threshold[nid] = thresh
    tb.gain[nid] = gain
    lid = tb.add(leaf=True, value=_leaf_value(GL, HL, cfg))
    rid = tb.add(leaf=True, value=_leaf_value(GR, HR, cfg))
    tb.link(nid, lid, rid)
    return lid, rid


def _grow_depthwise(binned: np.ndarray, edges: Sequence[np.ndarray],
                    grad: np.ndarray, hess: np.ndarray, rows: np.ndarray,
                    features: np.ndarray, cfg: _BoostConfig,
                    comm: _Comm) -> _Tree:
    """Level-order growth (XGBoost's default grow_policy): ALL child
    histograms of a level ride ONE allreduce — comm rounds per tree are
    bounded by max_depth, not leaf count."""
    n_bins = cfg.max_bins
    tb = _TreeBuilder()
    root_hist = comm.allreduce(
        _node_hist(binned, rows, grad, hess, features, n_bins))
    G0 = float(root_hist[0].sum())
    H0 = float(root_hist[1].sum())
    root = tb.add(leaf=True, value=_leaf_value(G0, H0, cfg))
    level = [(root, rows, root_hist, G0, H0)]
    n_leaves = 1
    max_leaves = cfg.effective_max_leaves()

    for _depth in range(cfg.max_depth):
        plans = []          # (nid, rows, hist, G, H, f_slot, b, gain)
        for nid, nrows, hist, G, H in level:
            if n_leaves >= max_leaves:
                break
            gain, f, b = _best_split(hist, cfg)
            if not math.isfinite(gain) or gain <= 0.0:
                continue
            plans.append((nid, nrows, hist, G, H, f, b, gain))
            n_leaves += 1
        if not plans:
            break

        parts = []
        smalls = []
        for nid, nrows, hist, G, H, f, b, gain in plans:
            feat = int(features[f])
            go_left = binned[nrows, feat] <= b
            lrows, rrows = nrows[go_left], nrows[~go_left]
            gl_cnt = float(hist[2, f, :b + 1].sum())
            left_is_small = gl_cnt <= float(hist[2, f].sum()) - gl_cnt
            parts.append((lrows, rrows, left_is_small))
            smalls.append(_node_hist(
                binned, lrows if left_is_small else rrows, grad, hess,
                features, n_bins))
        reduced = comm.allreduce(np.stack(smalls))

        nxt = []
        for (nid, nrows, hist, G, H, f, b, gain), \
                (lrows, rrows, left_is_small), shist in \
                zip(plans, parts, reduced):
            bhist = hist - shist
            lhist, rhist = ((shist, bhist) if left_is_small
                            else (bhist, shist))
            GL = float(lhist[0].sum()); HL = float(lhist[1].sum())
            GR, HR = G - GL, H - HL
            feat = int(features[f])
            thresh = float(edges[feat][b]) if edges[feat].size else 0.0
            lid, rid = _split_node(tb, nid, feat, thresh, gain,
                                   GL, HL, GR, HR, cfg)
            nxt.append((lid, lrows, lhist, GL, HL))
            nxt.append((rid, rrows, rhist, GR, HR))
        level = nxt
    return tb.build()


def _grow_leafwise(binned: np.ndarray, edges: Sequence[np.ndarray],
                   grad: np.ndarray, hess: np.ndarray, rows: np.ndarray,
                   features: np.ndarray, cfg: _BoostConfig,
                   comm: _Comm) -> _Tree:
    """Best-first growth (LightGBM): always split the frontier leaf with
    the highest gain, one allreduce per split."""
    n_bins = cfg.max_bins
    tb = _TreeBuilder()

    root_hist = comm.allreduce(
        _node_hist(binned, rows, grad, hess, features, n_bins))
    G0 = float(root_hist[0].sum())
    H0 = float(root_hist[1].sum())
    root = tb.add(leaf=True, value=_leaf_value(G0, H0, cfg))

    # Frontier entries: (-gain, tiebreak, node_id, depth, rows, hist, G, H,
    #                    feature_slot, bin)
    import heapq

    frontier: list = []
    counter = 0

    def consider(nid: int, depth: int, nrows: np.ndarray,
                 hist: np.ndarray, G: float, H: float) -> None:
        nonlocal counter
        if depth >= cfg.max_depth:
            return
        gain, f, b = _best_split(hist, cfg)
        if not math.isfinite(gain) or gain <= 0.0:
            return
        heapq.heappush(frontier,
                       (-gain, counter, nid, depth, nrows, hist, G, H, f, b))
        counter += 1

    consider(root, 0, rows, root_hist, G0, H0)
    n_leaves = 1
    max_leaves = cfg.effective_max_leaves()

    while frontier and n_leaves < max_leaves:
        (neg_gain, _, nid, depth, nrows, hist, G, H, f, b) = \
            heapq.heappop(frontier)
        feat = int(features[f])
        go_left = binned[nrows, feat] <= b
        lrows = nrows[go_left]
        rrows = nrows[~go_left]
        # Histogram subtraction: allreduce only the smaller child. "Smaller"
        # must be decided from GLOBAL counts (the reduced count channel of
        # the parent histogram at the split feature), not this rank's local
        # shard sizes — a local decision can differ across ranks and desync
        # the allreduce lockstep.
        global_left = float(hist[2, f, :b + 1].sum())
        global_total = float(hist[2, f].sum())
        left_is_small = global_left <= global_total - global_left
        small = lrows if left_is_small else rrows
        small_hist = comm.allreduce(
            _node_hist(binned, small, grad, hess, features, n_bins))
        big_hist = hist - small_hist
        lhist, rhist = ((small_hist, big_hist)
                        if left_is_small else (big_hist, small_hist))
        GL = float(lhist[0].sum()); HL = float(lhist[1].sum())
        GR = G - GL; HR = H - HL

        thresh = float(edges[feat][b]) if edges[feat].size else 0.0
        lid, rid = _split_node(tb, nid, feat, thresh, -neg_gain,
                               GL, HL, GR, HR, cfg)
        n_leaves += 1

        consider(lid, depth + 1, lrows, lhist, GL, HL)
        consider(rid, depth + 1, rrows, rhist, GR, HR)

    # Lockstep teardown: ranks must agree on the number of allreduce
    # rounds. They do — every decision above is a pure function of
    # reduced histograms, which are identical on all ranks.
    return tb.build()


# ---------------------------------------------------------------------------
# Objectives + metrics
# ---------------------------------------------------------------------------

def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _grad_hess(objective: str, margin: np.ndarray, y: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
    if objective == "regression":
        return margin - y, np.ones_like(margin)
    if objective == "binary":
        p = _sigmoid(margin)
        return p - y, np.maximum(p * (1 - p), 1e-16)
    if objective == "multiclass":
        p = _softmax(margin)                      # (n, K)
        onehot = np.zeros_like(p)
        onehot[np.arange(y.shape[0]), y.astype(np.int64)] = 1.0
        return p - onehot, np.maximum(p * (1 - p), 1e-16)
    raise ValueError(f"unknown objective {objective!r}")


def _default_metric(objective: str) -> str:
    return {"regression": "rmse", "binary": "logloss",
            "multiclass": "mlogloss"}[objective]


# Canonical name <- xgboost + lightgbm aliases. Sum-decomposable metrics
# only (shard-local sums allreduce exactly); AUC-class metrics need a
# global sort and are rejected at param-validation time.
_METRIC_ALIASES = {
    "rmse": "rmse", "l2_root": "rmse", "root_mean_squared_error": "rmse",
    "mse": "mse", "l2": "mse", "mean_squared_error": "mse",
    "mae": "mae", "l1": "mae", "mean_absolute_error": "mae",
    "logloss": "logloss", "binary_logloss": "logloss",
    "error": "error", "binary_error": "error",
    "mlogloss": "mlogloss", "multi_logloss": "mlogloss",
    "merror": "merror", "multi_error": "merror",
}


def _canon_metric(name: str) -> str:
    canon = _METRIC_ALIASES.get(str(name).lower())
    if canon is None:
        raise ValueError(
            f"unsupported eval metric {name!r}; supported (incl. aliases): "
            f"{sorted(_METRIC_ALIASES)}")
    return canon


def _metric_stats(metric: str, margin: np.ndarray, y: np.ndarray
                  ) -> np.ndarray:
    """Shard-local [weighted_sum, count]; allreduce-sum then finalize."""
    n = float(y.shape[0])
    if metric in ("rmse", "mse"):
        return np.array([float(np.sum((margin - y) ** 2)), n])
    if metric == "mae":
        return np.array([float(np.sum(np.abs(margin - y))), n])
    if metric == "logloss":
        p = np.clip(_sigmoid(margin), 1e-15, 1 - 1e-15)
        return np.array(
            [float(-np.sum(y * np.log(p) + (1 - y) * np.log(1 - p))), n])
    if metric == "error":
        pred = (_sigmoid(margin) > 0.5).astype(np.float64)
        return np.array([float(np.sum(pred != y)), n])
    if metric == "mlogloss":
        p = np.clip(_softmax(margin), 1e-15, None)
        return np.array(
            [float(-np.sum(np.log(p[np.arange(y.shape[0]),
                                    y.astype(np.int64)]))), n])
    if metric == "merror":
        pred = np.argmax(margin, axis=1)
        return np.array([float(np.sum(pred != y.astype(np.int64))), n])
    raise ValueError(f"unknown eval metric {metric!r}")


def _finalize_metric(metric: str, stats: np.ndarray) -> float:
    s, n = float(stats[0]), max(float(stats[1]), 1.0)
    if metric == "rmse":
        return math.sqrt(s / n)
    return s / n


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------

class Booster:
    """A trained model: config + per-class tree lists
    (reference capability: xgboost.Booster / lightgbm.Booster as held by
    the trainer's checkpoints, train/xgboost/xgboost_checkpoint.py:36)."""

    def __init__(self, cfg: _BoostConfig, n_features: int,
                 feature_names: Optional[List[str]] = None):
        self.cfg = cfg
        self.n_features = n_features
        # Training column order. numpy inputs to predict() must follow it;
        # DataFrame inputs are reordered by name automatically.
        self.feature_names = list(feature_names) if feature_names else None
        self.K = cfg.num_class if cfg.objective == "multiclass" else 1
        self.trees: List[List[_Tree]] = []     # [round][class]
        self.best_iteration: Optional[int] = None

    def _coerce(self, X) -> np.ndarray:
        if hasattr(X, "columns"):  # pandas DataFrame: align by name
            if self.feature_names is not None:
                missing = [c for c in self.feature_names
                           if c not in X.columns]
                if missing:
                    raise KeyError(
                        f"DataFrame is missing training columns {missing}")
                X = X[self.feature_names]
            X = X.to_numpy()
        X = np.asarray(X, dtype=np.float64)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"expected (n, {self.n_features}) features, got {X.shape}")
        return X

    # -- inference ---------------------------------------------------------
    def margin(self, X: np.ndarray,
               num_rounds: Optional[int] = None) -> np.ndarray:
        X = self._coerce(X)
        # When early stopping fired, inference defaults to the best
        # iteration — xgboost/lightgbm semantics — not the overfit tail;
        # pass num_rounds=len(trees) explicitly to use every round.
        if num_rounds is None and self.best_iteration is not None:
            num_rounds = self.best_iteration + 1
        rounds = (self.trees[:num_rounds] if num_rounds is not None
                  else self.trees)
        out = np.full((X.shape[0], self.K), self.cfg.base_score,
                      dtype=np.float64)
        for per_class in rounds:
            for k, tree in enumerate(per_class):
                out[:, k] += tree.predict(X)
        return out if self.K > 1 else out[:, 0]

    def predict(self, X: np.ndarray,
                num_rounds: Optional[int] = None) -> np.ndarray:
        m = self.margin(X, num_rounds)
        if self.cfg.objective == "binary":
            return _sigmoid(m)
        if self.cfg.objective == "multiclass":
            return np.argmax(m, axis=1)
        return m

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        m = self.margin(X)
        if self.cfg.objective == "binary":
            p = _sigmoid(m)
            return np.stack([1 - p, p], axis=1)
        if self.cfg.objective == "multiclass":
            return _softmax(m)
        raise ValueError("predict_proba needs a classification objective")

    @property
    def num_boosted_rounds(self) -> int:
        return len(self.trees)

    def feature_importances(self, kind: str = "gain") -> np.ndarray:
        out = np.zeros(self.n_features, dtype=np.float64)
        for per_class in self.trees:
            for tree in per_class:
                internal = ~tree.is_leaf
                if kind == "gain":
                    np.add.at(out, tree.feature[internal],
                              tree.gain[internal])
                else:  # split count
                    np.add.at(out, tree.feature[internal], 1.0)
        return out

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @classmethod
    def load(cls, path: str) -> "Booster":
        with open(path, "rb") as f:
            out = pickle.load(f)
        if not isinstance(out, cls):
            raise TypeError(f"{path} does not contain a Booster")
        return out

    def to_checkpoint(self) -> Checkpoint:
        import tempfile

        d = tempfile.mkdtemp(prefix="ray_tpu_gbdt_")
        self.save(os.path.join(d, "booster.pkl"))
        return Checkpoint(d, _ephemeral=True)

    @classmethod
    def from_checkpoint(cls, ckpt: Checkpoint) -> "Booster":
        return cls.load(os.path.join(ckpt.as_directory(), "booster.pkl"))


# ---------------------------------------------------------------------------
# Core training loop (rank-agnostic; comm injects distribution)
# ---------------------------------------------------------------------------

def _train_core(cfg: _BoostConfig, X: np.ndarray, y: np.ndarray,
                num_boost_round: int,
                evals: Sequence[Tuple[np.ndarray, np.ndarray, str]] = (),
                comm: Optional[_Comm] = None,
                callback: Optional[Callable[[int, Dict[str, float]], None]]
                = None,
                early_stopping_rounds: Optional[int] = None,
                world_size: int = 1, rank: int = 0,
                feature_names: Optional[List[str]] = None) -> Booster:
    comm = comm or _Comm()
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, F = X.shape

    # Agree on bin edges: every rank proposes candidates from its shard;
    # the allreduced per-feature min/max + a merged sample would be the
    # full sketch — a gathered subsample is enough and simpler. Each rank
    # contributes an identical-shaped sample matrix; the reduction
    # concatenates via allgather-free trick: pad to fixed size and
    # allreduce is wrong for quantiles, so ranks exchange through the
    # collective allgather only when distributed.
    if world_size > 1:
        from ..util import collective

        cap = max(1, 20_000 // world_size)
        if n <= cap:
            take = np.arange(n)          # small shard: exact quantiles
        else:
            rng = np.random.default_rng(cfg.seed + 7)
            take = rng.choice(n, cap, replace=False)
        gathered = collective.allgather(
            X[take], group_name=comm.group)  # type: ignore[attr-defined]
        sample = np.concatenate(gathered, axis=0)
    else:
        sample = X
    edges = _propose_edges(sample, cfg.max_bins, seed=cfg.seed)
    binned = _bin_data(X, edges)

    K = cfg.num_class if cfg.objective == "multiclass" else 1
    booster = Booster(cfg, F, feature_names)
    margin = np.full((n, K), cfg.base_score, dtype=np.float64)
    evals = [(np.asarray(ex, dtype=np.float64),
              np.asarray(ey, dtype=np.float64), name)
             for ex, ey, name in evals]
    eval_margins = [np.full((ex.shape[0], K), cfg.base_score)
                    for ex, _, _ in evals]

    metric = cfg.eval_metric or _default_metric(cfg.objective)
    rng = np.random.default_rng(cfg.seed + rank * 1009 + 1)
    col_rng = np.random.default_rng(cfg.seed + 13)  # same cols on all ranks
    best = (math.inf, -1)

    for it in range(num_boost_round):
        rows_all = np.arange(n)
        if cfg.subsample < 1.0:
            rows_all = rows_all[rng.random(n) < cfg.subsample]
        if cfg.colsample < 1.0:
            k = max(1, int(round(F * cfg.colsample)))
            features = np.sort(col_rng.choice(F, k, replace=False))
        else:
            features = np.arange(F)

        mflat = margin if K > 1 else margin[:, 0]
        grad, hess = _grad_hess(cfg.objective, mflat, y)
        per_class: List[_Tree] = []
        for kcls in range(K):
            g = grad[:, kcls] if K > 1 else grad
            h = hess[:, kcls] if K > 1 else hess
            tree = _grow_tree(binned, edges, g, h, rows_all, features,
                              cfg, comm)
            per_class.append(tree)
            margin[:, kcls] += tree.predict(X)
            for em, (ex, _, _) in zip(eval_margins, evals):
                em[:, kcls] += tree.predict(ex)
        booster.trees.append(per_class)

        # Globally-consistent metrics: shard-local sums allreduced.
        results: Dict[str, float] = {}
        stats = _metric_stats(metric, mflat, y)
        results[f"train-{metric}"] = _finalize_metric(
            metric, comm.allreduce(stats))
        for em, (ex, ey, name) in zip(eval_margins, evals):
            emf = em if K > 1 else em[:, 0]
            st = _metric_stats(metric, emf, ey)
            results[f"{name}-{metric}"] = _finalize_metric(
                metric, comm.allreduce(st))
        if callback is not None:
            callback(it, results)

        if early_stopping_rounds and evals:
            key = f"{evals[0][2]}-{metric}"
            if results[key] < best[0] - 1e-12:
                best = (results[key], it)
            elif it - best[1] >= early_stopping_rounds:
                booster.best_iteration = best[1]
                break
    if booster.best_iteration is None and evals and early_stopping_rounds:
        booster.best_iteration = best[1]
    return booster


def train(params: Dict[str, Any], dtrain: Tuple[np.ndarray, np.ndarray],
          *, num_boost_round: int = 10,
          evals: Sequence[Tuple[Tuple[np.ndarray, np.ndarray], str]] = (),
          early_stopping_rounds: Optional[int] = None,
          dialect: str = "xgboost",
          callback: Optional[Callable[[int, Dict[str, float]], None]] = None,
          feature_names: Optional[List[str]] = None,
          ) -> Booster:
    """Local (single-process) training entry point shaped like
    ``xgboost.train`` (reference capability: the library call that
    gbdt_trainer.py:205 dispatches to on each worker)."""
    cfg = _normalize_params(params, dialect)
    X, y = dtrain
    ev = [(np.asarray(ex), np.asarray(ey), name)
          for (ex, ey), name in evals]
    return _train_core(cfg, np.asarray(X), np.asarray(y), num_boost_round,
                       ev, callback=callback,
                       early_stopping_rounds=early_stopping_rounds,
                       feature_names=feature_names)


# ---------------------------------------------------------------------------
# Distributed trainers
# ---------------------------------------------------------------------------

def _materialize_shard(shard: Any, label_column: str
                       ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Dataset/DataIterator shard -> (X, y, feature_names) numpy. Feature
    order is sorted column names — the canonical order every worker (and
    the returned Booster) uses."""
    feats: List[np.ndarray] = []
    labels: List[np.ndarray] = []
    names: List[str] = []
    for batch in shard.iter_batches(batch_format="numpy"):
        if not isinstance(batch, dict):
            raise TypeError("GBDT trainers need dict batches "
                            "(column -> array)")
        if label_column not in batch:
            raise KeyError(f"label column {label_column!r} not in batch "
                           f"columns {sorted(batch)}")
        y = np.asarray(batch[label_column])
        names = [c for c in sorted(batch) if c != label_column]
        cols = [np.asarray(batch[c], dtype=np.float64).reshape(len(y), -1)
                for c in names]
        feats.append(np.concatenate(cols, axis=1))
        labels.append(y.astype(np.float64))
    if not feats:
        # Empty shard (fewer blocks than workers): width 0 — the trainer
        # loop reconciles the true feature count across the gang.
        return np.zeros((0, 0)), np.zeros((0,)), []
    return (np.concatenate(feats, axis=0), np.concatenate(labels, axis=0),
            names)


def _reconcile_width(X: np.ndarray, group: str) -> np.ndarray:
    """Agree on the feature count across ranks (a rank whose shard got no
    blocks has width 0); every rank calls this in lockstep."""
    from ..util import collective

    F = int(collective.allreduce(
        np.array([float(X.shape[1])]), group_name=group,
        op=collective.ReduceOp.MAX)[0])
    if X.shape[0] == 0:
        return np.zeros((0, F))
    if X.shape[1] != F:
        raise ValueError(
            f"feature count mismatch across shards: {X.shape[1]} != {F}")
    return X


class GBDTTrainer(TpuTrainer):
    """Distributed boosting over the TpuTrainer gang
    (reference: python/ray/train/gbdt_trainer.py:76 — same surface:
    params + datasets + label_column + num_boost_round; `fit()` returns a
    Result whose checkpoint holds the booster)."""

    _dialect = "xgboost"

    def __init__(self, *, params: Dict[str, Any],
                 label_column: str,
                 datasets: Dict[str, Any],
                 num_boost_round: int = 10,
                 early_stopping_rounds: Optional[int] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must include a 'train' dataset")
        cfg = _normalize_params(params, self._dialect)  # validate up front
        del cfg
        self.params = dict(params)
        self.label_column = label_column
        self.num_boost_round = num_boost_round
        self.early_stopping_rounds = early_stopping_rounds
        dialect = self._dialect

        def loop(loop_config: Dict[str, Any]) -> None:
            from . import session as S
            from ..util import collective

            ctx = S.get_context()
            rank, world = ctx.get_world_rank(), ctx.get_world_size()
            group_n = loop_config["group"]
            cfg2 = _normalize_params(loop_config["params"],
                                     loop_config["dialect"])
            X, y, fnames = _materialize_shard(
                S.get_dataset_shard("train"), loop_config["label_column"])
            evals = []
            for name in loop_config["eval_names"]:
                ex, ey, _ = _materialize_shard(S.get_dataset_shard(name),
                                               loop_config["label_column"])
                evals.append((ex, ey, name))

            comm: _Comm
            if world > 1:
                collective.init_collective_group(
                    world, rank, group_name=group_n)
                comm = _CollectiveComm(group_n)
                X = _reconcile_width(X, group_n)
                evals = [(_reconcile_width(ex, group_n), ey, name)
                         for ex, ey, name in evals]
            else:
                comm = _Comm()
            # Tensor-valued columns widen the matrix past the column list;
            # then name<->column alignment is lost — drop the names.
            if len(fnames) != X.shape[1]:
                fnames = None  # type: ignore[assignment]
            ok = False
            try:
                def cb(it: int, metrics: Dict[str, float]) -> None:
                    if rank == 0:
                        S.report({"training_iteration": it + 1, **metrics})

                booster = _train_core(
                    cfg2, X, y,
                    loop_config["num_boost_round"], evals, comm=comm,
                    callback=cb,
                    early_stopping_rounds=loop_config["early_stopping"],
                    world_size=world, rank=rank, feature_names=fnames)
                ok = True
                if rank == 0:
                    S.report({"done": True,
                              "num_boost_round": booster.num_boosted_rounds},
                             checkpoint=booster.to_checkpoint())
            finally:
                if world > 1:
                    if ok:
                        # Clean finish: all ranks drain, then rank 0
                        # releases the coordinator actor. On failure the
                        # coordinator is abandoned — the next fit attempt
                        # uses a FRESH group (see _fit_once), so stale
                        # round state can never leak into a retry.
                        try:
                            collective.barrier(group_name=group_n,
                                               timeout=30)
                        except Exception:  # noqa: BLE001
                            pass
                    collective.destroy_collective_group(
                        group_n, release_coordinator=ok and rank == 0)

        eval_names = [k for k in datasets if k != "train"]
        super().__init__(
            loop,
            train_loop_config={
                "params": self.params, "dialect": dialect,
                "label_column": label_column,
                "num_boost_round": num_boost_round,
                "early_stopping": early_stopping_rounds,
                # Seeded per fit attempt in _fit_once; never used as-is.
                "eval_names": eval_names, "group": "",
            },
            scaling_config=scaling_config or ScalingConfig(num_workers=1),
            run_config=run_config,
            datasets=datasets)

    def _fit_once(self, manager) -> Result:
        # Fresh collective group per attempt: a failure-retry must never
        # rejoin a coordinator holding a crashed gang's round state.
        import uuid

        self.train_loop_config["group"] = f"_gbdt:{uuid.uuid4().hex[:12]}"
        return super()._fit_once(manager)

    @classmethod
    def get_model(cls, checkpoint: Checkpoint) -> Booster:
        """reference: XGBoostTrainer.get_model(checkpoint)
        (train/xgboost/xgboost_trainer.py:83)."""
        return Booster.from_checkpoint(checkpoint)


class XGBoostTrainer(GBDTTrainer):
    """XGBoost-dialect distributed trainer
    (reference: python/ray/train/xgboost/xgboost_trainer.py:11)."""

    _dialect = "xgboost"


class LightGBMTrainer(GBDTTrainer):
    """LightGBM-dialect distributed trainer: leaf-wise growth,
    num_leaves-bounded (reference:
    python/ray/train/lightgbm/lightgbm_trainer.py:11)."""

    _dialect = "lightgbm"
