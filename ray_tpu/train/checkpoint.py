"""Checkpoints: directory-based handles + top-K retention + jax pytree IO.

Capability-equivalent to the reference's checkpoint stack
(reference: python/ray/train/_checkpoint.py:55 Checkpoint,
train/_internal/checkpoint_manager.py top-K retention,
train/_internal/storage.py StorageContext): a Checkpoint is a directory;
the manager persists/retains; pytree state rides orbax when available
(async-capable), with a numpy .npz fallback.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory full of state (reference: train/_checkpoint.py:55)."""

    def __init__(self, path: str, *, _ephemeral: bool = False):
        self.path = os.path.abspath(path)
        # Ephemeral checkpoints (from_pytree temp dirs) are MOVED into
        # storage by the manager instead of copied, so /tmp doesn't
        # accumulate one model copy per report().
        self._ephemeral = _ephemeral

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None
                    ) -> "Checkpoint":
        ephemeral = path is None
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        save_pytree(tree, path)
        return cls(path, _ephemeral=ephemeral)

    def as_directory(self) -> str:
        return self.path

    def to_pytree(self) -> Any:
        return load_pytree(self.path)

    def __repr__(self):
        return f"Checkpoint({self.path})"


# ---------------------------------------------------------------------------
# Pytree IO (orbax preferred, npz fallback)
# ---------------------------------------------------------------------------

def save_pytree(tree: Any, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, "state")
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, tree)
        return
    except Exception:  # noqa: BLE001 — fall back to npz
        # Remove any partially written orbax dir: load_pytree prefers
        # `state/`, so leftovers would shadow the valid npz fallback.
        shutil.rmtree(target, ignore_errors=True)
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    np.savez(
        os.path.join(path, "state.npz"),
        **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(path, "treedef.json"), "w") as f:
        json.dump({"n": len(leaves)}, f)
    import pickle

    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(path: str, like: Any = None) -> Any:
    orbax_dir = os.path.join(path, "state")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(orbax_dir)
        if like is not None:
            import jax
            return jax.tree.unflatten(
                jax.tree.structure(like), jax.tree.leaves(restored))
        return restored
    import pickle

    import jax
    import numpy as np

    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    leaves = [data[str(i)] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Manager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Persist reported checkpoints under storage_path; keep top-K
    (reference: train/_internal/checkpoint_manager.py)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint,
                 metrics: Dict[str, Any]) -> Optional[Checkpoint]:
        """Persist a reported checkpoint. Returns the stored handle, or
        None if retention evicted it immediately (score below the kept
        top-K) — callers must not treat None as the latest checkpoint."""
        with self._lock:
            idx = len(self._records)
            dest = os.path.join(self.storage_path, f"checkpoint_{idx:06d}")
            if os.path.abspath(checkpoint.path) != dest:
                if os.path.exists(dest):
                    shutil.rmtree(dest)
                if checkpoint._ephemeral:
                    shutil.move(checkpoint.path, dest)
                else:
                    shutil.copytree(checkpoint.path, dest)
            rec = {"path": dest, "metrics": dict(metrics),
                   "ts": time.time(), "index": idx}
            self._records.append(rec)
            self._evict_locked()
            self._write_manifest_locked()
            if not os.path.exists(dest):
                return None
            return Checkpoint(dest)

    def _score(self, rec) -> float:
        if not self.score_attribute:
            return rec["index"]
        v = rec["metrics"].get(self.score_attribute)
        if v is None:
            return float("-inf")
        return v if self.score_order == "max" else -v

    def _evict_locked(self):
        if not self.num_to_keep:
            return
        alive = [r for r in self._records if os.path.exists(r["path"])]
        if len(alive) <= self.num_to_keep:
            return
        alive.sort(key=self._score)
        for rec in alive[: len(alive) - self.num_to_keep]:
            shutil.rmtree(rec["path"], ignore_errors=True)

    def _write_manifest_locked(self):
        manifest = [
            {k: r[k] for k in ("path", "metrics", "ts", "index")}
            for r in self._records if os.path.exists(r["path"])
        ]
        with open(os.path.join(self.storage_path, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1, default=str)

    def latest(self) -> Optional[Checkpoint]:
        with self._lock:
            for rec in reversed(self._records):
                if os.path.exists(rec["path"]):
                    return Checkpoint(rec["path"])
        return None

    def best(self) -> Optional[Checkpoint]:
        with self._lock:
            alive = [r for r in self._records if os.path.exists(r["path"])]
            if not alive:
                return None
            return Checkpoint(max(alive, key=self._score)["path"])
