"""Checkpoints: directory-based handles + top-K retention + jax pytree IO.

Capability-equivalent to the reference's checkpoint stack
(reference: python/ray/train/_checkpoint.py:55 Checkpoint,
train/_internal/checkpoint_manager.py top-K retention,
train/_internal/storage.py StorageContext): a Checkpoint is a directory;
the manager persists/retains; pytree state rides orbax when available
(async-capable), with a numpy .npz fallback.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory full of state, addressed by local path OR storage
    URL (reference: train/_checkpoint.py:55 Checkpoint + from_uri —
    URI-addressed checkpoints download lazily through the external
    storage plane, so a checkpoint written on a host that later died
    still restores anywhere)."""

    def __init__(self, path: str, *, _ephemeral: bool = False):
        from ..core.external_storage import is_url

        if is_url(path) and not path.startswith("file://"):
            self.uri: Optional[str] = path
            self.path = ""  # resolved lazily by as_directory()
        else:
            if path.startswith("file://"):
                path = path[len("file://"):]
            self.uri = None
            self.path = os.path.abspath(path)
        self._local_cache: Optional[str] = None
        # Ephemeral checkpoints (from_pytree temp dirs) are MOVED into
        # storage by the manager instead of copied, so /tmp doesn't
        # accumulate one model copy per report().
        self._ephemeral = _ephemeral

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """reference: train Checkpoint.from_uri."""
        return cls(uri)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None
                    ) -> "Checkpoint":
        ephemeral = path is None
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        save_pytree(tree, path)
        return cls(path, _ephemeral=ephemeral)

    def as_directory(self) -> str:
        if self.uri is not None:
            if self._local_cache is None:
                import weakref

                from ..core.external_storage import storage_for_url

                local = tempfile.mkdtemp(prefix="ray_tpu_ckpt_dl_")
                storage_for_url(self.uri).download_dir(self.uri, local)
                self._local_cache = local
                # The download cache dies with the handle — otherwise
                # every resume leaves one model copy in /tmp (the
                # accumulation _ephemeral exists to prevent).
                weakref.finalize(self, shutil.rmtree, local, True)
            return self._local_cache
        return self.path

    def to_pytree(self) -> Any:
        return load_pytree(self.as_directory())

    def __getstate__(self):
        # The download cache is host-local; a shipped handle re-fetches.
        state = dict(self.__dict__)
        state["_local_cache"] = None
        return state

    def __repr__(self):
        return f"Checkpoint({self.uri or self.path})"


# ---------------------------------------------------------------------------
# Pytree IO (orbax preferred, npz fallback)
# ---------------------------------------------------------------------------

def save_pytree(tree: Any, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, "state")
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, tree)
        return
    except Exception:  # noqa: BLE001 — fall back to npz
        # Remove any partially written orbax dir: load_pytree prefers
        # `state/`, so leftovers would shadow the valid npz fallback.
        shutil.rmtree(target, ignore_errors=True)
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    np.savez(
        os.path.join(path, "state.npz"),
        **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
    with open(os.path.join(path, "treedef.json"), "w") as f:
        json.dump({"n": len(leaves)}, f)
    import pickle

    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(path: str, like: Any = None) -> Any:
    orbax_dir = os.path.join(path, "state")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(orbax_dir)
        if like is not None:
            import jax
            return jax.tree.unflatten(
                jax.tree.structure(like), jax.tree.leaves(restored))
        return restored
    import pickle

    import jax
    import numpy as np

    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    leaves = [data[str(i)] for i in range(len(data.files))]
    return jax.tree.unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Manager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Persist reported checkpoints under storage_path; keep top-K
    (reference: train/_internal/checkpoint_manager.py)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        from ..core.external_storage import is_url, storage_for_url

        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._next_index = 0
        # Remote storage_path (cp://, mem://): checkpoints upload
        # through the external-storage plane and the records hold URLs
        # (reference: train/_internal/storage.py URI storage_path).
        if is_url(storage_path) and not storage_path.startswith("file://"):
            self._storage = storage_for_url(storage_path)
            rest = storage_path.split("://", 1)[1]
            _, _, prefix = rest.partition("/")
            self._key_prefix = (prefix.rstrip("/") + "/") if prefix else ""
        else:
            self._storage = None
            self._key_prefix = ""
            if storage_path.startswith("file://"):
                self.storage_path = storage_path[len("file://"):]
            os.makedirs(self.storage_path, exist_ok=True)
            self._load_manifest()

    def _load_manifest(self) -> None:
        """Resume a prior manager's records from manifest.json so a
        fresh process pointing at the same storage_path can
        latest()/best() across restarts (the RLHF pipeline's
        restore_latest path). Local-dir managers only; a missing or
        stale manifest just means starting empty — dead paths are
        filtered by _exists at read time."""
        try:
            with open(os.path.join(self.storage_path,
                                   "manifest.json")) as f:
                records = json.load(f)
        except Exception:  # noqa: BLE001 — no/corrupt manifest
            return
        for rec in records:
            if isinstance(rec, dict) and "path" in rec:
                rec.setdefault("metrics", {})
                rec.setdefault("index", 0)
                rec["alive"] = True
                self._records.append(rec)
        if self._records:
            self._next_index = max(
                int(r["index"]) for r in self._records) + 1

    def _exists(self, rec_or_path) -> bool:
        """Liveness of a record/path. Remote records carry a local
        `alive` flag (set False on evict) instead of paying one
        network round trip per record per call."""
        if isinstance(rec_or_path, dict):
            if self._storage is not None:
                return rec_or_path.get("alive", True)
            return os.path.exists(rec_or_path["path"])
        if self._storage is not None:
            return self._storage.exists(rec_or_path)
        return os.path.exists(rec_or_path)

    def register(self, checkpoint: Checkpoint,
                 metrics: Dict[str, Any]) -> Optional[Checkpoint]:
        """Persist a reported checkpoint. Returns the stored handle, or
        None if retention evicted it immediately (score below the kept
        top-K) — callers must not treat None as the latest checkpoint."""
        with self._lock:
            idx = self._next_index
            self._next_index += 1
        name = f"checkpoint_{idx:06d}"
        # Upload OUTSIDE the lock: a multi-hundred-MB transfer must not
        # block latest()/best() (the resume path) for its duration.
        if self._storage is not None:
            dest = self._storage.upload_dir(
                checkpoint.as_directory(), self._key_prefix + name)
            if checkpoint._ephemeral:
                shutil.rmtree(checkpoint.as_directory(),
                              ignore_errors=True)
        else:
            dest = os.path.join(self.storage_path, name)
            if os.path.abspath(checkpoint.path) != dest:
                if os.path.exists(dest):
                    shutil.rmtree(dest)
                if checkpoint._ephemeral:
                    shutil.move(checkpoint.path, dest)
                else:
                    shutil.copytree(checkpoint.path, dest)
        rec = {"path": dest, "metrics": dict(metrics),
               "ts": time.time(), "index": idx, "alive": True}
        with self._lock:
            self._records.append(rec)
            evicted = self._evict_locked()
            manifest = self._manifest_locked()
        # Storage deletions + manifest write outside the lock too.
        for gone in evicted:
            if self._storage is not None:
                self._storage.delete_dir(gone["path"])
            else:
                shutil.rmtree(gone["path"], ignore_errors=True)
        self._write_manifest(manifest)
        if not self._exists(rec):
            return None
        return Checkpoint(dest)

    def _score(self, rec) -> float:
        if not self.score_attribute:
            return rec["index"]
        v = rec["metrics"].get(self.score_attribute)
        if v is None:
            return float("-inf")
        return v if self.score_order == "max" else -v

    def _evict_locked(self) -> List[Dict[str, Any]]:
        """Pick + mark evictions under the lock; the caller performs
        the (possibly remote) deletions outside it."""
        if not self.num_to_keep:
            return []
        alive = [r for r in self._records if self._exists(r)]
        if len(alive) <= self.num_to_keep:
            return []
        alive.sort(key=self._score)
        evicted = alive[: len(alive) - self.num_to_keep]
        for rec in evicted:
            rec["alive"] = False
        return evicted

    def _manifest_locked(self) -> str:
        return json.dumps([
            {k: r[k] for k in ("path", "metrics", "ts", "index")}
            for r in self._records if self._exists(r)
        ], indent=1, default=str)

    def _write_manifest(self, blob: str) -> None:
        try:
            if self._storage is not None:
                self._storage.put_blob(
                    self._key_prefix + "manifest.json", blob.encode())
                return
            with open(os.path.join(self.storage_path,
                                   "manifest.json"), "w") as f:
                f.write(blob)
        except Exception:  # noqa: BLE001 — manifest is advisory
            pass

    def latest(self) -> Optional[Checkpoint]:
        with self._lock:
            for rec in reversed(self._records):
                if self._exists(rec):
                    return Checkpoint(rec["path"])
        return None

    def best(self) -> Optional[Checkpoint]:
        with self._lock:
            alive = [r for r in self._records if self._exists(r)]
            if not alive:
                return None
            return Checkpoint(max(alive, key=self._score)["path"])
