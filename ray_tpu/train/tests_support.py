"""Importable test-support builders (worker processes can import
ray_tpu.* but not the tests/ directory — loops that run on spawned
workers get their fixtures from here)."""

from __future__ import annotations


def tiny_hf_trainer(output_dir, max_steps: int = 4, save_steps=None):
    """A from-scratch tiny BERT classifier on synthetic data — no hub
    downloads (zero-egress environments)."""
    import numpy as np
    from transformers import (
        BertConfig,
        BertForSequenceClassification,
        Trainer,
    )

    from .huggingface import default_training_args

    cfg = BertConfig(vocab_size=64, hidden_size=16,
                     num_hidden_layers=1, num_attention_heads=2,
                     intermediate_size=32, max_position_embeddings=32)
    model = BertForSequenceClassification(cfg)
    rng = np.random.default_rng(0)
    data = [{"input_ids": rng.integers(0, 64, size=8).tolist(),
             "attention_mask": [1] * 8,
             "labels": int(i % 2)} for i in range(16)]
    kw = dict(max_steps=max_steps, per_device_train_batch_size=4)
    if save_steps:
        kw.update(save_strategy="steps", save_steps=save_steps)
    args = default_training_args(str(output_dir), **kw)
    return Trainer(model=model, args=args, train_dataset=data)
