"""TpuTrainer — SPMD training orchestration over worker actors.

Capability-equivalent to the reference's Train stack
(reference: python/ray/train/base_trainer.py:74 BaseTrainer.fit :579,
data_parallel_trainer.py:26 DataParallelTrainer,
_internal/backend_executor.py:65 BackendExecutor — worker-group creation
in a placement group, rendezvous, run train_loop_per_worker, stream
`report()` results back, FailureConfig-driven group restarts), redesigned
TPU-first: no NCCL process-group bootstrapping — each worker drives its
chips through a jax Mesh built from the ScalingConfig's ParallelPlan, and
gang placement uses STRICT_PACK (or SliceAffinity) so all workers land on
one ICI slice.
"""

from __future__ import annotations

import logging
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .. import remote
from ..core.placement_group import (
    placement_group,
    remove_placement_group,
)
from ..core.task import PlacementGroupSchedulingStrategy
from .checkpoint import Checkpoint, CheckpointManager
from .config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from .session import ReportItem, _set_session, _TrainSession

logger = logging.getLogger("ray_tpu.train")


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []


class _TrainWorker:
    """Worker actor: hosts one SPMD rank's session and runs the user loop.
    Streamed method `run` yields ReportItems as training progresses
    (reference: backend_executor start_training + TrainingIterator
    polling, trainer.py:31 — here a streaming generator replaces the
    polling)."""

    def __init__(self, rank: int, world_size: int, name: str, plan_bytes):
        import cloudpickle

        self.rank = rank
        self.world_size = world_size
        self.name = name
        self.plan = cloudpickle.loads(plan_bytes) if plan_bytes else None

    def run(self, fn_bytes: bytes, loop_config: Optional[Dict[str, Any]],
            dataset_shards: Optional[Dict[str, Any]],
            start_checkpoint=None, rendezvous: Optional[Dict[str, Any]]
            = None):
        import cloudpickle

        fn = cloudpickle.loads(fn_bytes)
        session = _TrainSession(
            self.rank, self.world_size, self.name, loop_config,
            dataset_shards, self.plan, start_checkpoint=start_checkpoint)

        def _target():
            _set_session(session)
            joined = False
            try:
                # Pin jax to the platform this worker's environment
                # requests BEFORE any backend/rendezvous init: a
                # sitecustomize-registered accelerator plugin can
                # otherwise override the JAX_PLATFORMS env var and grab
                # a chip the gang doesn't own.
                plat = os.environ.get("JAX_PLATFORMS")
                if plat and "," not in plat:
                    import jax

                    try:
                        jax.config.update("jax_platforms", plat)
                    except Exception:  # noqa: BLE001 — backend is live
                        pass
                if rendezvous is not None:
                    self._join_gang(rendezvous)
                    joined = True
                import inspect

                if loop_config is not None and len(
                        inspect.signature(fn).parameters) >= 1:
                    fn(loop_config)
                else:
                    fn()
                if joined:
                    # Clean finish only: after a failure peers may be
                    # stuck in a collective and shutdown would block;
                    # the dedicated worker process dies with the actor.
                    from ..parallel.multihost import shutdown_multihost

                    shutdown_multihost()
            except BaseException as e:  # noqa: BLE001
                session.error = e
            finally:
                _set_session(None)
                session.finished.set()
                session.queue.put(None)

        t = threading.Thread(target=_target, daemon=True,
                             name=f"train-loop-{self.rank}")
        t.start()
        while True:
            item = session.queue.get()
            if item is None:
                break
            yield item
        if session.error is not None:
            raise session.error
        yield ReportItem({"__final__": True}, None, self.rank)

    def _join_gang(self, rdv: Dict[str, Any]) -> None:
        """jax.distributed rendezvous for this rank (reference:
        backend_executor.py:124 start → worker group → rendezvous →
        train; torch/config.py:62 TCP store ↔ here the coordinator
        address rides the control plane's KV)."""
        from ..parallel.multihost import init_multihost

        from ..parallel import multihost as mh

        if mh._initialized:
            # jax.distributed.initialize is once-per-process: a second
            # rank landing in this process would silently skip init and
            # hang the whole gang at the coordinator. Surface it.
            raise RuntimeError(
                "multihost rank cannot share a process with another "
                "rank (jax.distributed already initialized here); "
                "ensure each worker gets its own OS process — daemon "
                "placement or ray_tpu.init(num_worker_procs=...)")
        client = None
        if rdv.get("control_address"):
            from .._native.control_client import ControlClient

            host, _, port = rdv["control_address"].partition(":")
            client = ControlClient(int(port), host=host)
        try:
            init_multihost(
                coordinator_address=rdv.get("coordinator_address"),
                num_processes=self.world_size,
                process_id=self.rank,
                control_client=client,
                kv_key=rdv["kv_key"],
                port=rdv["coordinator_port"])
        finally:
            if client is not None:
                client.close()


class TpuTrainer:
    """reference-parity surface: TpuTrainer(train_loop_per_worker,
    train_loop_config=..., scaling_config=..., run_config=...,
    datasets=...).fit() -> Result."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        # Checkpoint every worker's session starts from; train loops read
        # it via train.get_checkpoint() (reference:
        # base_trainer.py resume_from_checkpoint → session checkpoint).
        # Tune's PBT exploit and trial restore set this between fits.
        self.resume_from_checkpoint = resume_from_checkpoint
        # Subclass hook (TorchTrainer): rank -> SchedulingStrategy,
        # replacing the default placement-group gang placement.
        self._strategy_factory: Optional[Callable[[int], Any]] = None

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        failures_allowed = self.run_config.failure_config.max_failures
        attempt = 0
        storage = self.run_config.resolve_storage()
        cc = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage, cc.num_to_keep, cc.checkpoint_score_attribute,
            cc.checkpoint_score_order)
        # Retries resume from the newest checkpoint WITHIN this fit;
        # the caller's resume_from_checkpoint is restored afterwards so
        # a reused trainer instance (Tuner trials) starts fresh.
        orig_resume = self.resume_from_checkpoint
        try:
            while True:
                try:
                    return self._fit_once(manager)
                except (KeyboardInterrupt, SystemExit):
                    raise  # user interrupts are not trial failures
                except Exception as e:  # noqa: BLE001
                    attempt += 1
                    if failures_allowed >= 0 \
                            and attempt > failures_allowed:
                        return Result(error=e, path=storage)
                    # Restarted groups resume from the newest checkpoint
                    # the failed attempt registered (reference:
                    # FailureConfig recovery restores the latest
                    # reported checkpoint).
                    latest = manager.latest()
                    if latest is not None:
                        self.resume_from_checkpoint = latest
                    logger.warning(
                        "Training attempt %d failed (%s); restarting "
                        "worker group (%d restarts left).", attempt,
                        type(e).__name__, failures_allowed - attempt)
        finally:
            self.resume_from_checkpoint = orig_resume

    def _make_rendezvous(self, n: int) -> Dict[str, Any]:
        """Per-attempt rendezvous spec: a fresh coordinator port and a
        fresh KV key, so a retried gang can never join a crashed gang's
        coordinator (reference: backend_executor re-creates the TCP
        store on restart)."""
        import uuid

        from ..core.runtime import global_runtime

        rt = global_runtime()
        rdv: Dict[str, Any] = {
            "coordinator_port": None,
            "kv_key": f"multihost/{self.run_config.name or 'train'}/"
                      f"{uuid.uuid4().hex[:12]}",
            "control_address": None,
            "coordinator_address": None,
        }
        if rt.remote_plane is not None:
            # Cluster mode: rank 0 picks a port free on ITS host and
            # publishes the coordinator address in the control plane's
            # KV; peers poll it (SURVEY §3.3 — the rendezvous path the
            # whole stack exists to serve).
            rdv["control_address"] = rt.remote_plane.address
        else:
            # Single-machine worker processes share the driver's host,
            # so a driver-side port probe is authoritative here.
            port = _free_port()
            rdv["coordinator_port"] = port
            rdv["coordinator_address"] = f"127.0.0.1:{port}"
        return rdv

    def _fit_once(self, manager: CheckpointManager) -> Result:
        import cloudpickle

        sc = self.scaling_config
        n = sc.num_workers
        storage = self.run_config.resolve_storage()

        # Gang placement: one bundle per worker (reference:
        # BackendExecutor start creates the PG; TPU-native default is
        # PACK onto one slice).
        from .. import get as ray_get, kill as ray_kill

        if sc.multihost and n > 1 and self._strategy_factory is None:
            rt = None
            from ..core.runtime import global_runtime

            rt = global_runtime()
            if rt.remote_plane is None:
                # Local mode: each rank MUST be its own OS process —
                # jax.distributed.initialize is once-per-process, so
                # thread actors sharing the driver process cannot form
                # a gang. Route ranks to dedicated worker processes
                # (same plane the torch/TF trainers use).
                if (rt.worker_pool is None
                        or rt.worker_pool.num_workers < n):
                    have = (0 if rt.worker_pool is None
                            else rt.worker_pool.num_workers)
                    raise RuntimeError(
                        f"ScalingConfig(multihost=True) outside a "
                        f"daemon cluster needs {n} worker processes "
                        f"but the runtime has {have}: call "
                        f"ray_tpu.init(num_worker_procs={n}) or "
                        "connect to a cluster "
                        "(ray_tpu.init(address=...))")
                from ..core.task import NodeAffinitySchedulingStrategy

                self._strategy_factory = lambda rank: \
                    NodeAffinitySchedulingStrategy(node_id="node-procs",
                                                   soft=False)

        pg = None
        if self._strategy_factory is None:
            pg = placement_group(
                [sc.worker_resources() for _ in range(n)],
                strategy=sc.placement_strategy)
        workers: List[Any] = []
        history: List[Dict[str, Any]] = []
        last_ckpt: Optional[Checkpoint] = None
        error: Optional[BaseException] = None
        try:
            if pg is not None:
                pg.wait(timeout=None)

            WorkerActor = remote(num_cpus=0)(_TrainWorker)
            plan_bytes = cloudpickle.dumps(sc.plan) if sc.plan else None
            for rank in range(n):
                if self._strategy_factory is not None:
                    strategy = self._strategy_factory(rank)
                else:
                    strategy = PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=rank)
                workers.append(
                    WorkerActor.options(
                        scheduling_strategy=strategy,
                        num_cpus=sc.cpus_per_worker,
                        num_tpus=sc.tpus_per_worker or None,
                        resources=sc.resources_per_worker or None,
                    ).remote(rank, n, self.run_config.name or "train",
                             plan_bytes))

            # Shard datasets across workers (streaming_split if possible).
            shards_per_worker: List[Dict[str, Any]] = [
                dict() for _ in range(n)]
            for name, ds in self.datasets.items():
                if hasattr(ds, "streaming_split"):
                    split = ds.streaming_split(n, equal=True)
                    for r in range(n):
                        shards_per_worker[r][name] = split[r]
                else:
                    for r in range(n):
                        shards_per_worker[r][name] = ds

            fn_bytes = cloudpickle.dumps(self.train_loop)
            rendezvous = None
            if sc.multihost and n > 1:
                rendezvous = self._make_rendezvous(n)
            streams = [
                w.run.options(num_returns="streaming").remote(
                    fn_bytes, self.train_loop_config, shards_per_worker[r],
                    self.resume_from_checkpoint, rendezvous)
                for r, w in enumerate(workers)
            ]

            # Drain all workers' report streams; rank-0 metrics drive
            # results, rank-0 checkpoints are persisted.
            def drain(stream, rank):
                nonlocal last_ckpt, error
                try:
                    for ref in stream:
                        item: ReportItem = ray_get(ref)
                        if item.metrics.get("__final__"):
                            continue
                        if item.checkpoint is not None and rank == 0:
                            stored = manager.register(
                                item.checkpoint, item.metrics)
                            if stored is not None:
                                last_ckpt = stored
                        if rank == 0:
                            history.append(item.metrics)
                except BaseException as e:  # noqa: BLE001
                    if error is None:
                        error = e

            threads = [
                threading.Thread(target=drain, args=(s, r), daemon=True)
                for r, s in enumerate(streams)
            ]
            for t in threads:
                t.start()
            # Abort the attempt on the FIRST rank failure: surviving
            # ranks may be blocked in a collective/rendezvous with the
            # dead peer and their streams stay silent for minutes — the
            # group teardown below unblocks them (reference:
            # backend_executor shuts the whole worker group down when
            # any worker fails).
            while True:
                alive = [t for t in threads if t.is_alive()]
                if not alive or error is not None:
                    break
                alive[0].join(timeout=0.2)
        finally:
            for w in workers:
                try:
                    ray_kill(w)
                except Exception:  # noqa: BLE001
                    pass
            if pg is not None:
                remove_placement_group(pg)

        if error is not None:
            raise error
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=last_ckpt or manager.latest(),
            path=storage,
            metrics_history=history,
        )


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcessPlaneTrainerMixin:
    """Shared scaffolding for trainers whose ranks each need their own
    OS process (torch gloo process groups, TF collective servers).
    Rank actors run as DEDICATED worker processes (worker_proc.py
    spawn_dedicated) that die with the actor — every fit attempt gets
    fresh processes, which is what lets frameworks with no in-process
    teardown (TF) re-rendezvous on retries."""

    def _init_process_plane(self) -> None:
        from ..core.task import NodeAffinitySchedulingStrategy

        self._strategy_factory = lambda rank: \
            NodeAffinitySchedulingStrategy(node_id="node-procs",
                                           soft=False)

    def _require_worker_procs(self, what: str) -> "None":
        from ..core.runtime import global_runtime

        rt = global_runtime()
        n = self.scaling_config.num_workers
        if rt.worker_pool is None or rt.worker_pool.num_workers < n:
            have = 0 if rt.worker_pool is None \
                else rt.worker_pool.num_workers
            raise RuntimeError(
                f"{what} needs {n} worker processes but the runtime "
                f"has {have}; call ray_tpu.init(num_worker_procs={n})")
