"""Horovod integration (gated on the package being installed).

Capability-equivalent to the reference's Horovod backend
(reference: python/ray/train/horovod/config.py:26 HorovodConfig,
:118 _HorovodBackend — gloo-controller rendezvous via env vars
HOROVOD_HOSTNAME/RANK/SIZE/controller addresses, then hvd.init() in
each worker).

Horovod is not in this image. HorovodTrainer refuses with guidance at
construction; when the package is present, the worker loop performs the
same env-var gloo rendezvous the reference backend does. Horovod's
allreduce role on TPU is filled natively — in-program collectives are
XLA's over ICI (ray_tpu.parallel), host-side ones are
ray_tpu.util.collective — so this adapter exists for portability of
existing horovod training scripts, not as the scaling path.
"""

from __future__ import annotations

import contextlib
import importlib.util
import socket
from typing import Any, Callable, Dict, Optional

from .config import RunConfig, ScalingConfig
from .trainer import ProcessPlaneTrainerMixin, Result, TpuTrainer

_HVD_ERROR = (
    "horovod is not installed in this environment. Horovod's role here "
    "is filled natively: XLA collectives over ICI for in-program "
    "reductions (ray_tpu.parallel), ray_tpu.util.collective for "
    "host-side ones, TorchTrainer/TensorflowTrainer for framework DDP. "
    "Install horovod[pytorch] to run existing horovod scripts unchanged."
)


class HorovodConfig:
    """(reference: train/horovod/config.py:26 — timeout + gloo controller
    knobs; the nics/mpi options have no analog here)."""

    def __init__(self, timeout_s: int = 300, placement_group_timeout_s:
                 int = 100, verbose: int = 1):
        self.timeout_s = timeout_s
        self.placement_group_timeout_s = placement_group_timeout_s
        self.verbose = verbose


def _start_rendezvous(num_workers: int, cfg: HorovodConfig):
    """Start horovod's gloo RendezvousServer on the driver and register
    the single-host allocation plan (reference:
    _HorovodBackend.on_start — RendezvousServer().start() + init(plan)).
    Returns (server, port, hostname)."""
    from horovod.runner.common.util.hosts import (
        get_host_assignment_plan,
        parse_hosts,
    )
    from horovod.runner.http.http_server import RendezvousServer

    server = RendezvousServer(verbose=cfg.verbose)
    port = server.start()
    hostname = socket.gethostname()
    hosts = parse_hosts(f"{hostname}:{num_workers}")
    plan = get_host_assignment_plan(hosts, num_workers)
    server.init(plan)
    return server, port, hostname


def _make_hvd_loop(user_fn: Callable, cfg: HorovodConfig, hostname: str,
                   port: int) -> Callable:
    """Env-var gloo rendezvous + hvd.init() around the user loop
    (reference: _HorovodBackend._setup_env_vars + worker hvd.init)."""
    import inspect

    takes_config = len(inspect.signature(user_fn).parameters) >= 1

    def loop(config: Optional[Dict[str, Any]] = None) -> None:
        import os

        import horovod.torch as hvd

        from .session import get_context

        ctx = get_context()
        os.environ.update({
            "HOROVOD_HOSTNAME": hostname,
            "HOROVOD_RANK": str(ctx.get_world_rank()),
            "HOROVOD_SIZE": str(ctx.get_world_size()),
            "HOROVOD_LOCAL_RANK": str(ctx.get_world_rank()),
            "HOROVOD_LOCAL_SIZE": str(ctx.get_world_size()),
            "HOROVOD_CROSS_RANK": "0",
            "HOROVOD_CROSS_SIZE": "1",
            "HOROVOD_CONTROLLER": "gloo",
            "HOROVOD_CPU_OPERATIONS": "gloo",
            "HOROVOD_GLOO_TIMEOUT_SECONDS": str(cfg.timeout_s),
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
        })
        hvd.init()
        try:
            if takes_config and config is not None:
                user_fn(config)
            else:
                user_fn()
        finally:
            hvd.shutdown()

    return loop


class HorovodTrainer(ProcessPlaneTrainerMixin, TpuTrainer):
    """(reference: train/horovod/horovod_trainer.py:11). Requires the
    horovod package; refuses with guidance when absent."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 horovod_config: Optional[HorovodConfig] = None):
        if importlib.util.find_spec("horovod") is None:
            raise ImportError(_HVD_ERROR)
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets)
        self.horovod_config = horovod_config or HorovodConfig()
        self._user_loop = train_loop_per_worker
        self._init_process_plane()

    def fit(self) -> Result:
        self._require_worker_procs("HorovodTrainer")
        return super().fit()

    def _fit_once(self, manager) -> Result:
        # Fresh rendezvous server per attempt (a retry must not reuse a
        # dead gang's KV state — same reasoning as TorchTrainer's
        # per-attempt address).
        server, port, hostname = _start_rendezvous(
            self.scaling_config.num_workers, self.horovod_config)
        try:
            self.train_loop = _make_hvd_loop(
                self._user_loop, self.horovod_config, hostname, port)
            return super()._fit_once(manager)
        finally:
            stop = getattr(server, "stop_server", None) or getattr(
                server, "stop", None)
            if stop is not None:
                with contextlib.suppress(Exception):
                    stop()
