"""Per-worker training session.

Capability-equivalent to the reference's _TrainSession
(reference: python/ray/train/_internal/session.py — report /
get_dataset_shard :464, world rank/size accessors): the user's
train_loop_per_worker calls `ray_tpu.train.report(metrics, checkpoint=...)`
and reads its context/mesh/dataset shard from here.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ReportItem:
    metrics: Dict[str, Any]
    checkpoint: Optional[Any] = None  # Checkpoint
    rank: int = 0


class TrainContext:
    def __init__(self, rank: int, world_size: int, session: "_TrainSession"):
        self._rank = rank
        self._world = world_size
        self._session = session

    def get_world_rank(self) -> int:
        return self._rank

    def get_world_size(self) -> int:
        return self._world

    def get_trial_name(self) -> str:
        return self._session.name


class _TrainSession:
    def __init__(self, rank: int, world_size: int, name: str,
                 loop_config: Optional[Dict[str, Any]] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 plan=None, start_checkpoint=None):
        self.rank = rank
        self.world_size = world_size
        self.name = name
        self.loop_config = loop_config or {}
        self.dataset_shards = dataset_shards or {}
        self.plan = plan
        # Checkpoint to resume from (trial restore / PBT exploit); user
        # code reads it via get_checkpoint() (reference:
        # ray.train.get_checkpoint / session.get_checkpoint).
        self.start_checkpoint = start_checkpoint
        self.queue: "queue.Queue[Optional[ReportItem]]" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # Set by the tune scheduler to early-stop a trial; report() raises
        # StopTrial at the next call (function-API trials unwind cleanly).
        self.stop_requested = threading.Event()

    def report(self, metrics: Dict[str, Any], checkpoint=None):
        self.queue.put(ReportItem(dict(metrics), checkpoint, self.rank))
        if self.stop_requested.is_set():
            raise StopTrial()

    def mesh(self):
        """Build the worker's mesh from the ScalingConfig plan (local
        devices; on a multi-host pod jax.distributed makes jax.devices()
        span hosts — same code path)."""
        from ..parallel.mesh import make_mesh
        from ..parallel.plan import ParallelPlan
        import jax

        plan = self.plan or ParallelPlan.auto(len(jax.devices()))
        return make_mesh(plan)


class StopTrial(BaseException):
    """Raised inside a trial when the scheduler early-stops it."""


_local = threading.local()


def _set_session(s: Optional[_TrainSession]):
    _local.session = s


def _get_session() -> Optional[_TrainSession]:
    return getattr(_local, "session", None)


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_checkpoint():
    """The checkpoint this trial/worker should resume from, or None
    (reference: ray.train.get_checkpoint)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("No active training session")
    return s.start_checkpoint


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        raise RuntimeError("No active training session")
    return TrainContext(s.rank, s.world_size, s)


def get_dataset_shard(name: str = "train"):
    s = _get_session()
    if s is None:
        raise RuntimeError("No active training session")
    if name not in s.dataset_shards:
        raise KeyError(
            f"No dataset shard {name!r}; have {sorted(s.dataset_shards)}")
    return s.dataset_shards[name]


def get_mesh():
    s = _get_session()
    if s is None:
        raise RuntimeError("No active training session")
    return s.mesh()
