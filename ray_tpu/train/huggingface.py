"""HF Transformers integration.

Capability-equivalent to the reference's transformers glue
(reference: python/ray/train/huggingface/transformers/
_transformers_utils.py — RayTrainReportCallback forwarding HF Trainer
logs/checkpoints into ray.train.report, prepare_trainer wiring it in).
Run a stock `transformers.Trainer` inside a TorchTrainer worker loop;
the callback streams HF's logs + saved checkpoints to the driver
through the session report channel.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional


def _trainer_callback_base():
    from transformers.trainer_callback import TrainerCallback

    return TrainerCallback


class RayTrainReportCallback(_trainer_callback_base()):
    """Forwards transformers Trainer events to ray_tpu.train.report
    (reference: _transformers_utils.py RayTrainReportCallback).

    - on_log: every HF log record (loss, lr, epoch…) becomes a report.
    - on_save: the just-written HF checkpoint directory rides along as
      the report's checkpoint, so CheckpointConfig retention and
      Result.checkpoint work unchanged.
    """

    def __init__(self):
        self._last_metrics: Dict[str, Any] = {}

    def on_log(self, args, state, control, logs=None, **kwargs):
        from . import session

        if not logs:
            return
        metrics = {k: v for k, v in logs.items()
                   if isinstance(v, (int, float))}
        metrics["step"] = state.global_step
        metrics["epoch"] = float(state.epoch or 0.0)
        self._last_metrics = metrics
        session.report(metrics)

    def on_save(self, args, state, control, **kwargs):
        from . import session
        from .checkpoint import Checkpoint

        ckpt_dir = os.path.join(
            args.output_dir, f"checkpoint-{state.global_step}")
        if not os.path.isdir(ckpt_dir):
            return
        metrics = dict(self._last_metrics)
        metrics["step"] = state.global_step
        session.report(metrics,
                       checkpoint=Checkpoint.from_directory(ckpt_dir))


def prepare_trainer(trainer):
    """Attach RayTrainReportCallback if absent and return the trainer
    (reference: _transformers_utils.py prepare_trainer)."""
    has = any(isinstance(cb, RayTrainReportCallback)
              for cb in trainer.callback_handler.callbacks)
    if not has:
        trainer.add_callback(RayTrainReportCallback())
    return trainer


class TransformersTrainer:
    """Convenience wrapper (reference capability:
    TransformersTrainer, deprecated in the reference in favor of
    TorchTrainer + prepare_trainer — both shapes work here).

    trainer_init_per_worker(config) -> transformers.Trainer runs on
    each worker; the HF Trainer's own torch.distributed support picks
    up the gloo process group TorchTrainer already created.
    """

    def __init__(self, trainer_init_per_worker, *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config=None, run_config=None,
                 torch_config=None):
        from .torch import TorchTrainer

        def loop(config: Optional[Dict[str, Any]] = None) -> None:
            hf_trainer = trainer_init_per_worker(config or {})
            prepare_trainer(hf_trainer)
            hf_trainer.train()

        self._inner = TorchTrainer(
            loop, train_loop_config=train_loop_config,
            scaling_config=scaling_config, run_config=run_config,
            torch_config=torch_config)

    def fit(self):
        return self._inner.fit()


def default_training_args(output_dir: Optional[str] = None, **overrides):
    """TrainingArguments tuned for this runtime: no hub/external
    reporting, CPU-only unless overridden."""
    from transformers import TrainingArguments

    kw: Dict[str, Any] = dict(
        output_dir=output_dir or tempfile.mkdtemp(prefix="hf_out_"),
        report_to=[],
        use_cpu=True,
        save_strategy="no",
        logging_steps=1,
        disable_tqdm=True,
    )
    kw.update(overrides)
    return TrainingArguments(**kw)
