"""TorchTrainer — distributed torch training over worker processes.

Capability-equivalent of the reference's torch Train path
(reference: python/ray/train/torch/torch_trainer.py:14 TorchTrainer;
torch/config.py:62 _setup_torch_process_group — rank-0 TCP rendezvous +
dist.init_process_group; torch/train_loop_utils.py:74 prepare_model
(DDP wrap) and :116 prepare_data_loader (DistributedSampler)): each
worker runs in its own PROCESS (the spawned-worker plane — gloo process
groups are per-process), rendezvouses over a TCP init_method, and runs
the user loop with ray_tpu.train.report() streaming back to the driver.

On this framework torch runs CPU/gloo (the TPU compute path is jax);
the capability carried over is the reference's worker-group
orchestration + DDP data parallelism for torch workloads.
"""

from __future__ import annotations

import inspect
import socket
from typing import Any, Callable, Dict, Optional

from .config import RunConfig, ScalingConfig
from .trainer import ProcessPlaneTrainerMixin, Result, TpuTrainer


class TorchConfig:
    """(reference: train/torch/config.py TorchConfig)."""

    def __init__(self, backend: str = "gloo",
                 init_timeout_s: float = 120.0):
        self.backend = backend
        self.init_timeout_s = init_timeout_s


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_torch_loop(user_fn: Callable, backend: str, addr: str,
                     timeout_s: float) -> Callable:
    """Wrap the user loop with process-group setup/teardown (reference:
    _TorchBackend.on_start → _setup_torch_process_group)."""
    takes_config = len(inspect.signature(user_fn).parameters) >= 1

    def loop(config: Optional[Dict[str, Any]] = None) -> None:
        import datetime

        import torch.distributed as dist

        from .session import get_context

        ctx = get_context()
        dist.init_process_group(
            backend,
            init_method=f"tcp://{addr}",
            rank=ctx.get_world_rank(),
            world_size=ctx.get_world_size(),
            timeout=datetime.timedelta(seconds=timeout_s))
        try:
            if takes_config and config is not None:
                user_fn(config)
            else:
                user_fn()
        finally:
            dist.destroy_process_group()

    return loop


class TorchTrainer(ProcessPlaneTrainerMixin, TpuTrainer):
    """TorchTrainer(train_loop_per_worker, scaling_config=
    ScalingConfig(num_workers=N)).fit() — the reference surface.

    Requires the out-of-process execution plane:
    ``ray_tpu.init(num_worker_procs=N)`` (gloo process groups need one
    OS process per rank)."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None):
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets)
        self.torch_config = torch_config or TorchConfig()
        self._user_loop = train_loop_per_worker
        self._init_process_plane()

    def fit(self) -> Result:
        self._require_worker_procs("TorchTrainer")
        return super().fit()

    def _fit_once(self, manager) -> Result:
        # Fresh rendezvous address per attempt: picking it at __init__
        # would race other port users until fit() AND reuse a possibly-
        # dead address across FailureConfig retries.
        tc = self.torch_config
        addr = f"127.0.0.1:{_free_port()}"
        self.train_loop = _make_torch_loop(
            self._user_loop, tc.backend, addr, tc.init_timeout_s)
        return super()._fit_once(manager)


# ---------------------------------------------------------------------------
# Loop utilities (reference: train/torch/train_loop_utils.py)
# ---------------------------------------------------------------------------

def prepare_model(model):
    """Wrap in DistributedDataParallel when world_size > 1
    (reference: prepare_model :74 — DDP/FSDP wrap + device move; here
    CPU/gloo, so the wrap is the capability)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Re-create the DataLoader with a DistributedSampler so each rank
    sees its shard (reference: prepare_data_loader :116). Loaders a
    DistributedSampler cannot shard (IterableDataset, custom
    batch_sampler) are returned unchanged with a warning."""
    import warnings

    import torch
    import torch.distributed as dist
    from torch.utils.data import DataLoader, DistributedSampler

    if not (dist.is_initialized() and dist.get_world_size() > 1):
        return data_loader
    if isinstance(data_loader.dataset,
                  torch.utils.data.IterableDataset):
        warnings.warn(
            "prepare_data_loader: IterableDataset cannot use a "
            "DistributedSampler; shard inside the dataset instead. "
            "Returning the loader unchanged.")
        return data_loader
    if not isinstance(
            data_loader.batch_sampler,
            torch.utils.data.sampler.BatchSampler):
        warnings.warn(
            "prepare_data_loader: custom batch_sampler is not "
            "re-shardable; returning the loader unchanged.")
        return data_loader
    sampler = DistributedSampler(
        data_loader.dataset, num_replicas=dist.get_world_size(),
        rank=dist.get_rank(),
        shuffle=not isinstance(
            data_loader.sampler, torch.utils.data.SequentialSampler))
    return DataLoader(
        data_loader.dataset, batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
        persistent_workers=data_loader.persistent_workers)
