"""Hyperparameter search over an RL algorithm with Tune.

DQN on GridWorld as a Tune trainable; ASHA stops weak lrs early.

    python examples/tune_rl.py
"""

import tempfile

import ray_tpu as ray
import ray_tpu.tune as tune
from ray_tpu.rl import DQN, DQNConfig


def main():
    ray.init(num_cpus=4, num_tpus=0)

    base = DQNConfig(env="GridWorld", num_env_runners=1,
                     num_envs_per_runner=8, rollout_length=32,
                     hidden=(32,), learning_starts=256, batch_size=64,
                     updates_per_iteration=8, epsilon_decay_iters=10,
                     train_iterations=15)
    trainable = DQN.as_trainable(base)

    res = tune.run(
        trainable,
        config={"lr": tune.grid_search([3e-4, 1e-3, 3e-3])},
        metric="episode_return_mean", mode="max",
        scheduler=tune.ASHAScheduler(
            metric="episode_return_mean", mode="max", max_t=15,
            grace_period=5),
        storage_path=tempfile.mkdtemp(),
        max_concurrent_trials=1,
    )
    best = res.get_best_result()
    print(f"best lr={best.config['lr']}: "
          f"return={best.metrics['episode_return_mean']:.2f}")
    ray.shutdown()


if __name__ == "__main__":
    main()
