"""Serve an LLM across multiple chips (tensor + fsdp parallel replica).

The engine lays weights out by their logical axes (heads/mlp/vocab
ride tp, embed rides fsdp) and shards the KV cache across kv-heads;
the compiled prefill/decode steps then run SPMD over the mesh with XLA
collectives over ICI. This is how an 8B-class model that cannot fit
one 16 GiB chip serves (tp=4/fsdp=2 over 8 chips); the demo runs the
same code path with a tiny model on a virtual 4-device CPU mesh and
checks the sharded engine's greedy tokens equal the single-chip
engine's.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=4 \
     JAX_PLATFORMS=cpu python examples/serve_llm_tp.py
"""

import os

# Hard-set (not setdefault): this demo runs a tiny random-weight model
# on a virtual CPU mesh — it must not grab a real TPU chip (the box's
# sitecustomize exports JAX_PLATFORMS=axon, which would win a default).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Robust 4-device provisioning (handles a pre-set smaller XLA_FLAGS
# count and an already-initialized backend alike).
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from __graft_entry__ import _provision_virtual_devices  # noqa: E402

if len(jax.devices()) < 4:
    _provision_virtual_devices(4)
import numpy as np  # noqa: E402

from ray_tpu.models import configs  # noqa: E402
from ray_tpu.models.transformer import init_params  # noqa: E402
from ray_tpu.parallel import ParallelPlan, make_mesh  # noqa: E402
from ray_tpu.serve.llm import LLMEngine  # noqa: E402


def run(mesh, params, cfg, prompts):
    eng = LLMEngine(cfg, params, num_slots=4, max_seq_len=128,
                    mesh=mesh)
    reqs = [eng.submit(p, max_new_tokens=16) for p in prompts]
    while eng.step():
        pass
    outs = [r.result(timeout=120) for r in reqs]
    eng._stop = True
    return outs


def main():
    cfg = configs.tiny_test()
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in (9, 17, 30, 12)]

    devices = jax.devices()
    print(f"{len(devices)} devices: {[d.platform for d in devices]}")

    single = run(None, params, cfg, prompts)
    plan = ParallelPlan(tp=2, fsdp=2)
    mesh = make_mesh(plan, devices=devices[:4])
    sharded = run(mesh, params, cfg, prompts)
    assert sharded == single, "sharded tokens diverged!"
    print(f"tp=2/fsdp=2 over {plan.num_devices} devices reproduces "
          f"single-chip tokens exactly:")
    for p, o in zip(prompts, sharded):
        print(f"  prompt[{len(p):2d} tok] -> {o[:8]}...")
    # The real 8B shape is the same call:
    #   LLMServer(configs.llama3_8b(), plan=ParallelPlan(tp=4, fsdp=2))


if __name__ == "__main__":
    main()
