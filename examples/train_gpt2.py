"""Train a GPT-2-class model on synthetic data with the TpuTrainer.

Runs on whatever jax sees: one TPU chip, a pod mesh, or (for smoke
runs) CPU. The ParallelPlan decides how the mesh axes are laid out —
the same script scales from 1 chip to a slice by changing the plan.

    python examples/train_gpt2.py            # tiny config, quick
    python examples/train_gpt2.py --full     # gpt2-125m shapes
"""

import argparse
import dataclasses

import jax
import jax.numpy as jnp

from ray_tpu.models import configs
from ray_tpu.parallel import ParallelPlan, make_mesh
from ray_tpu.train.step import (
    init_state,
    make_optimizer,
    make_train_step,
    shard_batch,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    cfg = configs.gpt2_125m() if args.full else configs.tiny_test()
    batch, seq = (16, 1024) if args.full else (8, 128)

    n = len(jax.devices())
    plan = ParallelPlan.auto(n) if n > 1 else ParallelPlan()
    mesh = make_mesh(plan, devices=jax.devices()[:plan.num_devices])
    opt = make_optimizer(lr=3e-4, warmup_steps=5, total_steps=1000)

    with jax.sharding.set_mesh(mesh):
        state = init_state(cfg, mesh, opt, seed=0)
        step = make_train_step(cfg, opt)
        k = jax.random.key(0)
        tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        b = shard_batch({
            "t": tokens,
            "y": jnp.roll(tokens, -1, axis=1),
            "m": jnp.ones((batch, seq), jnp.float32),
        }, mesh)
        for i in range(args.steps):
            state, metrics = step(state, b["t"], b["y"], b["m"])
            print(f"step {i}: loss={float(metrics['loss']):.4f} "
                  f"grad_norm={float(metrics['grad_norm']):.3f}")


if __name__ == "__main__":
    main()
