"""Data pipeline → sharded training ingest.

A Dataset is transformed lazily, shuffled, and streaming_split into
per-worker iterators — the standard train-ingest shape
(reference pattern: Dataset.streaming_split feeding Train workers).

    python examples/data_to_train.py
"""

import numpy as np

import ray_tpu as ray
import ray_tpu.data as data


def main():
    ray.init(num_cpus=2, num_tpus=0)

    # map_batches sees column-format batches ({"id": array}) and
    # returns columns.
    ds = (data.range(1000)
          .map_batches(lambda b: {"x": b["id"],
                                  "y": [v % 7 for v in b["id"]]})
          .random_shuffle(seed=0))

    shards = ds.streaming_split(2, equal=True)

    def consume(it, rank):
        n = 0
        for batch in it.iter_batches(batch_size=64):
            n += len(batch["x"]) if isinstance(batch, dict) \
                else len(batch)
        print(f"worker {rank}: consumed {n} rows")
        return n

    import threading

    counts = [0, 0]
    threads = [threading.Thread(
        target=lambda r=r: counts.__setitem__(r, consume(shards[r], r)))
        for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(counts) == 1000
    print("stats:\n" + ds.stats())
    ray.shutdown()


if __name__ == "__main__":
    main()
