"""Drive native ZeRO-sharded training from a DeepSpeed JSON config.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python examples/deepspeed_config_train.py

A reference user's ds_config carries over unchanged: the stages become
sharding declarations (stage 2 = optimizer-state sharded over the fsdp
mesh axis, params whole; stage 3 = params sharded too), XLA inserts the
reduce-scatter/all-gather collectives.
"""

import os

# Hard-set (not setdefault): this example demonstrates an 8-device mesh,
# which needs the virtual CPU platform when only one real chip exists.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip()

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import configs
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.train import (
    init_zero_state,
    make_zero_train_step,
    translate_deepspeed_config,
)

DS_CONFIG = {
    "train_batch_size": 64,
    "gradient_accumulation_steps": 2,
    "zero_optimization": {"stage": 2},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW",
                  "params": {"lr": 3e-4, "betas": [0.9, 0.95],
                             "weight_decay": 0.1}},
    "scheduler": {"type": "WarmupLR",
                  "params": {"warmup_num_steps": 10,
                             "total_num_steps": 100}},
}


def main():
    n = len(jax.devices())
    t = translate_deepspeed_config(DS_CONFIG, n_devices=n)
    print(f"stage={t.stage} plan={t.plan.describe()} "
          f"micro_batch/device={t.micro_batch_per_device} "
          f"accum={t.gradient_accumulation_steps} dtype={t.dtype.__name__}")

    cfg = configs.tiny_test()
    mesh = make_mesh(t.plan)
    opt = t.make_optimizer()
    rng = np.random.default_rng(0)
    B = t.micro_batch_per_device * n
    tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 32)), jnp.int32)
    mask = jnp.ones((B, 32), jnp.float32)

    with jax.sharding.set_mesh(mesh):
        state = init_zero_state(cfg, mesh, opt, stage=t.stage)
        step = make_zero_train_step(cfg, opt, mesh, stage=t.stage)
        for i in range(5):
            state, metrics = step(state, tok, tok, mask)
            print(f"step {i}: loss={float(metrics['loss']):.4f}")

    # The ZeRO property, visible in the shardings:
    mu_leaf = [x for x in jax.tree.leaves(state.opt_state)
               if hasattr(x, "sharding") and x.ndim >= 2][0]
    p_leaf = [x for x in jax.tree.leaves(state.params) if x.ndim >= 2][0]
    print(f"param spec:     {p_leaf.sharding.spec}")
    print(f"opt-state spec: {mu_leaf.sharding.spec}")


if __name__ == "__main__":
    main()
