"""Distributed gradient-boosted trees on the trainer gang.

Run: python examples/gbdt_train.py

Mirrors the reference's XGBoostTrainer example (reference:
doc/source/train/examples/xgboost/): datasets flow in as ray_tpu.data
Datasets, each worker holds a shard, per-level gradient histograms are
allreduced across the gang, and the fitted booster comes back through
the checkpoint.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pandas as pd

import ray_tpu as ray
from ray_tpu import data
from ray_tpu.train import (
    LightGBMTrainer,
    RunConfig,
    ScalingConfig,
    XGBoostTrainer,
)


def make_frame(n, seed):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = 2.5 * X[:, 0] - X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=n)
    df = pd.DataFrame({f"f{i}": X[:, i] for i in range(6)})
    df["target"] = y
    return df


def main():
    ray.init(num_cpus=4, num_tpus=0)
    train_ds = data.from_pandas(make_frame(4000, 0)).repartition(8)
    valid_ds = data.from_pandas(make_frame(800, 1))

    result = XGBoostTrainer(
        params={
            "objective": "reg:squarederror",
            "eta": 0.3,
            "max_depth": 6,
            "subsample": 0.9,
        },
        label_column="target",
        datasets={"train": train_ds, "valid": valid_ds},
        num_boost_round=50,
        early_stopping_rounds=8,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="gbdt_example"),
    ).fit()

    model = XGBoostTrainer.get_model(result.checkpoint)
    print(f"boosted {model.num_boosted_rounds} rounds; "
          f"last metrics: {result.metrics_history[-2]}")
    print(f"feature importances: {model.feature_importances().round(1)}")

    # Same data through the LightGBM dialect (leaf-wise growth).
    result2 = LightGBMTrainer(
        params={"objective": "regression", "num_leaves": 31,
                "learning_rate": 0.15, "metric": "l2"},
        label_column="target",
        datasets={"train": train_ds},
        num_boost_round=30,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="lgbm_example"),
    ).fit()
    model2 = LightGBMTrainer.get_model(result2.checkpoint)
    holdout = make_frame(500, 2)
    pred = model2.predict(holdout)  # DataFrame: columns aligned by name
    rmse = float(np.sqrt(np.mean((pred - holdout["target"]) ** 2)))
    print(f"lightgbm-dialect holdout rmse: {rmse:.4f}")
    ray.shutdown()


if __name__ == "__main__":
    main()
