"""Multi-host cluster walkthrough — runnable on one machine.

What it shows (the reference's `ray start` + driver + detached-actor
flow, on the daemon plane):
  1. a control plane + two node daemons as separate OS processes,
  2. a driver joining with init(address=...), spreading tasks and a
     placement group across daemons,
  3. a named DETACHED actor surviving the driver and being re-attached
     by a second driver,
  4. fault tolerance: killing a daemon, lineage reconstruction on the
     survivor.

Run:  python examples/multihost_cluster.py
(On real hosts you would instead run `ray-tpu start --head --bind-all`
on one machine, `ray-tpu start --address=HEAD:PORT --bind-all` on the
others, and pass that address to init().)
"""

import numpy as np

import ray_tpu as ray
from ray_tpu.cluster_utils import RealCluster


def main() -> None:
    cluster = RealCluster()
    try:
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)
        print(f"cluster control plane: {cluster.address}")

        # ---- driver 1 -------------------------------------------------
        ray.init(address=cluster.address)

        @ray.remote
        def where(x):
            import os

            return x, os.getpid()

        out = ray.get([where.remote(i) for i in range(8)])
        print("tasks ran in worker pids:",
              sorted({pid for _x, pid in out}))

        # A placement group SPREAD across both daemons.
        pg = ray.placement_group([{"CPU": 1}, {"CPU": 1}],
                                 strategy="SPREAD")
        ray.get(pg.ready())
        print("placement group bundles on:", pg.bundle_nodes(-1))
        ray.remove_placement_group(pg)

        # Objects move arena→arena over the native transfer plane.
        @ray.remote
        def make():
            return np.arange(250_000, dtype=np.float32)

        @ray.remote
        def consume(a):
            return float(a.sum())

        ref = make.remote()
        print("cross-node consume:", ray.get(consume.remote(ref)))

        # A named detached actor: outlives this driver.
        @ray.remote(lifetime="detached", name="kv")
        class KV:
            def __init__(self):
                self.d = {}

            def put(self, k, v):
                self.d[k] = v
                return len(self.d)

            def get(self, k):
                return self.d.get(k)

        kv = KV.remote()
        ray.get(kv.put.remote("round", 2))
        ray.shutdown()
        print("driver 1 exited; detached actor lives on")

        # ---- driver 2 -------------------------------------------------
        ray.init(address=cluster.address)
        kv2 = ray.get_actor("kv")
        print("driver 2 reads driver 1's state:",
              ray.get(kv2.get.remote("round")))

        # ---- fault tolerance ------------------------------------------
        big = make.remote()
        ray.get(big)  # materialize on some daemon
        from ray_tpu.core.runtime import global_runtime

        rt = global_runtime()
        stored = rt.store.get_if_exists(big.id())
        home = getattr(stored.data, "node_id", None) if stored else None
        if home is None:
            print("object landed inline; skipping the kill demo")
            ray.kill(kv2)
            ray.shutdown()
            return
        if rt.shm is not None:
            rt.shm.delete(big.id().binary())  # drop the local copy
        print(f"killing {home} (holds the only copy)…")
        cluster.kill_node(home)
        arr = ray.get(big, timeout=60)  # lineage reconstruction
        print("reconstructed on the survivor:", arr.shape)

        ray.kill(kv2)
        ray.shutdown()
    finally:
        cluster.shutdown()
    print("done")


if __name__ == "__main__":
    main()
