"""Model-based RL: train Dreamer (world model + latent imagination)
on CartPole.

The world model learns the env's dynamics from replayed sequences;
the actor-critic never touches the real env during its updates — it
trains on rollouts imagined inside the model (pure latent lax.scan
compute, ideal accelerator work).

    PYTHONPATH=. python examples/dreamer_rl.py
"""

from ray_tpu.rl import Dreamer, DreamerConfig

algo = Dreamer(DreamerConfig(
    env="CartPole", num_envs=8, rollout_length=32, seed=1))

# Expect: model_loss falls steadily (the world model fitting the
# dynamics) and imagined_return climbs as the actor improves inside
# the model. Real episode return improves later and is seed-sensitive
# at this tiny scale — model-based learning is warm-up heavy: the
# actor only gets useful gradients once the model is trustworthy, so
# give it a few hundred iterations (and seeds) to master the env.
for result in algo.train(30):
    it = result["training_iteration"]
    ret = result["episode_return_mean"]
    wm = result.get("model_loss", float("nan"))
    im = result.get("imagined_return", float("nan"))
    print(f"iter {it:2d}: return={ret:6.1f} "
          f"model_loss={wm:6.2f} imagined_return={im:5.2f}",
          flush=True)

algo.stop()
