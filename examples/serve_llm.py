"""Serve a (randomly initialized) LLM with continuous batching.

Demonstrates the serving stack end to end: a deployment wrapping the
continuous-batching LLMEngine, HTTP ingress, and concurrent requests
sharing decode ticks.

    python examples/serve_llm.py
"""

import json
import os
import threading
import urllib.request

# Hard-set (not setdefault): this demo serves a tiny random-weight model
# — it must not grab (or fail to share) a real TPU chip another process
# holds. Real-chip serving runs through `python bench.py --serve`.
os.environ["JAX_PLATFORMS"] = "cpu"

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu.models import configs

PORT = 18260


def main():
    ray.init(num_cpus=2, num_tpus=0)

    # The shared system prompt every request starts with: registered
    # once per replica, its prefill cost is paid once (prefix caching);
    # auto_prefix_min_hits would capture it automatically instead.
    SYSTEM_PROMPT = list(range(1, 17))

    @serve.deployment
    class Llm:
        def __init__(self):
            from ray_tpu.serve.llm import LLMServer

            self.server = LLMServer(configs.tiny_test(), num_slots=4,
                                    max_seq_len=128)
            self.server.register_prefix(SYSTEM_PROMPT)

        def __call__(self, payload):
            out = self.server.generate(
                SYSTEM_PROMPT + payload["prompt"],
                max_new_tokens=payload.get("max_tokens", 16))
            st = self.server.stats()
            return {"tokens": out["tokens"],
                    "ttft_ms": round(out["ttft_s"] * 1e3, 1),
                    "prefix_hits": st["prefix_hits"]}

    serve.run(Llm.bind(), name="llm", http=True, http_port=PORT)

    def ask(prompt):
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT}/llm",
            data=json.dumps({"prompt": prompt}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.load(r)["result"]

    threads, results = [], []
    for i in range(4):  # concurrent requests share the decode batch
        t = threading.Thread(
            target=lambda i=i: results.append(ask([1 + i, 2, 3])))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    for r in results:
        print(f"{len(r['tokens'])} tokens, TTFT {r['ttft_ms']}ms")
    serve.shutdown()
    ray.shutdown()


if __name__ == "__main__":
    main()
