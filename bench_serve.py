"""Serve overload bench — bounded latency under 2× capacity, not
collapse.

Drives a multi-replica deployment with a mixed-priority open-loop burst
at twice its measured capacity, kills a replica mid-burst, and records:

  - unloaded p99 TTFT (baseline)
  - p99 TTFT of ADMITTED high-priority requests under overload
    (gate: ≤ 3× unloaded p99 — the SLO the priority lane exists for)
  - shed rate (bounded queues shedding instead of queueing forever)
  - goodput (admitted completions/s) and retries (replica-kill replays)
  - hung clients (gate: 0 — every request resolves: result, 429, or a
    typed unavailability error)

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ...}

Run:  python bench_serve.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def emit(metric: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 4),
                      "unit": unit, **extra}), flush=True)
    try:
        import bench

        bench.push_history("serve_" + metric, value, unit,
                           match={}, extra=extra)
    except Exception:  # noqa: BLE001 - recording must not fail the run
        pass


def _p(sorted_xs, q):
    if not sorted_xs:
        return 0.0
    return sorted_xs[min(len(sorted_xs) - 1, int(len(sorted_xs) * q))]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--service-time-s", type=float, default=0.05)
    ap.add_argument("--burst-s", type=float, default=None)
    args = ap.parse_args()
    burst_s = args.burst_s or (4.0 if args.quick else 10.0)

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=max(4, args.replicas + 1), num_tpus=0)
    service_s = args.service_time_s

    @serve.deployment(num_replicas=args.replicas,
                      max_ongoing_requests=2,
                      max_queued_requests=8,
                      max_request_retries=4)
    def infer(_payload):
        time.sleep(service_s)
        return {"ok": True}

    handle = serve.run(infer.bind(), name="infer", http=False)

    # -- unloaded baseline: sequential requests, p99 "TTFT" ------------
    lat = []
    for _ in range(40 if args.quick else 100):
        t0 = time.perf_counter()
        handle.remote({}).result(timeout=30)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    unloaded_p99 = _p(lat, 0.99)
    emit("unloaded_p99_ttft", unloaded_p99, "s")

    # Measured capacity: max_ongoing × replicas slots, each serving one
    # request per service time.
    capacity_rps = (2 * args.replicas) / service_s
    offered_rps = 2.0 * capacity_rps
    emit("offered_load", offered_rps, "req/s",
         capacity=round(capacity_rps, 1))

    # -- 2× capacity mixed-priority burst + replica kill mid-burst -----
    results = {"hi": [], "lo": []}   # latencies of admitted completions
    shed = {"hi": 0, "lo": 0}
    errors = 0
    hung = 0
    lock = threading.Lock()
    threads = []
    stop_at = time.monotonic() + burst_s

    def client(priority_name: str, priority: int):
        nonlocal errors, hung
        h = handle.options(priority=priority)
        t0 = time.perf_counter()
        try:
            fut = h.remote({})
        except serve.BackPressureError:
            with lock:
                shed[priority_name] += 1
            return
        try:
            fut.result(timeout=60)
            with lock:
                results[priority_name].append(
                    time.perf_counter() - t0)
        except serve.BackPressureError:
            with lock:
                shed[priority_name] += 1
        except (serve.ReplicaUnavailableError,
                serve.DeploymentUnavailableError):
            with lock:
                errors += 1
        except Exception:  # noqa: BLE001 — incl. GetTimeoutError
            with lock:
                hung += 1

    interval = 1.0 / offered_rps
    killed = False
    n_sent = 0
    t_start = time.monotonic()
    while time.monotonic() < stop_at:
        # 20% high priority, 80% low — deterministic interleave.
        pri = ("hi", 1) if n_sent % 5 == 0 else ("lo", 0)
        t = threading.Thread(target=client, args=pri, daemon=True)
        t.start()
        threads.append(t)
        n_sent += 1
        if not killed and time.monotonic() - t_start > burst_s / 2:
            # Replica kill mid-burst: in-flight requests replay, the
            # controller replaces the corpse, zero clients hang.
            controller = handle._controller
            replicas, _ = ray_tpu.get(
                controller.get_replicas.remote("infer"))
            ray_tpu.kill(replicas[0])
            killed = True
            emit("replica_killed_at", time.monotonic() - t_start, "s")
        time.sleep(interval)
    for t in threads:
        t.join(timeout=90)
        if t.is_alive():
            hung += 1
    wall = time.monotonic() - t_start

    hi = sorted(results["hi"])
    lo = sorted(results["lo"])
    total_shed = shed["hi"] + shed["lo"]
    admitted = len(hi) + len(lo)
    loaded_p99_hi = _p(hi, 0.99)
    emit("loaded_p99_ttft_high_priority", loaded_p99_hi, "s",
         n=len(hi))
    emit("loaded_p99_ttft_low_priority", _p(lo, 0.99), "s", n=len(lo))
    emit("shed_rate", total_shed / max(1, n_sent), "fraction",
         shed_hi=shed["hi"], shed_lo=shed["lo"], sent=n_sent)
    emit("goodput", admitted / wall, "req/s")
    emit("unavailable_errors", errors, "count")
    emit("hung_clients", hung, "count")
    snap = handle._router.admission.snapshot()
    emit("leaked_ongoing", snap["ongoing"] + snap["queued"], "count")

    ok = True
    if hung:
        print(f"FAIL: {hung} hung clients", flush=True)
        ok = False
    if snap["ongoing"] or snap["queued"]:
        print(f"FAIL: admission leak {snap}", flush=True)
        ok = False
    if unloaded_p99 > 0 and hi and loaded_p99_hi > 3 * unloaded_p99:
        print(f"FAIL: high-priority p99 {loaded_p99_hi:.3f}s exceeds "
              f"3x unloaded p99 {unloaded_p99:.3f}s", flush=True)
        ok = False
    if total_shed == 0:
        print("WARN: no shedding at 2x capacity (burst too short?)",
              flush=True)

    serve.shutdown()
    ray_tpu.shutdown()
    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
