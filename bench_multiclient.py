"""Multi-client dispatch-plane scaling (VERDICT r4 #5).

Reference bar: release_logs/2.9.0/microbenchmark.json publishes
MULTI-CLIENT rows (24.3k tasks/s, 26.7k n:n actor calls/s on 64 cores);
every repo number so far was single-driver. This bench runs the same
shapes with N separate DRIVER PROCESSES joined to one real daemon
plane (control-plane daemon + node-daemon OS processes) and records
per-client and aggregate rates for N = 1, 2, 4 — the per-client
degradation curve is the scaling story for the dispatch plane on this
1-core box (clients, daemons, and workers all share one core, so the
aggregate ceiling here is the core, not the protocol; the recorded
curve shows how gracefully the plane shares it).

Run: python bench_multiclient.py [--quick]
Prints one JSON line per N; records scale_multiclient_* in
BENCH_HISTORY.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_CHILD = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n_tasks, n_calls = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
# num_cpus=0: this driver contributes no execution resources, so every
# task goes through the daemon dispatch plane (the thing under test).
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers
t0 = time.perf_counter()
ray.get([noop.remote() for _ in range(n_tasks)])
task_dt = time.perf_counter() - t0

@ray.remote
class Echo:
    def ping(self):
        return None

a = Echo.remote()
ray.get(a.ping.remote())
t0 = time.perf_counter()
ray.get([a.ping.remote() for _ in range(n_calls)])
act_dt = time.perf_counter() - t0
print(json.dumps({"tasks_s": n_tasks / task_dt,
                  "actor_calls_s": n_calls / act_dt}))
"""


def run_clients(addr: str, n_clients: int, n_tasks: int,
                n_calls: int) -> dict:
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, addr, str(n_tasks), str(n_calls)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
        for _ in range(n_clients)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        line = out.strip().splitlines()[-1]
        outs.append(json.loads(line))
    return {
        "clients": n_clients,
        "agg_tasks_s": sum(o["tasks_s"] for o in outs),
        "per_client_tasks_s": [round(o["tasks_s"], 1) for o in outs],
        "agg_actor_calls_s": sum(o["actor_calls_s"] for o in outs),
        "per_client_actor_calls_s": [round(o["actor_calls_s"], 1)
                                     for o in outs],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n_tasks = 200 if args.quick else 2000
    n_calls = 200 if args.quick else 2000

    from ray_tpu.cluster_utils import RealCluster

    cluster = RealCluster()
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=4)
        base = None
        for n in (1, 2, 4):
            r = run_clients(cluster.address, n, n_tasks, n_calls)
            if base is None:
                base = r
            # Degradation: per-client rate vs the single-client rate.
            r["tasks_per_client_vs_1"] = round(
                (r["agg_tasks_s"] / n) / base["agg_tasks_s"], 3)
            r["actor_calls_per_client_vs_1"] = round(
                (r["agg_actor_calls_s"] / n)
                / base["agg_actor_calls_s"], 3)
            print(json.dumps({
                "metric": f"multiclient_{n}",
                "value": round(r["agg_tasks_s"], 1),
                "unit": "tasks/s", **{k: v for k, v in r.items()
                                      if k != "clients"}}), flush=True)
            try:
                import bench

                bench.push_history(
                    f"scale_multiclient_{n}_tasks_s",
                    r["agg_tasks_s"], "tasks/s", match={},
                    extra={"per_client": r["per_client_tasks_s"],
                           "vs_1client": r["tasks_per_client_vs_1"]})
                bench.push_history(
                    f"scale_multiclient_{n}_actor_calls_s",
                    r["agg_actor_calls_s"], "calls/s", match={},
                    extra={"per_client": r["per_client_actor_calls_s"],
                           "vs_1client": r["actor_calls_per_client_vs_1"]})
            except Exception:  # noqa: BLE001
                pass
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
