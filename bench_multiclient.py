"""Multi-client dispatch-plane scaling (VERDICT r4 #5; ISSUE 10 #1).

Reference bar: release_logs/2.9.0/microbenchmark.json publishes
MULTI-CLIENT rows (24.3k tasks/s, 26.7k n:n actor calls/s on 64 cores);
every repo number so far was single-driver. This bench runs the same
shapes with N separate DRIVER PROCESSES joined to one real daemon
plane (control-plane daemon + node-daemon OS processes) and records
per-client and aggregate rates for N = 1, 2, 4 — the per-client
degradation curve is the scaling story for the dispatch plane on this
1-core box (clients, daemons, and workers all share one core, so the
aggregate ceiling here is the core, not the protocol; the recorded
curve shows how gracefully the plane shares it).

A second shape, the THREAD STORM, runs N driver threads in ONE
process, each doing synchronous task round-trips against the daemon.
Separate driver processes all burn CPU pickling, so on one core a
throughput drop could be core saturation rather than the daemon
serializing; one storming process caps driver-side CPU at ~one
thread's worth (the driver GIL), so the aggregate curve across thread
counts isolates how the DAEMON's dispatch loop handles concurrent
in-flight requests. A loop that serializes request handling (the
pure-Python plane, which parses/admits/replies under its GIL in one
loop thread) holds aggregate flat-to-down as threads rise; the native
plane (src/node_dispatch.cc: epoll + off-GIL admission) should let
concurrent round-trips overlap.

Both shapes run under RAY_TPU_NATIVE_DISPATCH=1 and =0 and record
scale_multiclient_* / scale_threadstorm_* rows in BENCH_HISTORY.json
with a `dispatch` match key, so native and Python curves form separate
comparable series.

Run: python bench_multiclient.py [--quick] [--dispatch native|python|both]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_CHILD = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n_tasks, n_calls = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
# num_cpus=0: this driver contributes no execution resources, so every
# task goes through the daemon dispatch plane (the thing under test).
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers
t0 = time.perf_counter()
ray.get([noop.remote() for _ in range(n_tasks)])
task_dt = time.perf_counter() - t0

@ray.remote
class Echo:
    def ping(self):
        return None

a = Echo.remote()
ray.get(a.ping.remote())
t0 = time.perf_counter()
ray.get([a.ping.remote() for _ in range(n_calls)])
act_dt = time.perf_counter() - t0
print(json.dumps({"tasks_s": n_tasks / task_dt,
                  "actor_calls_s": n_calls / act_dt}))
"""

_STORM_CHILD = r"""
import json, os, sys, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n_threads, per_thread = (sys.argv[1], int(sys.argv[2]),
                               int(sys.argv[3]))
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers

# Each thread does SYNCHRONOUS round-trips: submit one task, wait for
# its result, repeat. One thread measures latency; N threads measure
# whether N concurrent in-flight requests overlap in the daemon (the
# driver GIL is released for the whole socket wait, so driver-side
# serialization costs only the pickling slice).
counts = [0] * n_threads
gate = threading.Barrier(n_threads + 1)

def storm(i):
    gate.wait()
    for _ in range(per_thread):
        ray.get(noop.remote())
        counts[i] += 1

threads = [threading.Thread(target=storm, args=(i,), daemon=True)
           for i in range(n_threads)]
for t in threads:
    t.start()
gate.wait()
t0 = time.perf_counter()
for t in threads:
    t.join()
dt = time.perf_counter() - t0
print(json.dumps({"tasks_s": sum(counts) / dt}))
"""


def run_clients(addr: str, n_clients: int, n_tasks: int,
                n_calls: int) -> dict:
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, addr, str(n_tasks), str(n_calls)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
        for _ in range(n_clients)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        line = out.strip().splitlines()[-1]
        outs.append(json.loads(line))
    return {
        "clients": n_clients,
        "agg_tasks_s": sum(o["tasks_s"] for o in outs),
        "per_client_tasks_s": [round(o["tasks_s"], 1) for o in outs],
        "agg_actor_calls_s": sum(o["actor_calls_s"] for o in outs),
        "per_client_actor_calls_s": [round(o["actor_calls_s"], 1)
                                     for o in outs],
    }


def run_storm(addr: str, n_threads: int, per_thread: int) -> dict:
    p = subprocess.Popen(
        [sys.executable, "-c", _STORM_CHILD, addr, str(n_threads),
         str(per_thread)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    out, _ = p.communicate(timeout=600)
    line = out.strip().splitlines()[-1]
    r = json.loads(line)
    return {"threads": n_threads, "agg_tasks_s": r["tasks_s"]}


def run_suite(dispatch: str, n_tasks: int, n_calls: int,
              per_thread: int, record: bool = True) -> None:
    """One full pass (multiclient + thread storm) under one dispatch
    plane; nodes inherit RAY_TPU_NATIVE_DISPATCH via the env overlay."""
    from ray_tpu.cluster_utils import RealCluster

    env = {"RAY_TPU_NATIVE_DISPATCH":
           "1" if dispatch == "native" else "0"}
    bench = None
    if record:  # --quick runs print but don't pollute the history
        try:
            import bench
        except Exception:  # noqa: BLE001
            bench = None

    cluster = RealCluster()
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=4, env=env)
        base = None
        for n in (1, 2, 4):
            r = run_clients(cluster.address, n, n_tasks, n_calls)
            if base is None:
                base = r
            # Degradation: per-client rate vs the single-client rate.
            r["tasks_per_client_vs_1"] = round(
                (r["agg_tasks_s"] / n) / base["agg_tasks_s"], 3)
            r["actor_calls_per_client_vs_1"] = round(
                (r["agg_actor_calls_s"] / n)
                / base["agg_actor_calls_s"], 3)
            # Aggregate retention: the ISSUE 10 acceptance bar (4-driver
            # aggregate >= 90% of 1-driver aggregate, native).
            r["agg_vs_1client"] = round(
                r["agg_tasks_s"] / base["agg_tasks_s"], 3)
            print(json.dumps({
                "metric": f"multiclient_{n}", "dispatch": dispatch,
                "value": round(r["agg_tasks_s"], 1),
                "unit": "tasks/s", **{k: v for k, v in r.items()
                                      if k != "clients"}}), flush=True)
            if bench is not None:
                bench.push_history(
                    f"scale_multiclient_{n}_tasks_s",
                    r["agg_tasks_s"], "tasks/s",
                    match={"dispatch": dispatch},
                    extra={"per_client": r["per_client_tasks_s"],
                           "vs_1client": r["tasks_per_client_vs_1"],
                           "agg_vs_1client": r["agg_vs_1client"]})
                bench.push_history(
                    f"scale_multiclient_{n}_actor_calls_s",
                    r["agg_actor_calls_s"], "calls/s",
                    match={"dispatch": dispatch},
                    extra={"per_client": r["per_client_actor_calls_s"],
                           "vs_1client":
                               r["actor_calls_per_client_vs_1"]})
        storm_base = None
        for n in (1, 4, 8):
            s = run_storm(cluster.address, n, per_thread)
            if storm_base is None:
                storm_base = s
            s["agg_vs_1thread"] = round(
                s["agg_tasks_s"] / storm_base["agg_tasks_s"], 3)
            print(json.dumps({
                "metric": f"threadstorm_{n}", "dispatch": dispatch,
                "value": round(s["agg_tasks_s"], 1),
                "unit": "tasks/s",
                "agg_vs_1thread": s["agg_vs_1thread"]}), flush=True)
            if bench is not None:
                bench.push_history(
                    f"scale_threadstorm_{n}_tasks_s",
                    s["agg_tasks_s"], "tasks/s",
                    match={"dispatch": dispatch},
                    extra={"agg_vs_1thread": s["agg_vs_1thread"]})
    finally:
        cluster.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--dispatch", choices=["native", "python", "both"],
                    default="both")
    args = ap.parse_args()
    n_tasks = 200 if args.quick else 2000
    n_calls = 200 if args.quick else 2000
    per_thread = 50 if args.quick else 250

    modes = (["native", "python"] if args.dispatch == "both"
             else [args.dispatch])
    for mode in modes:
        run_suite(mode, n_tasks, n_calls, per_thread,
                  record=not args.quick)


if __name__ == "__main__":
    main()
