"""Multi-client dispatch-plane scaling (VERDICT r4 #5; ISSUE 10 #1).

Reference bar: release_logs/2.9.0/microbenchmark.json publishes
MULTI-CLIENT rows (24.3k tasks/s, 26.7k n:n actor calls/s on 64 cores);
every repo number so far was single-driver. This bench runs the same
shapes with N separate DRIVER PROCESSES joined to one real daemon
plane (control-plane daemon + node-daemon OS processes) and records
per-client and aggregate rates for N = 1, 2, 4 — the per-client
degradation curve is the scaling story for the dispatch plane on this
1-core box (clients, daemons, and workers all share one core, so the
aggregate ceiling here is the core, not the protocol; the recorded
curve shows how gracefully the plane shares it).

A second shape, the THREAD STORM, runs N driver threads in ONE
process, each doing synchronous task round-trips against the daemon.
Separate driver processes all burn CPU pickling, so on one core a
throughput drop could be core saturation rather than the daemon
serializing; one storming process caps driver-side CPU at ~one
thread's worth (the driver GIL), so the aggregate curve across thread
counts isolates how the DAEMON's dispatch loop handles concurrent
in-flight requests. A loop that serializes request handling (the
pure-Python plane, which parses/admits/replies under its GIL in one
loop thread) holds aggregate flat-to-down as threads rise; the native
plane (src/node_dispatch.cc: epoll + off-GIL admission) should let
concurrent round-trips overlap.

A third shape, DISPATCH LATENCY, is a single client doing sequential
round-trips and recording p50/p99 — the per-task dispatch cost the
native worker hand-off (ISSUE 15) is meant to shrink. On the native
plane the daemon's task_native_handoff stat (admission→worker-write)
rides in the row so the C-side slice of the latency is attributable.

Both shapes run under RAY_TPU_NATIVE_DISPATCH=1 and =0 and record
scale_multiclient_* / scale_threadstorm_* / scale_dispatch_latency_*
rows in BENCH_HISTORY.json with a `dispatch` match key, so native and
Python curves form separate comparable series. Every row carries
cpu_count, per-plane CPU seconds (client_cpu_s from the driver
processes, daemon_cpu_s from the daemons' own rusage via the load
report) and the drainer busy-fraction, so a reader can tell protocol
effects from core saturation: on a 1-core box (loud stderr caveat)
the aggregate ceiling is the core, not the protocol.

Run: python bench_multiclient.py [--quick] [--dispatch native|python|both]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_CHILD = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n_tasks, n_calls = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
# num_cpus=0: this driver contributes no execution resources, so every
# task goes through the daemon dispatch plane (the thing under test).
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers
c0 = time.process_time()
t0 = time.perf_counter()
ray.get([noop.remote() for _ in range(n_tasks)])
task_dt = time.perf_counter() - t0
task_cpu = time.process_time() - c0

@ray.remote
class Echo:
    def ping(self):
        return None

a = Echo.remote()
ray.get(a.ping.remote())
c0 = time.process_time()
t0 = time.perf_counter()
ray.get([a.ping.remote() for _ in range(n_calls)])
act_dt = time.perf_counter() - t0
act_cpu = time.process_time() - c0
print(json.dumps({"tasks_s": n_tasks / task_dt,
                  "actor_calls_s": n_calls / act_dt,
                  "cpu_s": task_cpu + act_cpu}))
"""

_STORM_CHILD = r"""
import json, os, sys, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n_threads, per_thread = (sys.argv[1], int(sys.argv[2]),
                               int(sys.argv[3]))
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers

# Each thread does SYNCHRONOUS round-trips: submit one task, wait for
# its result, repeat. One thread measures latency; N threads measure
# whether N concurrent in-flight requests overlap in the daemon (the
# driver GIL is released for the whole socket wait, so driver-side
# serialization costs only the pickling slice).
counts = [0] * n_threads
gate = threading.Barrier(n_threads + 1)

def storm(i):
    gate.wait()
    for _ in range(per_thread):
        ray.get(noop.remote())
        counts[i] += 1

threads = [threading.Thread(target=storm, args=(i,), daemon=True)
           for i in range(n_threads)]
for t in threads:
    t.start()
gate.wait()
c0 = time.process_time()
t0 = time.perf_counter()
for t in threads:
    t.join()
dt = time.perf_counter() - t0
print(json.dumps({"tasks_s": sum(counts) / dt,
                  "cpu_s": time.process_time() - c0}))
"""

_LAT_CHILD = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())  # parent sets cwd to the repo root
import ray_tpu as ray

addr, n = sys.argv[1], int(sys.argv[2])
ray.init(address=addr, num_cpus=0, num_tpus=0)

@ray.remote
def noop():
    return None

ray.get([noop.remote() for _ in range(16)])  # warm dispatch + workers
lats = []
c0 = time.process_time()
for _ in range(n):
    t0 = time.perf_counter()
    ray.get(noop.remote())
    lats.append(time.perf_counter() - t0)
cpu = time.process_time() - c0
lats.sort()
print(json.dumps({
    "p50_us": lats[len(lats) // 2] * 1e6,
    "p99_us": lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e6,
    "n": n, "cpu_s": cpu}))
"""


def run_clients(addr: str, n_clients: int, n_tasks: int,
                n_calls: int) -> dict:
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, addr, str(n_tasks), str(n_calls)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
        for _ in range(n_clients)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        line = out.strip().splitlines()[-1]
        outs.append(json.loads(line))
    return {
        "clients": n_clients,
        "agg_tasks_s": sum(o["tasks_s"] for o in outs),
        "per_client_tasks_s": [round(o["tasks_s"], 1) for o in outs],
        "agg_actor_calls_s": sum(o["actor_calls_s"] for o in outs),
        "per_client_actor_calls_s": [round(o["actor_calls_s"], 1)
                                     for o in outs],
        "client_cpu_s": round(sum(o["cpu_s"] for o in outs), 3),
    }


def run_storm(addr: str, n_threads: int, per_thread: int) -> dict:
    p = subprocess.Popen(
        [sys.executable, "-c", _STORM_CHILD, addr, str(n_threads),
         str(per_thread)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    out, _ = p.communicate(timeout=600)
    line = out.strip().splitlines()[-1]
    r = json.loads(line)
    return {"threads": n_threads, "agg_tasks_s": r["tasks_s"],
            "client_cpu_s": round(r["cpu_s"], 3)}


def run_latency(addr: str, n: int) -> dict:
    p = subprocess.Popen(
        [sys.executable, "-c", _LAT_CHILD, addr, str(n)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    out, _ = p.communicate(timeout=600)
    r = json.loads(out.strip().splitlines()[-1])
    return {"p50_us": round(r["p50_us"], 1),
            "p99_us": round(r["p99_us"], 1), "n": r["n"],
            "client_cpu_s": round(r["cpu_s"], 3)}


def _daemon_sample(node_ids) -> dict:
    """Daemon-side CPU accounting over the load report: summed
    process CPU seconds (rusage) and drainer busy seconds across the
    cluster's daemons. Deltas around a measured section give the
    per-plane cost of that section; the drainer busy delta divided by
    wall time is the busy-fraction (≈0 on the native warm path, where
    the drainer never runs for plain tasks)."""
    from ray_tpu.core import runtime as _runtime

    rt = _runtime.global_runtime()
    cpu = 0.0
    busy = 0.0
    handoff: dict = {}
    for nid in node_ids:
        load = rt.scheduler.get_node(nid).client.call(
            {"type": "ping"})["load"]
        cpu += load.get("proc_cpu_s", 0.0)
        busy += load.get("drainers", {}).get("busy_s_total", 0.0)
        for k, v in (load.get("native_handoff") or {}).items():
            handoff[k] = handoff.get(k, 0) + v
    return {"cpu_s": cpu, "drainer_busy_s": busy, "handoff": handoff}


class _PlaneMeter:
    """Wraps one measured section: wall clock + daemon CPU deltas."""

    def __init__(self, node_ids):
        self.node_ids = node_ids

    def __enter__(self):
        import time as _time

        self._t0 = _time.perf_counter()
        self._s0 = _daemon_sample(self.node_ids)
        return self

    def __exit__(self, *exc):
        import time as _time

        s1 = _daemon_sample(self.node_ids)
        self.wall_s = _time.perf_counter() - self._t0
        self.daemon_cpu_s = round(s1["cpu_s"] - self._s0["cpu_s"], 3)
        self.drainer_busy_frac = round(
            (s1["drainer_busy_s"] - self._s0["drainer_busy_s"])
            / max(self.wall_s, 1e-9), 4)
        self.handoff = s1["handoff"]
        return False

    def row_extra(self) -> dict:
        return {"cpu_count": os.cpu_count(),
                "daemon_cpu_s": self.daemon_cpu_s,
                "drainer_busy_frac": self.drainer_busy_frac}


def run_suite(dispatch: str, n_tasks: int, n_calls: int,
              per_thread: int, n_lat: int,
              record: bool = True) -> None:
    """One full pass (multiclient + thread storm + dispatch latency)
    under one dispatch plane; nodes inherit RAY_TPU_NATIVE_DISPATCH
    via the env overlay."""
    from ray_tpu.cluster_utils import RealCluster

    env = {"RAY_TPU_NATIVE_DISPATCH":
           "1" if dispatch == "native" else "0"}
    bench = None
    if record:  # --quick runs print but don't pollute the history
        try:
            import bench
        except Exception:  # noqa: BLE001
            bench = None

    node_ids = ("daemon-1", "daemon-2")
    cluster = RealCluster()
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=4, env=env)
        # The parent joins with no resources purely to sample the
        # daemons' load reports (proc_cpu_s, drainer busy seconds)
        # around each measured section.
        cluster.connect(num_cpus=0)
        base = None
        for n in (1, 2, 4):
            with _PlaneMeter(node_ids) as m:
                r = run_clients(cluster.address, n, n_tasks, n_calls)
            if base is None:
                base = r
            # Degradation: per-client rate vs the single-client rate.
            r["tasks_per_client_vs_1"] = round(
                (r["agg_tasks_s"] / n) / base["agg_tasks_s"], 3)
            r["actor_calls_per_client_vs_1"] = round(
                (r["agg_actor_calls_s"] / n)
                / base["agg_actor_calls_s"], 3)
            # Aggregate retention: the ISSUE 10 acceptance bar (4-driver
            # aggregate >= 90% of 1-driver aggregate, native).
            r["agg_vs_1client"] = round(
                r["agg_tasks_s"] / base["agg_tasks_s"], 3)
            r.update(m.row_extra())
            print(json.dumps({
                "metric": f"multiclient_{n}", "dispatch": dispatch,
                "value": round(r["agg_tasks_s"], 1),
                "unit": "tasks/s", **{k: v for k, v in r.items()
                                      if k != "clients"}}), flush=True)
            if bench is not None:
                bench.push_history(
                    f"scale_multiclient_{n}_tasks_s",
                    r["agg_tasks_s"], "tasks/s",
                    match={"dispatch": dispatch},
                    extra={"per_client": r["per_client_tasks_s"],
                           "vs_1client": r["tasks_per_client_vs_1"],
                           "agg_vs_1client": r["agg_vs_1client"],
                           "client_cpu_s": r["client_cpu_s"],
                           **m.row_extra()})
                bench.push_history(
                    f"scale_multiclient_{n}_actor_calls_s",
                    r["agg_actor_calls_s"], "calls/s",
                    match={"dispatch": dispatch},
                    extra={"per_client": r["per_client_actor_calls_s"],
                           "vs_1client":
                               r["actor_calls_per_client_vs_1"],
                           "client_cpu_s": r["client_cpu_s"],
                           **m.row_extra()})
        storm_base = None
        for n in (1, 4, 8):
            with _PlaneMeter(node_ids) as m:
                s = run_storm(cluster.address, n, per_thread)
            if storm_base is None:
                storm_base = s
            s["agg_vs_1thread"] = round(
                s["agg_tasks_s"] / storm_base["agg_tasks_s"], 3)
            s.update(m.row_extra())
            print(json.dumps({
                "metric": f"threadstorm_{n}", "dispatch": dispatch,
                "value": round(s["agg_tasks_s"], 1),
                "unit": "tasks/s",
                **{k: v for k, v in s.items()
                   if k != "threads"}}), flush=True)
            if bench is not None:
                bench.push_history(
                    f"scale_threadstorm_{n}_tasks_s",
                    s["agg_tasks_s"], "tasks/s",
                    match={"dispatch": dispatch},
                    extra={"agg_vs_1thread": s["agg_vs_1thread"],
                           "client_cpu_s": s["client_cpu_s"],
                           **m.row_extra()})
        # Dispatch latency: single client, sequential round-trips.
        # p50 is the headline (the native hand-off's target); p99
        # catches scheduling jitter. On the native plane the daemon's
        # admission→worker-write stat attributes the C-side slice.
        with _PlaneMeter(node_ids) as m:
            lat = run_latency(cluster.address, n_lat)
        extra = {"p99_us": lat["p99_us"], "n": lat["n"],
                 "client_cpu_s": lat["client_cpu_s"], **m.row_extra()}
        if dispatch == "native":
            extra["handoff"] = m.handoff
            from ray_tpu.core import runtime as _runtime
            es = _runtime.global_runtime().scheduler.get_node(
                "daemon-1").client.call(
                    {"type": "ping"})["load"]["event_stats"]
            extra["handoff_stats"] = es.get(
                "node_dispatch_native", {}).get("task_native_handoff")
        print(json.dumps({
            "metric": "dispatch_latency", "dispatch": dispatch,
            "value": lat["p50_us"], "unit": "us_p50", **extra}),
            flush=True)
        if bench is not None:
            bench.push_history("scale_dispatch_latency_us",
                               lat["p50_us"], "us_p50",
                               match={"dispatch": dispatch},
                               extra=extra)
    finally:
        cluster.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--dispatch", choices=["native", "python", "both"],
                    default="both")
    args = ap.parse_args()
    n_tasks = 200 if args.quick else 2000
    n_calls = 200 if args.quick else 2000
    per_thread = 50 if args.quick else 250
    n_lat = 100 if args.quick else 1000

    if (os.cpu_count() or 1) == 1:
        print("=" * 70, file=sys.stderr)
        print("WARNING: os.cpu_count() == 1 — clients, daemons, and "
              "workers all\nshare one core. Aggregate throughput and "
              "retention on this box\nmeasure core-sharing fairness, "
              "NOT protocol scaling; treat absolute\nnumbers and "
              "cross-plane deltas accordingly (per-plane CPU seconds\n"
              "in each row show where the core actually went).",
              file=sys.stderr)
        print("=" * 70, file=sys.stderr)

    modes = (["native", "python"] if args.dispatch == "both"
             else [args.dispatch])
    for mode in modes:
        run_suite(mode, n_tasks, n_calls, per_thread, n_lat,
                  record=not args.quick)


if __name__ == "__main__":
    main()
