"""Benchmark: flagship train-step throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N}

North-star metric (BASELINE.md): tokens/sec/chip training the BASELINE
config-1 model (GPT-2-125M class). The reference publishes no tokens/sec
number (SURVEY.md §6) — vs_baseline is the ratio against the previous
recorded round in BENCH_HISTORY.json (1.0 on first measurement).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny config + fewer steps (smoke test)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=0, help="0 = auto")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import configs
    from ray_tpu.parallel import ParallelPlan, make_mesh
    from ray_tpu.train.step import (
        init_state,
        make_optimizer,
        make_train_step,
        shard_batch,
    )

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n_dev = len(devices)

    if args.quick or not on_tpu:
        cfg = configs.tiny_test()
        batch, seq, steps = 8, 128, 5
        metric = "tiny_train_tokens_per_sec_smoke"
    else:
        cfg = configs.gpt2_125m()
        batch, seq, steps = (args.batch or 16), 1024, args.steps
        metric = "gpt2_125m_train_tokens_per_sec_per_chip"

    plan = ParallelPlan.auto(n_dev) if n_dev > 1 else ParallelPlan()
    mesh = make_mesh(plan, devices=devices[:plan.num_devices])
    opt = make_optimizer(lr=3e-4, warmup_steps=10, total_steps=10_000)

    with jax.sharding.set_mesh(mesh):
        state = init_state(cfg, mesh, opt, seed=0)
        step_fn = make_train_step(cfg, opt)
        k = jax.random.key(0)
        tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jnp.ones_like(tokens, dtype=jnp.float32)
        b = shard_batch(
            {"t": tokens, "y": targets, "m": mask}, mesh)

        # Warmup / compile. float() = device→host fetch, a hard sync
        # barrier (block_until_ready alone does not flush the remote
        # execution queue on tunneled backends).
        state, m = step_fn(state, b["t"], b["y"], b["m"])
        final_loss = float(m["loss"])

        # Best-of-segments: the tunnel to the chip has large run-to-run
        # variance; the fastest segment reflects the machine's rate.
        n_seg, dt = 3, float("inf")
        seg = max(1, steps // n_seg)
        for _ in range(n_seg):
            t0 = time.perf_counter()
            for _ in range(seg):
                state, m = step_fn(state, b["t"], b["y"], b["m"])
            final_loss = float(m["loss"])
            dt = min(dt, time.perf_counter() - t0)
        assert final_loss == final_loss, "non-finite loss"

    tokens_per_sec = batch * seq * seg / dt
    per_chip = tokens_per_sec / max(1, plan.num_devices)

    # vs_baseline: ratio to the previous recorded measurement.
    hist_path = os.path.join(os.path.dirname(__file__), "BENCH_HISTORY.json")
    history = []
    if os.path.exists(hist_path):
        try:
            history = json.load(open(hist_path))
        except Exception:  # noqa: BLE001
            history = []
    # Compare only against entries timed the same way — mixing the old
    # whole-run mean with best-of-segments would misattribute the
    # methodology change as speedup.
    method = "best-of-3-segments"
    prev = next((h["value"] for h in reversed(history)
                 if h.get("metric") == metric
                 and h.get("method") == method), None)
    vs = (per_chip / prev) if prev else 1.0
    history.append({
        "metric": metric, "value": per_chip, "unit": "tokens/s/chip",
        "ts": time.time(), "devices": n_dev, "method": method,
        "platform": devices[0].platform, "batch": batch, "seq": seq,
    })
    try:
        json.dump(history, open(hist_path, "w"), indent=1)
    except Exception:  # noqa: BLE001
        pass

    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
