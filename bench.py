"""Benchmark: flagship train-step throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N}

North-star metric (BASELINE.md): tokens/sec/chip training the BASELINE
config-1 model (GPT-2-125M class). The reference publishes no tokens/sec
number (SURVEY.md §6) — vs_baseline is the ratio against the pinned bar
in BASELINE.json "published" (falling back to the previous comparable
BENCH_HISTORY.json entry; 1.0 on first measurement).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time


def time_best_of(step_once, sync, *, steps: int, n_seg: int = 3,
                 converge: float = 0.01, max_seg: int = 10) -> float:
    """Seconds per step: best segment of `steps` calls each, repeated
    until the measurement is noise-proof.

    `sync()` must force completion with a host fetch — on tunneled
    backends block_until_ready alone does not flush the remote queue.
    Best-of because the tunnel has large run-to-run variance; the
    fastest segment reflects the machine's rate. One recorded sample
    used to decide a round, so segments repeat (up to `max_seg`) until
    the two fastest agree within `converge` — the best is then a stable
    property of the code, not of one tunnel draw.
    """
    sync()  # flush warmup/compile before the clock starts
    times: list[float] = []
    while len(times) < max_seg:
        t0 = time.perf_counter()
        for _ in range(steps):
            step_once()
        sync()
        times.append((time.perf_counter() - t0) / steps)
        if len(times) >= n_seg:
            a, b = sorted(times)[:2]
            if b - a <= converge * a:
                break
    return min(times)


def core_api_smoke() -> None:
    """Gate: exercise the task/actor API itself before any model bench.

    VERDICT r4 weak #1: the round-4 snapshot shipped with a broken
    FunctionManager because bench + dryrun only touched the model/
    parallel path — a snapshot where `ray.get(f.remote())` raises could
    still pass every gate. This runs submit/get, error propagation,
    retries, streaming generators, actor calls and the runtime context
    in ~2s and aborts the bench (non-zero exit) on any failure.
    """
    import ray_tpu as ray

    ray.shutdown()
    ray.init(num_cpus=2, num_tpus=0)
    try:
        @ray.remote
        def add(a, b):
            return a + b

        assert ray.get(add.remote(40, 2)) == 42

        @ray.remote
        def boom():
            raise RuntimeError("expected")

        try:
            ray.get(boom.remote())
            raise AssertionError("task error did not propagate")
        except ray.TaskError:
            pass

        attempts = []

        @ray.remote(max_retries=3, retry_exceptions=True)
        def flaky():
            attempts.append(1)
            if len(attempts) < 2:
                raise RuntimeError("transient")
            return "recovered"

        assert ray.get(flaky.remote()) == "recovered"

        @ray.remote(num_returns="streaming")
        def gen(n):
            for i in range(n):
                yield i * i

        assert [ray.get(r) for r in gen.remote(4)] == [0, 1, 4, 9]

        @ray.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]

        ctx = ray.get_runtime_context()
        assert ctx.job_id is not None
        assert ctx.get_node_id() is not None
    finally:
        ray.shutdown()


def pinned_baseline(metric: str, match: dict | None = None):
    """Fixed scoreboard bar for `metric` from BASELINE.json "published".

    vs_baseline must compare against a *pinned* number — comparing to
    the most recent history entry made every round a ratchet against
    its own tunnel noise (VERDICT r2 weak #1). A pin only applies when
    the run's config matches the pin's recorded "match" fields (batch/
    seq/platform — comparing across configs would report config changes
    as speedups). Returns None if no applicable pin exists.
    """
    path = os.path.join(os.path.dirname(__file__), "BASELINE.json")
    try:
        pub = json.load(open(path)).get("published", {})
        entry = pub.get(metric)
        if isinstance(entry, dict):
            pin_cfg = entry.get("match", {})
            if match is not None and any(
                    match.get(k) != v for k, v in pin_cfg.items()):
                return None
            return float(entry["value"])
        if entry is not None:
            return float(entry)
    except Exception:  # noqa: BLE001
        pass
    return None


def push_history(metric: str, value: float, unit: str, match: dict,
                 extra: dict):
    """Append a BENCH_HISTORY.json entry; return the most recent prior
    value whose entry matches `match` (metric + the config fields that
    make measurements comparable — comparing across configs would report
    config changes as speedups)."""
    hist_path = os.path.join(os.path.dirname(__file__),
                             "BENCH_HISTORY.json")
    history = []
    if os.path.exists(hist_path):
        try:
            history = json.load(open(hist_path))
        except Exception:  # noqa: BLE001
            history = []
    prev = next((h["value"] for h in reversed(history)
                 if h.get("metric") == metric
                 and all(h.get(k) == v for k, v in match.items())), None)
    history.append({"metric": metric, "value": value, "unit": unit,
                    "ts": time.time(), **match, **extra})
    try:
        json.dump(history, open(hist_path, "w"), indent=1)
    except Exception:  # noqa: BLE001
        pass
    return prev


# Config-identity fields a BENCH_HISTORY row may carry: two rows are
# comparable only when all of these agree (same reasoning as
# push_history's `match`).
_IDENTITY_KEYS = ("unit", "platform", "batch", "seq", "model", "steps")

# Direction by unit: a throughput drop and a latency rise are both
# regressions.
_HIGHER_BETTER = {"tok/s", "tokens/s", "img/s", "images/s", "req/s",
                  "tasks/s", "GB/s", "x"}
_LOWER_BETTER = {"s", "ms", "seconds", "%"}


def check_regressions(threshold_pct: float = 10.0,
                      hist_path: str | None = None,
                      min_prior: int = 2,
                      trailing: int = 5) -> list:
    """Compare each metric's freshest BENCH_HISTORY row against the
    trailing median of its prior comparable rows (same metric + config
    identity + platform). The median — not the previous row — is the
    bar, so one noisy run neither hides nor fakes a regression.

    → list of regression dicts (empty = clean). Groups with fewer than
    `min_prior` prior rows are reported as "insufficient history", not
    failed."""
    path = hist_path or os.path.join(os.path.dirname(__file__),
                                     "BENCH_HISTORY.json")
    try:
        history = json.load(open(path))
    except Exception:  # noqa: BLE001
        print(f"no readable history at {path}", file=sys.stderr)
        return []
    groups: dict = {}
    for row in history:
        if not isinstance(row, dict) or "metric" not in row:
            continue
        key = (row["metric"],) + tuple(
            (k, row.get(k)) for k in _IDENTITY_KEYS)
        groups.setdefault(key, []).append(row)
    regressions = []
    for key, rows in sorted(groups.items()):
        metric, unit = key[0], rows[-1].get("unit")
        last, prior = rows[-1], rows[:-1]
        label = metric + "".join(
            f" {k}={v}" for k, v in key[1:]
            if v is not None and k != "unit")
        if unit in _HIGHER_BETTER:
            sign = 1.0
        elif unit in _LOWER_BETTER:
            sign = -1.0
        else:  # booleans ("ok") and unknown units aren't trendable
            continue
        if len(prior) < min_prior:
            print(f"  SKIP {label}: {len(prior)} prior rows "
                  f"(need {min_prior})", file=sys.stderr)
            continue
        vals = sorted(r["value"] for r in prior[-trailing:])
        n = len(vals)
        med = (vals[n // 2] if n % 2 else
               (vals[n // 2 - 1] + vals[n // 2]) / 2.0)
        if med == 0:
            continue
        delta_pct = sign * (last["value"] - med) / abs(med) * 100.0
        status = "ok"
        if delta_pct < -threshold_pct:
            status = "REGRESSION"
            regressions.append({
                "metric": metric, "unit": unit, "value": last["value"],
                "trailing_median": med, "delta_pct": delta_pct,
                "label": label})
        print(f"  {status:>10} {label}: {last['value']:.6g} {unit} "
              f"vs trailing median {med:.6g} "
              f"({delta_pct:+.1f}%)", file=sys.stderr)
    return regressions


def _chip_peak_flops(device) -> float:
    """Stated peak dense FLOP/s for the chip (bf16), so the MFU claim
    is checkable. Override with RAY_TPU_CHIP_PEAK_FLOPS when the table
    lags the hardware. 0 = unknown (MFU omitted)."""
    env = os.environ.get("RAY_TPU_CHIP_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    table = {
        # chip-level bf16 peaks from published TPU specs
        "v4": 275e12,
        "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
        "v5": 459e12, "v5p": 459e12,
        "v6 lite": 918e12, "v6e": 918e12, "trillium": 918e12,
    }
    for key, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return 0.0


def bench_serve(quick: bool, model: str = "gpt2-125m",
                trials: int = 7, emit: bool = True) -> dict:
    """Serving north-star (BASELINE.md): req/s + p50 TTFT from the
    continuous-batching engine. Protocol (VERDICT r2 weak #2): the
    request burst repeats `trials` times and ONE history entry records
    the summary — a single-burst sample spread 2× across rounds. The
    recorded value is the median of the 3 FASTEST trials: the tunnel's
    minute-scale load drift only ever slows a trial down (same
    rationale as the train bench's best-of-segments), so the fast
    cluster is the machine's rate; all trial rates are recorded
    alongside for transparency. Prints one JSON line."""
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs
    from ray_tpu.models.transformer import init_params
    from ray_tpu.serve.llm import LLMEngine

    from dataclasses import replace

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if quick or not on_tpu:
        cfg, n_req, slots = configs.tiny_test(), 8, 4
        metric = "tiny_serve_req_per_sec_smoke"
        prompt_len, max_new, max_seq = 16, 16, 128
        trials = min(trials, 2)
        cfg = replace(cfg, max_seq_len=max_seq)
    else:
        cfg = configs.get(model)
        # 128-request bursts: a ~6s burst samples too little of the
        # tunnel's load swings; doubling the burst halves the spread.
        n_req, slots = 128, int(os.environ.get("RAY_TPU_BENCH_SLOTS", 16))
        metric = f"{model.replace('-', '_')}_serve_req_per_sec"
        prompt_len, max_new, max_seq = 128, 64, 1024
        # Serve in bf16 (inference has no optimizer needing master
        # weights); the smoke path keeps tiny_test's f32 so its history
        # entries stay comparable.
        cfg = replace(cfg, param_dtype=jnp.bfloat16, max_seq_len=max_seq)

    params = init_params(cfg, jax.random.key(0))
    # No decode_block tuning: the engine adapts the fused-block size
    # online to the active slots' remaining budgets (llm.py step()).
    engine = LLMEngine(cfg, params, num_slots=slots, max_seq_len=max_seq)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=prompt_len).tolist()
               for _ in range(n_req)]

    # Warm the compile caches off-clock: one full-length request
    # (prefill bucket + the adaptive decode block the run will use) and
    # an over-subscribed mini-burst (queue-side first-token path).
    engine.start()
    engine.submit(prompts[0], max_new_tokens=max_new).result()
    warm = [engine.submit(p, max_new_tokens=2)
            for p in prompts[:slots + 4]]
    for r in warm:
        r.result()

    runs = []  # (rate, per-request ttfts, gen tok/s) per trial
    for _ in range(max(1, trials)):
        t0 = time.perf_counter()
        reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        for r in reqs:
            r.result()
        dt = time.perf_counter() - t0
        runs.append((n_req / dt, [r.ttft_s for r in reqs],
                     sum(len(r.tokens) for r in reqs) / dt))
    engine.stop()

    rates = [r[0] for r in runs]
    # Every reported stat comes from the SAME 3 fastest trials — mixing
    # the fast-cluster req/s with all-trial TTFT would pair numbers
    # measured under different conditions.
    top = sorted(runs, key=lambda r: -r[0])[:3]
    top_rates = [r[0] for r in top]
    req_s = statistics.median(top_rates)
    # spread of the fast cluster — the stability claim (NOT an IQR:
    # range of the 3 fastest trials)
    top3_range = max(top_rates) - min(top_rates)
    ttft_all = sorted(t for r in top for t in r[1])
    p50 = ttft_all[len(ttft_all) // 2]
    tok_rates = [r[2] for r in top]
    run_match = {"prompt_len": prompt_len, "max_new": max_new,
                 "slots": slots, "decode_block": engine.decode_block,
                 "platform": jax.devices()[0].platform}
    prev = push_history(
        metric, req_s, "req/s", match=run_match,
        extra={"ttft_p50_s": p50, "trials": len(rates),
               "top3_range": round(top3_range, 3),
               "trial_rates": [round(x, 2) for x in rates]})
    base = pinned_baseline(metric, run_match) or prev
    out = {
        "metric": metric, "value": round(req_s, 2), "unit": "req/s",
        "vs_baseline": round(req_s / base, 3) if base else 1.0,
        "ttft_p50_ms": round(p50 * 1e3, 1),
        "trials": len(rates), "top3_range": round(top3_range, 3),
        "gen_tokens_per_sec": round(statistics.median(tok_rates), 1),
    }
    if emit:
        print(json.dumps(out))
    return out


def _smoke_prefix_equivalence() -> None:
    """Prefix-cache smoke gate: greedy tokens from a prefix-cached
    suffix prefill must EQUAL the full-prompt prefill's (same model,
    same prompts). Prints one JSON line with value 1.0 on equivalence.
    """
    from dataclasses import replace

    import jax
    import numpy as np

    from ray_tpu.models import configs
    from ray_tpu.models.generate import (
        compute_prefix_kv,
        init_kv_cache,
        prefill_sample_batch,
        prefill_suffix_batch,
    )
    from ray_tpu.models.transformer import init_params

    cfg = replace(configs.tiny_test(), max_seq_len=128)
    pre, suf, slots, max_seq, W = 48, 8, 4, 128, 4
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, pre).tolist()
    prompts = [prefix + rng.integers(0, cfg.vocab_size, suf).tolist()
               for _ in range(W)]

    import jax.numpy as jnp

    pk, pv = compute_prefix_kv(cfg, params, prefix)
    fbuf = np.zeros((W, 64), np.int32)
    sbuf = np.zeros((W, 8), np.int32)
    for j, p in enumerate(prompts):
        fbuf[j, :len(p)] = p
        sbuf[j, :suf] = p[pre:]
    flens = jnp.full((W,), pre + suf, jnp.int32)
    slens = jnp.full((W,), suf, jnp.int32)
    slot_idx = jnp.arange(W, dtype=jnp.int32) % slots
    temps = jnp.zeros((W,), jnp.float32)  # greedy
    key = jax.random.key(0)

    _, toks_full = prefill_sample_batch(
        cfg, params, init_kv_cache(cfg, slots, max_seq),
        jnp.asarray(fbuf), flens, slot_idx, 0, temps, key)
    _, toks_suffix = prefill_suffix_batch(
        cfg, params, init_kv_cache(cfg, slots, max_seq), pk, pv,
        jnp.asarray(sbuf), slens, slot_idx, 0, temps, key)
    same = bool(np.array_equal(np.asarray(toks_full),
                               np.asarray(toks_suffix)))
    metric = "tiny_serve_prefix_equivalence_smoke"
    push_history(metric, 1.0 if same else 0.0, "ok",
                 match={"prefix_len": pre, "suffix_len": suf,
                        "platform": jax.devices()[0].platform},
                 extra={})
    print(json.dumps({
        "metric": metric, "value": 1.0 if same else 0.0, "unit": "ok",
        "vs_baseline": 1.0 if same else 0.0,
    }))
    if not same:
        sys.exit("prefix-cached prefill diverged from full prefill")


def bench_serve_prefix(quick: bool, model: str = "llama-654m",
                       trials: int = 5) -> None:
    """Prefix-caching serving scenario: a long shared system prompt
    (480 tok) + short user suffixes (32 tok) — the chat-serving shape
    vLLM's automatic prefix caching targets.

    The recorded value is the ADMISSION-WAVE DEVICE-TIME speedup:
    dispatch-to-ready of one full-prompt prefill tile vs the
    prefix-cached suffix tile, best-of-K paired (deterministic device
    compute — the quantity the feature actually changes). An
    engine-level end-to-end burst rides along as extra; on this
    single tunneled chip the burst wall is round-trip-bound (each
    engine tick pays ~150 ms of tunnel before any FLOPs), so the e2e
    number under-reports the saving a local or larger-model deployment
    sees. Prints one JSON line."""
    import statistics
    from dataclasses import replace

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs
    from ray_tpu.models.generate import (
        compute_prefix_kv,
        init_kv_cache,
        prefill_sample_batch,
        prefill_suffix_batch,
    )
    from ray_tpu.models.transformer import init_params
    from ray_tpu.serve.llm import LLMEngine

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if quick or not on_tpu:
        # Smoke = CORRECTNESS, not speed: the tiny model's waves are
        # microseconds of device time, unresolvable behind the ~150 ms
        # tunnel RTT — the old speedup smoke once recorded a 0.86×
        # "slowdown" with both arms pinned at the timer floor (VERDICT
        # r3 weak #1). Equivalence (prefix-cached prefill ≡ full
        # prefill, greedy) is exactly what must not regress; the real
        # speedup gate is the pinned llama_654m_serve_prefix_speedup.
        _smoke_prefix_equivalence()
        return
    cfg = configs.get(model)
    cfg = replace(cfg, param_dtype=jnp.bfloat16, max_seq_len=1024)
    pre, suf, n_req, new, slots, max_seq = 480, 32, 64, 4, 4, 1024
    metric = f"{model.replace('-', '_')}_serve_prefix_speedup"

    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, pre).tolist()
    prompts = [prefix + rng.integers(0, cfg.vocab_size, suf).tolist()
               for _ in range(n_req)]

    # ---- primary: paired device time per admission wave ----
    W = LLMEngine._ADMIT_TILE
    pk, pv = compute_prefix_kv(cfg, params, prefix)
    full_bucket = 1
    while full_bucket < pre + suf:
        full_bucket *= 2
    suf_bucket = 1
    while suf_bucket < suf:
        suf_bucket *= 2
    fbuf = np.zeros((W, full_bucket), np.int32)
    sbuf = np.zeros((W, suf_bucket), np.int32)
    for j in range(W):
        p = prompts[j % n_req]
        fbuf[j, :len(p)] = p
        sbuf[j, :suf] = p[pre:]
    flens = np.full((W,), pre + suf, np.int32)
    slens = np.full((W,), suf, np.int32)
    slot_idx = np.arange(W, dtype=np.int32) % slots
    temps = np.zeros((W,), np.float32)
    key = jax.random.key(0)

    # Hoist device transfers out of the timed closures: the loop must
    # measure the prefill work alone, and the 512-wide full buffer's
    # per-dispatch upload would bias the two arms asymmetrically.
    fbuf_d, flens_d = jnp.asarray(fbuf), jnp.asarray(flens)
    sbuf_d, slens_d = jnp.asarray(sbuf), jnp.asarray(slens)
    slot_d, temps_d = jnp.asarray(slot_idx), jnp.asarray(temps)

    def wave_full(cache):
        return prefill_sample_batch(
            cfg, params, cache, fbuf_d, flens_d, slot_d, 0, temps_d, key)

    def wave_suffix(cache):
        return prefill_suffix_batch(
            cfg, params, cache, pk, pv, sbuf_d, slens_d, slot_d, 0,
            temps_d, key)

    def null_rtt():
        """Host<->device round trip with no compute (the tunnel's
        block_until_ready can return before execution; a real host
        fetch is the only reliable sync, and it costs one RTT that
        must be subtracted from chained timings)."""
        x = jnp.zeros((8,), jnp.float32) + 1
        np.asarray(x)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(x + 1)
            best = min(best, time.perf_counter() - t0)
        return best

    def time_wave(fn, rtt, reps=3):
        """Per-wave device time: K cache-chained waves (serial on
        device) behind ONE real host sync, K sized so the chain runs
        >=0.5 s — the subtracted RTT and its jitter stay <20% of the
        measurement even for the ~ms suffix waves."""
        cache = init_kv_cache(cfg, slots, max_seq)
        cache, toks = fn(cache)            # compile + warm
        np.asarray(toks)

        def run(k):
            nonlocal cache
            t0 = time.perf_counter()
            for _ in range(k):
                cache, toks = fn(cache)
            np.asarray(toks)
            return time.perf_counter() - t0

        K = 8
        est = max(1e-4, (run(K) - rtt) / K)
        K = int(min(512, max(K, math.ceil(0.5 / est))))
        best = min(run(K) for _ in range(reps))
        return max(1e-5, (best - rtt) / K)

    rtt = null_rtt()
    t_full = time_wave(wave_full, rtt)
    t_suffix = time_wave(wave_suffix, rtt)
    wave_speedup = t_full / t_suffix

    # ---- extra: engine-level end-to-end burst (RTT-bound here) ----
    def burst(register: bool):
        eng = LLMEngine(cfg, params, num_slots=slots,
                        max_seq_len=max_seq)
        if register:
            eng.register_prefix(prefix)
        warm = eng.submit(prompts[0], max_new_tokens=2)
        while eng.step():
            pass
        warm.result(timeout=300)
        reqs = [eng.submit(p, max_new_tokens=new) for p in prompts]
        t0 = time.perf_counter()
        while eng.step():
            pass
        wall = time.perf_counter() - t0
        for r in reqs:
            r.result(timeout=300)
        return wall

    walls = []
    for t in range(max(1, trials)):
        # Alternate pair order so slow monotone tunnel drift cancels.
        if t % 2 == 0:
            w_off, w_on = burst(False), burst(True)
        else:
            w_on, w_off = burst(True), burst(False)
        walls.append(w_off / w_on)
    e2e_x = statistics.median(walls)

    run_match = {"prefix_len": pre, "suffix_len": suf, "tile": W,
                 "slots": slots,
                 "platform": jax.devices()[0].platform}
    push_history(metric, wave_speedup, "x", match=run_match,
                 extra={"wave_ms_full": round(t_full * 1e3, 1),
                        "wave_ms_suffix": round(t_suffix * 1e3, 1),
                        "e2e_burst_speedup": round(e2e_x, 2),
                        "trials": len(walls)})
    # Pinned gate (VERDICT r3 #7c): vs_baseline compares the device-
    # time speedup against the bar in BASELINE.json; <1.0 = the
    # prefix-cache device-time win regressed.
    bar = pinned_baseline(metric, run_match)
    print(json.dumps({
        "metric": metric, "value": round(wave_speedup, 2), "unit": "x",
        "vs_baseline": round(wave_speedup / bar, 3) if bar
        else round(wave_speedup, 2),
        "wave_ms_full": round(t_full * 1e3, 1),
        "wave_ms_suffix": round(t_suffix * 1e3, 1),
        "e2e_burst_speedup": round(e2e_x, 2),
    }))


def bench_vit(quick: bool) -> None:
    """BASELINE config 4 (ViT-L/CLIP image path): images/s training a
    ViT classifier. Prints one JSON line."""

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import vit

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if quick or not on_tpu:
        cfg, batch, steps = vit.vit_tiny_test(), 8, 3
        metric = "tiny_vit_images_per_sec_smoke"
    else:
        # ViT-L/16 at 224px does not leave replica headroom on one
        # 16G chip with f32 optimizer state; ViT-B-class shapes carry
        # the same kernel mix (patchify→MHA→MLP over 196 tokens).
        cfg = vit.ViTConfig(image_size=224, patch_size=16, d_model=768,
                            n_layers=12, n_heads=12, d_ff=3072,
                            n_classes=1000)
        # 60-step segments amortize the tunnel-RTT sync (same rationale
        # as the flagship default --steps).
        batch, steps = 64, 60
        metric = "vit_b16_train_images_per_sec_per_chip"

    params = vit.init_params(cfg, jax.random.key(0))
    opt = optax.adamw(3e-4, weight_decay=0.05)
    opt_state = opt.init(params)

    def loss_fn(params, images, labels):
        return vit.classification_loss(cfg, params, images, labels)[0]

    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    k = jax.random.key(1)
    images = jax.random.normal(
        k, (batch, cfg.image_size, cfg.image_size, 3), jnp.float32)
    labels = jax.random.randint(k, (batch,), 0, cfg.n_classes)
    state = {}

    def step_once():
        nonlocal params, opt_state
        params, opt_state, state["loss"] = step(params, opt_state,
                                                images, labels)

    step_once()
    img_s = batch / time_best_of(
        step_once, lambda: float(state["loss"]), steps=steps)
    run_match = {"batch": batch, "platform": jax.devices()[0].platform,
                 "method": "best-of-segments", "seg_steps": steps}
    prev = push_history(metric, img_s, "images/s",
                        match=run_match, extra={})
    base = pinned_baseline(metric, run_match) or prev
    print(json.dumps({
        "metric": metric, "value": round(img_s, 1), "unit": "images/s",
        "vs_baseline": round(img_s / base, 3) if base else 1.0,
    }))


def bench_rlhf(quick: bool, model: str = "gpt2-125m") -> None:
    """North-star config 5: the end-to-end GRPO RLHF loop (rollout
    fan-out → sharded learner update → relay weight refresh). Pushes
    three rows per run — generation tokens/s, wall-clock per iteration
    and weight-refresh seconds — and prints one JSON line."""

    import jax

    import ray_tpu
    from ray_tpu.models import configs
    from ray_tpu.rlhf import RLHFConfig, RLHFPipeline

    if quick:
        mcfg = configs.tiny_test(vocab=128)
        prefix, iters = "tiny", 2
        num_gen, num_prompts, group = 2, 4, 2
        prompt_len, max_new = 4, 8
    else:
        mcfg = configs.get(model)
        prefix, iters = model.replace("-", "_"), 2
        num_gen, num_prompts, group = 4, 8, 4
        prompt_len, max_new = 16, 16

    import numpy as np

    cfg = RLHFConfig(
        model=mcfg, num_generators=num_gen, num_prompts=num_prompts,
        prompt_len=prompt_len, group_size=group,
        max_new_tokens=max_new,
        # Cheap stand-in reward: the loop's cost profile (rollout,
        # update, refresh) is what's measured, not reward quality.
        reward_fn=lambda comp: (comp == 7).mean(axis=1),
        lr=1e-4, warmup_steps=2, total_steps=100)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=max(2, num_gen), num_tpus=0)
    pipe = RLHFPipeline(cfg)
    try:
        pipe.train_iteration()  # warmup: compile + first refresh
        # Iterations dominated by full-model forward/backward, so the
        # best-of-segments protocol (built for ms-scale steps) would
        # cost minutes per extra segment; best-of-N iterations gives
        # the same "machine rate, not scheduler draw" property.
        outs = [pipe.train_iteration() for _ in range(iters)]
    finally:
        pipe.shutdown()
        ray_tpu.shutdown()
    best = min(outs, key=lambda o: o["iteration_s"])
    tok_s = max(o["tokens_per_s"] for o in outs)

    run_match = {"platform": jax.devices()[0].platform,
                 "num_generators": num_gen, "num_prompts": num_prompts,
                 "group_size": group, "prompt_len": prompt_len,
                 "max_new_tokens": max_new}
    suffix = "_smoke" if quick else ""
    rows = [
        (f"{prefix}_grpo_tokens_per_sec{suffix}", tok_s, "tokens/s"),
        (f"{prefix}_rlhf_iteration_seconds{suffix}",
         best["iteration_s"], "s"),
        (f"{prefix}_rlhf_weight_refresh_seconds{suffix}",
         best["refresh_s"], "s"),
    ]
    out = {}
    for metric, value, unit in rows:
        prev = push_history(metric, value, unit, match=run_match,
                            extra={"refresh_bytes":
                                   int(best["refresh_bytes"])})
        base = pinned_baseline(metric, run_match) or prev
        out[metric] = {"value": round(value, 3), "unit": unit,
                       "vs_baseline":
                       round(value / base, 3) if base else 1.0}
    print(json.dumps({
        "metric": f"{prefix}_grpo_tokens_per_sec{suffix}",
        "value": round(tok_s, 1), "unit": "tokens/s",
        "vs_baseline": out[rows[0][0]]["vs_baseline"],
        "reward_mean": round(best["reward_mean"], 4),
        "refresh_bytes": int(best["refresh_bytes"]),
        "extra_metrics": [
            {"metric": m, **out[m]} for m, _, _ in rows[1:]],
    }))


def bench_critpath(quick: bool, model: str = "gpt2-125m") -> None:
    """Critical-path attribution scoreboard (the baseline ROADMAP
    item 3's compiled task graphs must move). Two rows:

    * ``rlhf_dispatch_share_of_critical_path`` — one traced RLHF train
      iteration analyzed by observability.critpath: the % of the
      iteration's critical path attributed to the dispatch planes
      (driver submit + admission + dispatch queue + native handoff).
      "%" is lower-better, so check_regressions flags dispatch-share
      growth automatically.
    * ``serve_ttft_queue_share`` — TTFT waterfall from the
      continuous-batching engine's per-request queue/prefill/decode
      stamps: the % of median TTFT spent queued before admission.

    Prints one JSON line (second row rides under extra_metrics)."""
    from dataclasses import replace

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu
    from ray_tpu.models import configs
    from ray_tpu.models.transformer import init_params
    from ray_tpu.observability import critpath
    from ray_tpu.rlhf import RLHFConfig, RLHFPipeline
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.util import tracing

    if quick:
        mcfg = configs.tiny_test(vocab=128)
        num_gen, num_prompts, group = 2, 4, 2
        prompt_len, max_new = 4, 8
    else:
        mcfg = configs.get(model)
        num_gen, num_prompts, group = 4, 8, 4
        prompt_len, max_new = 16, 16

    cfg = RLHFConfig(
        model=mcfg, num_generators=num_gen, num_prompts=num_prompts,
        prompt_len=prompt_len, group_size=group,
        max_new_tokens=max_new,
        reward_fn=lambda comp: (comp == 7).mean(axis=1),
        lr=1e-4, warmup_steps=2, total_steps=100)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=max(2, num_gen), num_tpus=0)
    spans: list = []
    tracing.setup_tracing(spans.append)
    trace_id = None
    try:
        pipe = RLHFPipeline(cfg)
        try:
            pipe.train_iteration()  # warmup: compile + first refresh
            with tracing.span("rlhf_iteration", "bench"):
                trace_id = tracing.current_trace_id()
                pipe.train_iteration()
        finally:
            pipe.shutdown()
        from ray_tpu.core.runtime import global_runtime

        events = global_runtime().timeline()
    finally:
        tracing.clear_tracing()
        ray_tpu.shutdown()

    report = critpath.analyze(events, trace_id)
    critpath.record_plane_metrics(report)
    share_pct = report.get("dispatch_share", 0.0) * 100.0

    run_match = {"platform": jax.devices()[0].platform,
                 "num_generators": num_gen, "num_prompts": num_prompts,
                 "group_size": group, "prompt_len": prompt_len,
                 "max_new_tokens": max_new}
    metric = "rlhf_dispatch_share_of_critical_path"
    prev = push_history(
        metric, share_pct, "%", match=run_match,
        extra={"kind": report.get("kind"),
               "makespan_s": round(report.get("makespan_s", 0.0), 4),
               "critical_path_len": len(report.get("critical_path", [])),
               "planes": {p: round(v, 4)
                          for p, v in
                          (report.get("planes") or {}).items()}})
    base = pinned_baseline(metric, run_match) or prev

    # --- serve TTFT waterfall row -------------------------------------
    on_tpu = jax.devices()[0].platform not in ("cpu",)
    if quick or not on_tpu:
        scfg, n_req, slots = configs.tiny_test(), 12, 4
        sprompt_len, smax_new, max_seq = 8, 8, 128
        scfg = replace(scfg, max_seq_len=max_seq)
    else:
        scfg = configs.get(model)
        n_req, slots = 64, 16
        sprompt_len, smax_new, max_seq = 64, 32, 1024
        scfg = replace(scfg, param_dtype=jnp.bfloat16,
                       max_seq_len=max_seq)
    params = init_params(scfg, jax.random.key(0))
    engine = LLMEngine(scfg, params, num_slots=slots,
                       max_seq_len=max_seq)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, scfg.vocab_size,
                            size=sprompt_len).tolist()
               for _ in range(n_req)]
    engine.start()
    try:
        engine.submit(prompts[0], max_new_tokens=smax_new).result()
        # Oversubscribed burst (n_req > slots): the queue plane must be
        # nonzero or the waterfall row measures nothing.
        reqs = [engine.submit(p, max_new_tokens=smax_new)
                for p in prompts]
        for r in reqs:
            r.result()
    finally:
        engine.stop()

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else 0.0

    q50 = med([r.queue_s for r in reqs])
    p50 = med([r.prefill_s for r in reqs])
    d50 = med([r.decode_s for r in reqs])
    t50 = med([r.ttft_s for r in reqs if r.ttft_s is not None])
    queue_share = 100.0 * q50 / t50 if t50 > 0 else 0.0
    serve_match = {"platform": jax.devices()[0].platform,
                   "n_req": n_req, "slots": slots,
                   "prompt_len": sprompt_len, "max_new": smax_new}
    metric2 = "serve_ttft_queue_share"
    push_history(metric2, queue_share, "%", match=serve_match,
                 extra={"queue_p50_s": round(q50, 4),
                        "prefill_p50_s": round(p50, 4),
                        "decode_p50_s": round(d50, 4),
                        "ttft_p50_s": round(t50, 4)})

    print(json.dumps({
        "metric": metric, "value": round(share_pct, 2), "unit": "%",
        "vs_baseline": round(share_pct / base, 3) if base else 1.0,
        "kind": report.get("kind"),
        "makespan_s": round(report.get("makespan_s", 0.0), 4),
        "critical_path": (report.get("critical_names")
                          or report.get("critical_path") or [])[:8],
        "extra_metrics": [
            {"metric": metric2, "value": round(queue_share, 2),
             "unit": "%", "queue_p50_ms": round(q50 * 1e3, 2),
             "prefill_p50_ms": round(p50 * 1e3, 2),
             "decode_p50_ms": round(d50 * 1e3, 2)}],
    }))


def bench_soak(quick: bool, minutes: float = 5.0,
               load_s: float | None = None) -> dict:
    """Leak-ledger soak gate (README "Leak ledger & soak gating").

    Drives mixed unary/streaming serve load plus out-of-process task
    storms while periodically killing a replica mid-stream
    (ServeFaultInjector.crash_on_request) and SIGKILLing a busy
    worker, then quiesces. PASS requires, at quiescence:

      1. cross-plane reconciliation green, and
      2. zero LIVE leak suspects (chaos-churned entries must all have
         been reclaimed or released);

    then proves the detector itself works: a dropped slot release
    (`AdmissionController.inject_fault("drop_release")`) must be
    flagged as a leak suspect — attributed to THIS file's acquisition
    site — within one reconciliation period of crossing the age
    threshold. Exits nonzero on failure; one JSON line on success.
    `--quick` is the ~60s tier-1 smoke; the full run load-cycles for
    `minutes` (--soak-minutes)."""
    import random
    import signal
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu._private.config import config
    from ray_tpu._private.fault_injection import ServeFaultInjector
    from ray_tpu.core.task import NodeAffinitySchedulingStrategy
    from ray_tpu.observability.ledger import get_ledger

    # Tight cadence so the smoke observes several reconciliation
    # passes; the leak floor is dropped so the injected leak crosses
    # its threshold in seconds instead of the production 30.
    interval_s, leak_floor_s = 1.0, 3.0
    config.apply({"ledger_interval_s": interval_s,
                  "ledger_leak_min_age_s": leak_floor_s,
                  "ledger_leak_k": 8.0})
    if load_s is None:
        load_s = 12.0 if quick else max(60.0, minutes * 60.0)
    kill_every_s = min(4.0 if quick else 15.0, max(1.0, load_s / 3))

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0, num_worker_procs=2)
    lg = get_ledger()
    proc = NodeAffinitySchedulingStrategy(node_id="node-procs",
                                          soft=False)

    @serve.deployment(num_replicas=2, max_request_retries=3)
    class SoakApp:
        def __call__(self, x):
            time.sleep(0.01)
            return x * 2

        def stream(self, n):
            for i in range(n):
                time.sleep(0.002)
                yield i

    @ray_tpu.remote(scheduling_strategy=proc, max_retries=3)
    def storm(i):
        return os.getpid()

    handle = serve.run(SoakApp.bind())
    injector = ServeFaultInjector(handle._controller)
    stop = threading.Event()
    stats = {"unary": 0, "stream": 0, "storm": 0, "errors": 0}
    stats_lock = threading.Lock()

    def _count(key, n=1):
        with stats_lock:
            stats[key] += n

    def unary_loop():
        while not stop.is_set():
            futs = [handle.remote(i) for i in range(8)]
            for f in futs:
                try:
                    f.result(timeout=60)
                    _count("unary")
                except Exception:  # noqa: BLE001 — chaos in flight
                    _count("errors")

    def stream_loop():
        sh = handle.options(method_name="stream", stream=True)
        while not stop.is_set():
            try:
                for r in sh.remote(20):
                    ray_tpu.get(r)
                _count("stream")
            except Exception:  # noqa: BLE001 — replica died mid-stream
                _count("errors")

    def storm_loop():
        while not stop.is_set():
            refs = [storm.remote(i) for i in range(16)]
            try:
                ray_tpu.get(refs, timeout=60)
                _count("storm", 16)
            except Exception:  # noqa: BLE001 — worker killed mid-task
                _count("errors")

    threads = [threading.Thread(target=fn, daemon=True)
               for fn in (unary_loop, stream_loop, storm_loop)]
    for t in threads:
        t.start()

    rng = random.Random(0)
    t_end = time.monotonic() + load_s
    next_kill, kill_replica = time.monotonic() + kill_every_s, True
    kills = {"replica": 0, "worker": 0}
    while time.monotonic() < t_end:
        time.sleep(0.25)
        if time.monotonic() < next_kill:
            continue
        next_kill = time.monotonic() + kill_every_s
        try:
            if kill_replica:
                # Replica dies on its next request — mid-stream, given
                # the streaming loop's constant pressure.
                injector.crash_on_request(
                    "SoakApp", count=1, replica_index=rng.randrange(2))
                kills["replica"] += 1
            else:
                # SIGKILL a live worker process mid-hand-off.
                pid = ray_tpu.get(storm.remote(0), timeout=30)
                os.kill(pid, signal.SIGKILL)
                kills["worker"] += 1
        except Exception:  # noqa: BLE001 — racing prior chaos
            pass
        kill_replica = not kill_replica
    stop.set()
    for t in threads:
        t.join(timeout=90)

    # Load can end with a crash still armed (it fires on the NEXT
    # request) or a replica mid-replacement; drain that before gating —
    # the probe absorbs the armed crash and proves the door is healthy.
    deadline = time.monotonic() + 60
    while True:
        try:
            handle.remote(-1).result(timeout=10)
            break
        except Exception:  # noqa: BLE001 — replacement in progress
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.5)

    # Quiescence: all load stopped; give the planes a few snapshot
    # periods to drain, then demand green + zero live suspects.
    verdict, live = None, None
    deadline = time.monotonic() + max(20.0, 10 * interval_s)
    while time.monotonic() < deadline:
        time.sleep(interval_s)
        rep = lg.snapshot()
        verdict, live = rep["reconciliation"], lg.live_suspects()
        if verdict["green"] and not live:
            break
    ok_quiesce = bool(verdict and verdict["green"] and not live)
    if not ok_quiesce:
        print(json.dumps({"metric": "soak", "pass": False,
                          "phase": "quiescence",
                          "reconciliation": verdict,
                          "live_suspects": live, "stats": stats,
                          "kills": kills}))
        serve.shutdown()
        ray_tpu.shutdown()
        sys.exit(1)

    # Injected leak: drop the NEXT slot release on the handle — the
    # slot and its ledger entry stay held forever. The detector must
    # flag it within one reconciliation period of crossing the age
    # threshold, attributed to this file.
    handle._router.admission.inject_fault("drop_release", 1)
    handle.remote(99).result(timeout=60)
    t_inj = time.time()
    threshold = lg.detector.threshold_s("serve.handle")
    flagged = None
    deadline = t_inj + threshold + 3 * interval_s + 10.0
    while time.time() < deadline and flagged is None:
        time.sleep(interval_s / 2)
        lg.snapshot()
        for s in lg.live_suspects():
            if s.get("plane") == "serve.handle":
                flagged = s
                break
    detect_s = time.time() - t_inj
    site = (flagged or {}).get("site", "")
    ok_leak = flagged is not None and "bench" in site
    serve.shutdown()
    ray_tpu.shutdown()
    if not ok_leak:
        print(json.dumps({"metric": "soak", "pass": False,
                          "phase": "injected_leak", "flagged": flagged,
                          "threshold_s": threshold,
                          "waited_s": round(detect_s, 1)}))
        sys.exit(1)

    out = {
        "metric": "soak", "pass": True, "quick": quick,
        "load_s": load_s, "stats": stats, "kills": kills,
        "leak_detect_s": round(detect_s, 2),
        "leak_threshold_s": round(threshold, 2),
        "leak_site": site,
    }
    # Gate the lag PAST the age threshold, not raw detection time: the
    # threshold is learned from the run's own hold history, so raw
    # detect_s varies with load shape while the lag should always be
    # about one reconciliation period.
    push_history("soak_leak_detection_lag_s",
                 max(0.0, detect_s - threshold), "s",
                 match={"quick": quick},
                 extra={"detect_s": round(detect_s, 2),
                        "threshold_s": round(threshold, 2),
                        "kills": kills})
    print(json.dumps(out))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny config + fewer steps (smoke test)")
    # 180 → 60-step segments: on the ~150ms-RTT tunneled chip the final
    # sync's RTT is amortized over the segment, so short segments
    # under-report the device rate by ~10% (6-step segments) vs ~1%
    # (60-step). Segments repeat until the two fastest agree within 1%.
    ap.add_argument("--steps", type=int, default=180)
    ap.add_argument("--batch", type=int, default=0, help="0 = auto")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--model", default=None,
                    help="named model config "
                         "(gpt2-125m, llama-654m, llama-1b4); default "
                         "gpt2-125m, except --serve-prefix defaults to "
                         "llama-654m")
    ap.add_argument("--serve-prefix", action="store_true",
                    help="prefix-caching serving scenario (admission-"
                         "wave device-time speedup; default model "
                         "llama-654m)")
    ap.add_argument("--serve", action="store_true",
                    help="serving benchmark (req/s + TTFT) instead of "
                         "the train step")
    ap.add_argument("--vit", action="store_true",
                    help="image-model benchmark (BASELINE config 4)")
    ap.add_argument("--rlhf", action="store_true",
                    help="end-to-end GRPO RLHF loop (north-star "
                         "config 5): rollout tokens/s, iteration "
                         "wall-clock, weight-refresh seconds")
    ap.add_argument("--critpath", action="store_true",
                    help="critical-path attribution scoreboard: traced "
                         "RLHF iteration's dispatch share of the "
                         "critical path + serve TTFT queue share "
                         "(the ROADMAP item 3 baseline)")
    ap.add_argument("--soak", action="store_true",
                    help="leak-ledger soak gate: mixed serve load + "
                         "task storms + replica/worker kills; passes "
                         "only if reconciliation is green and zero "
                         "leak suspects remain at quiescence, and an "
                         "injected dropped release is detected and "
                         "site-attributed (--quick = ~60s smoke)")
    ap.add_argument("--soak-minutes", type=float, default=5.0,
                    help="load duration for the full --soak run "
                         "(ignored under --quick)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="record the run's tracing spans and write a "
                         "chrome://tracing JSON to PATH")
    ap.add_argument("--profile", nargs="?", const="bench.profile.collapsed",
                    default=None, metavar="PATH",
                    help="sample this process's stacks for the whole "
                         "run and write a collapsed flamegraph to PATH "
                         "(default bench.profile.collapsed); also "
                         "reports the sampler's measured overhead")
    ap.add_argument("--check-regressions", action="store_true",
                    help="no new run: compare the freshest "
                         "BENCH_HISTORY.json row of each metric/config "
                         "group against the trailing median of its "
                         "prior rows; exit 1 on any regression beyond "
                         "the threshold")
    ap.add_argument("--regression-threshold", type=float, default=10.0,
                    metavar="PCT",
                    help="regression tolerance in percent (default 10)")
    ap.add_argument("--advisory", action="store_true",
                    help="with --check-regressions: report regressions "
                         "but exit 0 — the tier-1 verify flow runs "
                         "this shape so a noisy bench box cannot fail "
                         "the gate, while the verdict still lands in "
                         "the log")
    ap.add_argument("--history", default=None, metavar="PATH",
                    help="BENCH_HISTORY.json override "
                         "(--check-regressions)")
    args = ap.parse_args()

    if args.check_regressions:
        regs = check_regressions(
            threshold_pct=args.regression_threshold,
            hist_path=args.history)
        if regs:
            verdict = "ADVISORY" if args.advisory else "FAIL"
            print(f"{verdict}: {len(regs)} regression(s) beyond "
                  f"{args.regression_threshold:.0f}%", file=sys.stderr)
            if not args.advisory:
                sys.exit(1)
        else:
            print("no regressions", file=sys.stderr)
        return

    if args.profile:
        _run_profiled(args)
    else:
        _maybe_traced_run(args)


def _maybe_traced_run(args) -> None:
    if args.trace:
        from ray_tpu.util import tracing

        spans: list = []
        tracing.setup_tracing(spans.append)
        root = tracing.span("bench", "bench",
                            argv=" ".join(sys.argv[1:]))
        root.__enter__()
        try:
            _run(args)
        finally:
            root.__exit__(None, None, None)
            tracing.clear_tracing()
            with open(args.trace, "w") as f:
                json.dump(spans, f)
            print(f"wrote {len(spans)} trace events to {args.trace}",
                  file=sys.stderr)
    else:
        _run(args)


def _sampler_overhead(interval_s: float = 0.01) -> tuple:
    """(off_s, on_s) wall time of a fixed-work busy loop without/with
    the sampler armed. Measured on synthetic work, NOT by running the
    bench twice — a second real run would double-push BENCH_HISTORY
    and pay minutes of wall clock for one percentage."""
    import time as _time

    from ray_tpu.observability import StackSampler

    def busy() -> int:
        x = 0
        for i in range(2_000_000):
            x += i * i
        return x

    busy()  # warm caches/JIT-free but stabilizes first-run noise
    t0 = _time.perf_counter()
    busy()
    off = _time.perf_counter() - t0
    sampler = StackSampler(interval_s=interval_s)
    sampler.start()
    try:
        t0 = _time.perf_counter()
        busy()
        on = _time.perf_counter() - t0
    finally:
        sampler.stop()
    return off, on


def _contprof_overhead(reps: int = 12) -> tuple:
    """(off_s, on_s) wall time of fixed busy work without/with the
    CONTINUOUS profiler armed — same synthetic-work rationale as
    _sampler_overhead, but against the always-on duty-cycled loop.
    Measured at a 5% duty cycle (1s interval, 50ms capture), which
    upper-bounds the production ~3% (2s every 60s)."""
    import tempfile
    import time as _time

    from ray_tpu.observability.continuous import ContinuousProfiler

    def busy() -> int:
        x = 0
        for i in range(2_000_000):
            x += i * i
        return x

    busy()
    t0 = _time.perf_counter()
    for _ in range(reps):
        busy()
    off = _time.perf_counter() - t0
    with tempfile.TemporaryDirectory() as d:
        prof = ContinuousProfiler(
            "bench", directory=d, interval_s=1.0, duration_s=0.05,
            sample_interval_s=0.01).start()
        try:
            t0 = _time.perf_counter()
            for _ in range(reps):
                busy()
            on = _time.perf_counter() - t0
        finally:
            prof.stop()
    return off, on


def _run_profiled(args) -> None:
    """Arm the on-demand stack sampler around one real bench pass and
    write the flamegraph next to the results."""
    import time as _time

    import jax

    from ray_tpu.observability import StackSampler
    from ray_tpu.observability.stack_sampler import to_collapsed

    off, on = _sampler_overhead()
    overhead_pct = max(0.0, (on - off) / off * 100.0) if off else 0.0
    # Always-on-vs-off row: the continuous profiler's claim is that it
    # can be left on forever; the scoreboard holds it to <=3%.
    coff, con = _contprof_overhead()
    cont_pct = max(0.0, (con - coff) / coff * 100.0) if coff else 0.0
    push_history("contprof_overhead_pct", cont_pct, "%",
                 match={"platform": jax.devices()[0].platform},
                 extra={"off_s": round(coff, 4), "on_s": round(con, 4)})
    verdict = "OK (<=3%)" if cont_pct <= 3.0 else "FAIL (>3%)"
    print(f"continuous profiler overhead on a synthetic busy loop: "
          f"{cont_pct:.2f}% {verdict} "
          f"({coff * 1e3:.0f}ms off vs {con * 1e3:.0f}ms on)",
          file=sys.stderr)
    sampler = StackSampler(interval_s=0.01)
    sampler.start()
    t0 = _time.perf_counter()
    try:
        _maybe_traced_run(args)
    finally:
        wall = _time.perf_counter() - t0
        samples = sampler.stop()
        with open(args.profile, "w") as f:
            f.write(to_collapsed(samples))
        print(f"wrote {len(samples)} unique stacks to {args.profile} "
              f"(run wall {wall:.1f}s; sampler overhead on a "
              f"synthetic busy loop: {overhead_pct:.1f}% — "
              f"{off * 1e3:.0f}ms off vs {on * 1e3:.0f}ms on)",
              file=sys.stderr)


def _run(args) -> None:
    # The gate's first check is the framework's identity, not the model
    # path (VERDICT r4 #1): a broken task API must fail the bench run.
    core_api_smoke()
    print("core API smoke OK", file=sys.stderr)

    if args.soak:
        bench_soak(args.quick, minutes=args.soak_minutes)
        return
    if args.serve_prefix:
        bench_serve_prefix(args.quick, model=args.model or "llama-654m")
        return
    args.model = args.model or "gpt2-125m"
    if args.serve:
        bench_serve(args.quick, model=args.model)
        return
    if args.vit:
        bench_vit(args.quick)
        return
    if args.rlhf:
        bench_rlhf(args.quick, model=args.model)
        return
    if args.critpath:
        bench_critpath(args.quick, model=args.model)
        return

    out = bench_train(model=args.model, quick=args.quick,
                      steps=args.steps, batch=args.batch, seq=args.seq)

    # Gate promotion (VERDICT r4 #7): the driver-captured line must
    # reflect the stack's real MFU (654M is matmul-saturated; the 125M
    # flagship is d768-bound at ~39% by construction) and the serving
    # path. One JSON line, three metrics: flagship train + 654M train
    # MFU + 654M serve burst ride along under "extra_metrics". The
    # ride-alongs run at their PINNED configs (seq=1024, 7-trial burst
    # protocol) regardless of --seq, or the bars silently stop applying.
    on_tpu = out.get("platform") not in ("cpu", None)
    if (on_tpu and not args.quick and args.model == "gpt2-125m"
            and args.seq == 1024):  # the driver's default invocation;
        # long-seq sweeps are their own measurement, not gate runs
        extras = []
        try:
            extras.append(bench_train(model="llama-654m", quick=False,
                                      steps=180, batch=0, seq=1024))
        except (Exception, SystemExit) as e:  # noqa: BLE001 - incl.
            # sys.exit; the flagship line must print no matter what the
            # extra does (Ctrl-C still interrupts)
            extras.append({"metric": "llama_654m_train", "error": repr(e)})
        try:
            extras.append(bench_serve(False, model="llama-654m",
                                      trials=7, emit=False))
        except (Exception, SystemExit) as e:  # noqa: BLE001
            extras.append({"metric": "llama_654m_serve", "error": repr(e)})
        out["extra_metrics"] = extras
    print(json.dumps(out))


def bench_train(model: str, quick: bool, steps: int, batch: int,
                seq: int) -> dict:
    """Train-step throughput for one model config; pushes history and
    returns the result dict (caller prints)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import configs
    from ray_tpu.parallel import ParallelPlan, make_mesh
    from ray_tpu.train.step import (
        init_state,
        make_optimizer,
        make_train_step,
        shard_batch,
    )

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n_dev = len(devices)

    if quick or not on_tpu:
        if model != "gpt2-125m":
            sys.exit(f"--model {model} needs the full TPU run "
                     "(it would be silently replaced by the tiny smoke "
                     "config here)")
        cfg = configs.tiny_test()
        batch, seq, steps = 8, 128, 5
        metric = "tiny_train_tokens_per_sec_smoke"
    elif model != "gpt2-125m":
        # Scale points (VERDICT r2 #1): per-model batch chosen so
        # params + Adam state + full-remat activations fit 16 GiB.
        cfg = configs.get(model)
        if seq > cfg.max_seq_len:
            sys.exit(f"--seq {seq} exceeds {model} "
                     f"max_seq_len {cfg.max_seq_len}")
        auto_batch = {"llama-654m": 8, "llama-1b4": 8}.get(model, 4)
        batch = batch or auto_batch
        slug = model.replace("-", "_")
        metric = (f"{slug}_train_tokens_per_sec_per_chip" if seq == 1024
                  else f"{slug}_train_tokens_per_sec_per_chip_seq{seq}")
    else:
        from dataclasses import replace

        # remat_policy="dots" measured best at this scale (the full
        # remat/chunked-CE/batch sweep is recorded in PARITY.md).
        cfg = replace(configs.gpt2_125m(), remat_policy="dots")
        # Long sequences need smaller batches to fit activations.
        auto_batch = max(1, 16 * 1024 // seq)
        batch = batch or auto_batch
        metric = ("gpt2_125m_train_tokens_per_sec_per_chip" if seq == 1024
                  else f"gpt2_125m_train_tokens_per_sec_per_chip_seq{seq}")

    plan = ParallelPlan.auto(n_dev) if n_dev > 1 else ParallelPlan()
    mesh = make_mesh(plan, devices=devices[:plan.num_devices])
    opt = make_optimizer(lr=3e-4, warmup_steps=10, total_steps=10_000)

    with jax.sharding.set_mesh(mesh):
        state = init_state(cfg, mesh, opt, seed=0)
        step_fn = make_train_step(cfg, opt)
        k = jax.random.key(0)
        tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jnp.ones_like(tokens, dtype=jnp.float32)
        b = shard_batch(
            {"t": tokens, "y": targets, "m": mask}, mesh)

        holder = {}

        def step_once():
            nonlocal state
            state, holder["m"] = step_fn(state, b["t"], b["y"], b["m"])

        step_once()  # warmup/compile
        seg_steps = max(1, steps // 3)
        per_step = time_best_of(
            step_once, lambda: float(holder["m"]["loss"]),
            steps=seg_steps)
        assert float(holder["m"]["loss"]) == float(
            holder["m"]["loss"]), "non-finite loss"

    tokens_per_sec = batch * seq / per_step
    per_chip = tokens_per_sec / max(1, plan.num_devices)

    # MFU: achieved model FLOP/s ÷ stated chip peak. Train FLOPs/token
    # ≈ 6·N_params + 12·L·d_model·S (fwd+bwd matmuls + self-attention;
    # PaLM appendix-B accounting — remat overcounts are NOT credited).
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(
        state.params) if hasattr(x, "size"))
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    peak = _chip_peak_flops(devices[0])
    mfu = (per_chip * flops_per_token / peak) if peak else None

    # vs_baseline: ratio to the pinned bar in BASELINE.json "published"
    # (falls back to the previous comparable measurement when no pin
    # exists). "method" distinguishes best-of-segments timing from the
    # older whole-run mean; batch/seq/platform are the config identity.
    run_match = {"method": "best-of-segments", "seg_steps": seg_steps,
                 "batch": batch, "seq": seq,
                 "platform": devices[0].platform}
    prev = push_history(metric, per_chip, "tokens/s/chip",
                        match=run_match, extra={"devices": n_dev})
    base = pinned_baseline(metric, run_match) or prev
    vs = (per_chip / base) if base else 1.0

    out = {
        "metric": metric,
        "value": round(per_chip, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs, 3),
        "platform": devices[0].platform,
    }
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
        out["peak_flops_assumed"] = peak
        out["params"] = n_params
        # MFU pinned gate (VERDICT r3 #7b): at a matmul-saturated size
        # (654M+) MFU is the number the engine is judged on — the
        # flagship 125M sits at ~39% MFU by CONSTRUCTION (d768 matmuls
        # under-fill the 128x128 MXU), so a tokens/s gate there can't
        # see engine regressions the way an MFU bar at 654M can.
        if not metric.startswith("tiny_"):
            mfu_metric = metric.split("_train_")[0] + "_train_mfu"
            push_history(mfu_metric, mfu, "mfu", match=run_match,
                         extra={"peak_flops_assumed": peak})
            mfu_bar = pinned_baseline(mfu_metric, run_match)
            if mfu_bar:
                out["mfu_vs_bar"] = round(mfu / mfu_bar, 3)
    return out


if __name__ == "__main__":
    main()
