// Object transfer plane — node-to-node bulk object movement between
// per-node shared-memory stores.
//
// Capability-equivalent of the reference's object manager
// (reference: src/ray/object_manager/object_manager.h:117 — PullManager
// pull_manager.h:52, PushManager push_manager.h:30, chunked transfer
// over dedicated gRPC channels in object_manager.proto Push/Pull): each
// node runs a server thread bound to its shm arena; peers PULL objects
// (zero-copy read from the pinned arena mapping on the sending side,
// streamed in chunks, created+sealed into the receiving arena) or PUSH
// them proactively. Plain TCP instead of gRPC — the capability is the
// chunked bulk plane, not wire compatibility.
//
// Builds WITH the store core: #include "shm_store.cc" gives this
// library its own connection to the named arena; coordination with
// other processes happens through the arena's process-shared mutex.

#include "shm_store.cc"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" int rts_abort(void* handle, const uint8_t* id);

namespace {

constexpr uint64_t kChunk = 4ull << 20;  // 4 MiB write chunks
constexpr uint8_t OP_PULL = 1;
constexpr uint8_t OP_PUSH = 2;
constexpr uint8_t OP_STAT = 3;  // size query (no payload) — the pull
                                // manager's admission control needs the
                                // size BEFORE committing budget

bool send_all(int fd, const void* data, uint64_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = send(fd, p, n > kChunk ? kChunk : n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= w;
  }
  return true;
}

// Timed cv wait. Under TSAN this routes through a system_clock
// wait_until → pthread_cond_timedwait: gcc-10's libtsan has no
// interceptor for the pthread_cond_clockwait that libstdc++'s
// wait_for uses, so TSAN misses the wait's internal unlock and
// reports bogus double-locks/races on everything the lock guards.
template <typename Pred>
bool cv_wait_for_ms(std::condition_variable& cv,
                    std::unique_lock<std::mutex>& lk, int ms,
                    Pred pred) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(lk,
                       std::chrono::system_clock::now() +
                           std::chrono::milliseconds(ms),
                       pred);
#else
  return cv.wait_for(lk, std::chrono::milliseconds(ms), pred);
#endif
}

bool recv_all(int fd, void* data, uint64_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

struct TransferServer {
  void* store = nullptr;     // rts_connect handle (owned)
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread acceptor;
  // Connection workers run DETACHED (no zombie std::thread per
  // connection); stop() shuts their sockets down and waits for the
  // active count to drain.
  std::atomic<int> active_workers{0};
  std::mutex fd_mu;
  std::vector<int> conn_fds;

  ~TransferServer() = default;
};

void drain(int fd, uint64_t left) {
  std::vector<char> sink(left > kChunk ? kChunk : left);
  while (left > 0) {
    uint64_t n = left > sink.size() ? sink.size() : left;
    if (!recv_all(fd, sink.data(), n)) return;
    left -= n;
  }
}

void serve_conn(TransferServer* ts, int fd) {
  Store* st = reinterpret_cast<Store*>(ts->store);
  for (;;) {
    uint8_t op;
    if (!recv_all(fd, &op, 1)) break;
    uint8_t id[kIdLen];
    if (!recv_all(fd, id, kIdLen)) break;

    if (op == OP_PULL) {
      uint64_t off = 0, size = 0;
      int64_t rsize = -1;
      // Pin while sending so eviction can't pull the mapping out from
      // under the send (reference: object pinning during transfer).
      bool pinned = rts_get(ts->store, id, &off, &size, 1) == 0;
      if (pinned) rsize = static_cast<int64_t>(size);
      if (!send_all(fd, &rsize, 8)) {
        if (pinned) rts_release(ts->store, id);
        break;
      }
      bool ok = true;
      if (pinned) {
        ok = send_all(fd, st->base + off, size);
        rts_release(ts->store, id);
      }
      if (!ok) break;
    } else if (op == OP_STAT) {
      uint64_t off = 0, size = 0;
      int64_t rsize = -1;
      if (rts_get(ts->store, id, &off, &size, 0) == 0)
        rsize = static_cast<int64_t>(size);
      if (!send_all(fd, &rsize, 8)) break;
    } else if (op == OP_PUSH) {
      uint64_t size = 0;
      if (!recv_all(fd, &size, 8)) break;
      uint64_t off = 0;
      uint8_t status = 0;
      int rc = rts_create(ts->store, id, size, &off);
      if (rc == 0) {
        if (!recv_all(fd, st->base + off, size)) {
          rts_abort(ts->store, id);
          break;
        }
        rts_seal(ts->store, id);
      } else {
        // Duplicate (-1, idempotent success) or store full (status 2):
        // either way the payload is in flight — drain it so the
        // persistent connection stays framed and the peer gets the
        // REAL status instead of a reset mid-send.
        drain(fd, size);
        if (rc != -1) status = 2;
      }
      if (!send_all(fd, &status, 1)) break;
    } else {
      break;
    }
  }
  close(fd);
}

}  // namespace

extern "C" {

// Abort a created-but-unsealed object (receiver-side failure path).
int rts_abort(void* handle, const uint8_t* id) {
  return rts_delete(handle, id);
}

// bind_all != 0 → 0.0.0.0 (real node-to-node topologies); 0 →
// loopback (same-host testing without exposing the arena).
void* rto_serve(const char* shm_name, uint64_t capacity, int port,
                int bind_all) {
  void* store = rts_connect(shm_name, capacity, 0);
  if (store == nullptr) return nullptr;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(bind_all ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    rts_disconnect(store);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  TransferServer* ts = new TransferServer();
  ts->store = store;
  ts->listen_fd = fd;
  ts->port = ntohs(addr.sin_port);
  ts->acceptor = std::thread([ts]() {
    for (;;) {
      int cfd = accept(ts->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (ts->stopping.load()) return;
        if (errno == EINTR) continue;
        return;
      }
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lock(ts->fd_mu);
        if (ts->stopping.load()) {
          close(cfd);
          continue;
        }
        ts->conn_fds.push_back(cfd);
      }
      ts->active_workers.fetch_add(1);
      std::thread([ts, cfd]() {
        serve_conn(ts, cfd);
        {
          std::lock_guard<std::mutex> lock(ts->fd_mu);
          auto& v = ts->conn_fds;
          v.erase(std::remove(v.begin(), v.end(), cfd), v.end());
        }
        ts->active_workers.fetch_sub(1);
      }).detach();
    }
  });
  return ts;
}

int rto_port(void* handle) {
  return reinterpret_cast<TransferServer*>(handle)->port;
}

void rto_stop(void* handle) {
  TransferServer* ts = reinterpret_cast<TransferServer*>(handle);
  ts->stopping.store(true);
  shutdown(ts->listen_fd, SHUT_RDWR);
  close(ts->listen_fd);
  if (ts->acceptor.joinable()) ts->acceptor.join();
  // Kick idle workers out of recv_all — an open-but-quiet client must
  // not wedge stop().
  {
    std::lock_guard<std::mutex> lock(ts->fd_mu);
    for (int fd : ts->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  while (ts->active_workers.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  rts_disconnect(ts->store);
  delete ts;
}

// Client-side persistent connection to a peer's transfer server.
void* rto_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return reinterpret_cast<void*>(static_cast<intptr_t>(fd) + 1);
}

void rto_close(void* conn) {
  close(static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1);
}

// Pull `id` from the peer into the local arena. Returns 0 on success,
// -1 remote miss, -2 local store full, -3 wire error, -4 local dup.
int rto_pull(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint8_t op = OP_PULL;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t size;
  if (!recv_all(fd, &size, 8)) return -3;
  if (size < 0) return -1;
  uint64_t off = 0;
  int rc = rts_create(local_store, id, size, &off);
  if (rc != 0) {
    // Duplicate (-1) or local store full: the server is already
    // streaming `size` bytes — drain them or the persistent
    // connection desyncs and every later request reads payload bytes
    // as headers.
    drain(fd, size);
    return rc == -1 ? -4 : -2;
  }
  if (!recv_all(fd, st->base + off, size)) {
    rts_abort(local_store, id);
    return -3;
  }
  rts_seal(local_store, id);
  return 0;
}

// Size of `id` on the peer without transferring it. >=0 size, -1 miss,
// -3 wire error.
int64_t rto_stat(void* conn, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  uint8_t op = OP_STAT;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t size;
  if (!recv_all(fd, &size, 8)) return -3;
  return size;
}

// Push a local object to the peer. Returns 0 ok, -1 local miss,
// -2 peer full, -3 wire error.
int rto_push(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint64_t off = 0, size = 0;
  if (rts_get(local_store, id, &off, &size, 1) != 0) return -1;
  uint8_t op = OP_PUSH;
  bool ok = send_all(fd, &op, 1) && send_all(fd, id, kIdLen) &&
            send_all(fd, &size, 8) && send_all(fd, st->base + off, size);
  rts_release(local_store, id);
  if (!ok) return -3;
  uint8_t status = 0;
  if (!recv_all(fd, &status, 1)) return -3;
  return status == 0 ? 0 : -2;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Pull/Push manager — the transfer-plane POLICY layer.
//
// Reference capabilities (re-designed, not translated):
//   pull_manager.h:52  — fair queueing across requesters, a global
//                        in-flight byte budget, retry, cancellation,
//                        sender-death abort surfaced to the puller;
//   push_manager.h:30  — chunked push scheduling under the same
//                        in-flight budget.
//
// Architecture: N worker threads drain per-requester FIFO queues in
// round-robin order (one requester's thousand pulls cannot starve
// another's one). Before streaming, a worker learns the object's size
// (OP_STAT) and blocks until the global in-flight byte total fits the
// budget (an oversized object is admitted only alone, so it can never
// deadlock). Wire errors retry with a fresh connection; every socket
// carries SO_RCVTIMEO/SO_SNDTIMEO so a dead or wedged sender turns
// into a timeout, the partially-created local object is aborted
// (rts_abort inside rto_pull) and the final status is surfaced to the
// waiter. Concurrent requests for the same id coalesce onto one
// transfer (reference: PullManager object deduplication).
// ---------------------------------------------------------------------------

namespace {

struct PullOp {
  uint64_t requester;
  std::string host;
  int port;
  std::string ep;                   // "host:port" concurrency bucket
  uint8_t id[kIdLen];
  bool is_push;
  std::atomic<int> status{1};       // 1 = pending/running
  std::vector<uint64_t> tickets;    // all waiters coalesced onto this op
  bool queued = true;
};

struct PullMgr {
  void* store = nullptr;            // local arena (owned)
  uint64_t budget;
  uint64_t inflight = 0;
  int timeout_ms;
  int retries;
  int ep_cap = 3;  // max workers on ONE endpoint: a dead peer's
                   // timeouts must not occupy every worker and stall
                   // pulls from healthy peers
  std::mutex mu;
  std::condition_variable work_cv;  // queue -> workers
  std::condition_variable done_cv;  // op completion -> waiters
  std::condition_variable budget_cv;
  std::map<uint64_t, std::deque<PullOp*>> queues;  // per requester
  uint64_t rr_key = 0;              // fair cursor (next requester >=)
  std::unordered_map<std::string, int> ep_active;
  std::unordered_map<std::string, PullOp*> by_id;  // coalesce (pulls,
                                                   // keyed id+endpoint)
  std::unordered_map<uint64_t, PullOp*> tickets;
  uint64_t next_ticket = 1;
  uint64_t queued_ops = 0, active_ops = 0;
  int wait_refs = 0;  // rtp_wait callers inside the manager — rtp_stop
                      // must not free the manager under them
  bool stopping = false;
  std::vector<std::thread> workers;
};

std::string coalesce_key(const uint8_t* id, const std::string& ep) {
  // Endpoint is part of the identity: a pull naming a HEALTHY source
  // must not coalesce onto (and inherit the failure of) an in-flight
  // pull of the same object from a dead one.
  return std::string(reinterpret_cast<const char*>(id), kIdLen) + "@" +
         ep;
}

void set_socket_timeouts(int fd, int timeout_ms) {
  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

// Per-worker endpoint->connection cache. Keyed by "host:port".
struct WorkerConns {
  std::unordered_map<std::string, void*> conns;

  void* get(const std::string& host, int port, int timeout_ms) {
    std::string key = host + ":" + std::to_string(port);
    auto it = conns.find(key);
    if (it != conns.end()) return it->second;
    void* c = rto_connect(host.c_str(), port);
    if (c != nullptr) {
      int fd = static_cast<int>(reinterpret_cast<intptr_t>(c)) - 1;
      set_socket_timeouts(fd, timeout_ms);
      conns[key] = c;
    }
    return c;
  }

  void drop(const std::string& host, int port) {
    std::string key = host + ":" + std::to_string(port);
    auto it = conns.find(key);
    if (it != conns.end()) {
      rto_close(it->second);
      conns.erase(it);
    }
  }

  void close_all() {
    for (auto& kv : conns) rto_close(kv.second);
    conns.clear();
  }
};

// Fair pick: round-robin over requester queues, skipping ops whose
// endpoint already has ep_cap workers on it. Returns nullptr when no
// eligible op exists (caller re-waits).
PullOp* next_op_locked(PullMgr* m) {
  if (m->queues.empty()) return nullptr;
  // Walk the ordered map in place starting at the round-robin cursor
  // (lower_bound + wrap) instead of materializing a key vector per
  // pick — the pick runs under m->mu on every worker dispatch.
  const size_t n = m->queues.size();
  auto it = m->queues.lower_bound(m->rr_key);
  for (size_t k = 0; k < n; ++k, ++it) {
    if (it == m->queues.end()) it = m->queues.begin();
    if (it->second.empty()) continue;
    PullOp* op = it->second.front();
    // find(), not operator[]: a saturation probe must not plant
    // permanent zero-count entries for every endpoint it skips.
    auto ea = m->ep_active.find(op->ep);
    if (ea != m->ep_active.end() && ea->second >= m->ep_cap) continue;
    it->second.pop_front();
    uint64_t key = it->first;
    if (it->second.empty()) m->queues.erase(it);
    m->rr_key = key + 1;
    m->ep_active[op->ep]++;
    return op;
  }
  return nullptr;
}

void finish_op_locked(PullMgr* m, PullOp* op, int status) {
  op->status.store(status);
  if (!op->is_push) {
    m->by_id.erase(coalesce_key(op->id, op->ep));
  }
  auto ea = m->ep_active.find(op->ep);
  if (ea != m->ep_active.end() && --ea->second <= 0)
    m->ep_active.erase(ea);
  m->active_ops--;
  m->done_cv.notify_all();
  m->work_cv.notify_all();  // endpoint slot freed — re-run the pick
  if (op->tickets.empty()) {
    // Every waiter cancelled (rtp_cancel) while the op ran: nobody
    // will ever rtp_wait it — free it now or it leaks for the
    // manager's lifetime.
    delete op;
  }
}

void pull_worker(PullMgr* m) {
  WorkerConns conns;
  for (;;) {
    PullOp* op;
    {
      std::unique_lock<std::mutex> lk(m->mu);
      // wait_for (not wait): with work queued but every op's endpoint
      // saturated, the predicate is true yet nothing is runnable — the
      // timeout turns that state into a cheap poll; completions also
      // notify, so pickup is normally immediate.
      cv_wait_for_ms(m->work_cv, lk, 50, [m] {
        return m->stopping || m->queued_ops > 0;
      });
      if (m->stopping) break;
      op = next_op_locked(m);
      if (op == nullptr) continue;
      m->queued_ops--;
      m->active_ops++;
      op->queued = false;
    }

    int rc = -3;
    uint64_t admitted = 0;
    for (int attempt = 0; attempt <= m->retries; attempt++) {
      // Local-presence FIRST: an object already in the local arena
      // must succeed even when its source peer is dead (no connect).
      if (!op->is_push && rts_contains(m->store, op->id)) {
        rc = 0;
        break;
      }
      void* conn = conns.get(op->host, op->port, m->timeout_ms);
      if (conn == nullptr) {
        rc = -3;
        continue;  // connect refused/timed out — retry
      }
      int64_t size;
      if (op->is_push) {
        uint64_t off = 0, sz = 0;
        if (rts_get(m->store, op->id, &off, &sz, 0) != 0) {
          rc = -1;
          break;  // local miss: nothing to push, no retry will help
        }
        size = static_cast<int64_t>(sz);
      } else {
        size = rto_stat(conn, op->id);
        if (size == -1) {
          rc = -1;
          break;  // remote miss is authoritative, not retryable here
        }
        if (size < 0) {
          conns.drop(op->host, op->port);
          rc = -3;
          continue;
        }
      }
      {
        std::unique_lock<std::mutex> lk(m->mu);
        uint64_t need = static_cast<uint64_t>(size);
        m->budget_cv.wait(lk, [m, need] {
          return m->stopping || m->inflight + need <= m->budget ||
                 m->inflight == 0;  // oversized: admit alone
        });
        if (m->stopping) {
          rc = -6;
          break;
        }
        m->inflight += need;
        admitted = need;
      }
      rc = op->is_push ? rto_push(conn, m->store, op->id)
                       : rto_pull(conn, m->store, op->id);
      {
        std::lock_guard<std::mutex> lk(m->mu);
        m->inflight -= admitted;
        admitted = 0;
        m->budget_cv.notify_all();
      }
      if (rc == -4) rc = 0;  // already present locally = success
      if (rc != -3) break;   // success or non-wire error: done
      // Wire error (sender died / timed out mid-transfer): the partial
      // local object was aborted inside rto_pull; reconnect and retry.
      conns.drop(op->host, op->port);
    }
    if (admitted) {
      std::lock_guard<std::mutex> lk(m->mu);
      m->inflight -= admitted;
      m->budget_cv.notify_all();
    }
    {
      std::lock_guard<std::mutex> lk(m->mu);
      finish_op_locked(m, op, rc);
    }
  }
  conns.close_all();
}

}  // namespace

extern "C" {

// budget_bytes: global in-flight byte cap (0 = half the arena — tied
// to the receiving arena's capacity so concurrent pulls cannot blow it
// out). timeout_ms guards every socket op; retries = extra attempts
// after a wire error.
void* rtp_start(const char* shm_name, uint64_t budget_bytes,
                int nworkers, int timeout_ms, int retries) {
  void* store = rts_connect(shm_name, 0, 0);
  if (store == nullptr) return nullptr;
  PullMgr* m = new PullMgr();
  m->store = store;
  m->budget = budget_bytes ? budget_bytes : rts_capacity(store) / 2;
  m->timeout_ms = timeout_ms > 0 ? timeout_ms : 30000;
  m->retries = retries >= 0 ? retries : 2;
  if (nworkers <= 0) nworkers = 4;
  // Leave at least one worker free of any single endpoint so a dead
  // peer's socket timeouts cannot stall pulls from healthy peers.
  m->ep_cap = nworkers > 1 ? nworkers - 1 : 1;
  for (int i = 0; i < nworkers; i++) {
    m->workers.emplace_back(pull_worker, m);
  }
  return m;
}

// Enqueue a pull (is_push=0) of `id` from host:port into the local
// arena, or a push (is_push=1) of local `id` to host:port. `requester`
// is the fairness key (per consumer). Returns a ticket for rtp_wait.
uint64_t rtp_submit(void* handle, uint64_t requester, const char* host,
                    int port, const uint8_t* id, int is_push) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::string ep = std::string(host) + ":" + std::to_string(port);
  std::lock_guard<std::mutex> lk(m->mu);
  uint64_t t = m->next_ticket++;
  if (!is_push) {
    // Coalesce onto an in-flight pull of the same object FROM THE
    // SAME endpoint (a healthy alternate source must not inherit a
    // dead source's failure).
    auto it = m->by_id.find(coalesce_key(id, ep));
    if (it != m->by_id.end()) {
      it->second->tickets.push_back(t);
      m->tickets[t] = it->second;
      return t;
    }
  }
  PullOp* op = new PullOp();
  op->requester = requester;
  op->host = host;
  op->port = port;
  op->ep = std::move(ep);
  memcpy(op->id, id, kIdLen);
  op->is_push = is_push != 0;
  op->tickets.push_back(t);
  if (!is_push) {
    m->by_id[coalesce_key(id, op->ep)] = op;
  }
  m->tickets[t] = op;
  m->queues[requester].push_back(op);
  m->queued_ops++;
  m->work_cv.notify_one();
  return t;
}

// Block until the ticket's transfer completes (or timeout_ms passes).
// Returns the transfer status (0 ok, -1 miss, -2 store full, -3 wire
// error after retries, -6 manager stopping) or -5 on wait timeout.
// A completed ticket is consumed; the op is freed with its last ticket.
int rtp_wait(void* handle, uint64_t ticket, int timeout_ms) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::unique_lock<std::mutex> lk(m->mu);
  auto it = m->tickets.find(ticket);
  if (it == m->tickets.end()) return -7;  // unknown/already consumed
  PullOp* op = it->second;
  m->wait_refs++;
  auto pred = [m, op] {
    return m->stopping || op->status.load() != 1;
  };
  bool timed_out = false;
  if (timeout_ms < 0) {
    m->done_cv.wait(lk, pred);
  } else if (!cv_wait_for_ms(m->done_cv, lk, timeout_ms, pred)) {
    timed_out = true;
  }
  m->wait_refs--;
  m->done_cv.notify_all();  // rtp_stop waits on wait_refs == 0
  if (timed_out) return -5;
  int st = op->status.load();
  if (st == 1) st = -6;  // woken by stop while still pending
  m->tickets.erase(ticket);
  auto& tk = op->tickets;
  tk.erase(std::remove(tk.begin(), tk.end(), ticket), tk.end());
  if (tk.empty()) delete op;
  return st;
}

// Abandon a ticket (e.g. after a wait timeout the caller will not
// retry). The underlying transfer keeps running — other coalesced
// waiters still get it — but this ticket's registration is dropped so
// an abandoned op cannot accumulate for the manager's lifetime
// (review r5: each timed-out wait leaked its op + ticket entry).
void rtp_cancel(void* handle, uint64_t ticket) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::lock_guard<std::mutex> lk(m->mu);
  auto it = m->tickets.find(ticket);
  if (it == m->tickets.end()) return;
  PullOp* op = it->second;
  m->tickets.erase(it);
  auto& tk = op->tickets;
  tk.erase(std::remove(tk.begin(), tk.end(), ticket), tk.end());
  // Completed op with no waiters left: free now. A still-pending/
  // running op stays — the worker's finish_op_locked frees it when it
  // completes with no tickets (queued ops keep running: a coalesced
  // submit may still attach before completion).
  if (tk.empty() && op->status.load() != 1) delete op;
}

void rtp_stats(void* handle, uint64_t* inflight_bytes,
               uint64_t* queued, uint64_t* active) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::lock_guard<std::mutex> lk(m->mu);
  if (inflight_bytes) *inflight_bytes = m->inflight;
  if (queued) *queued = m->queued_ops;
  if (active) *active = m->active_ops;
}

void rtp_stop(void* handle) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  {
    std::lock_guard<std::mutex> lk(m->mu);
    m->stopping = true;
    m->work_cv.notify_all();
    m->budget_cv.notify_all();
  }
  for (auto& w : m->workers) w.join();
  {
    std::unique_lock<std::mutex> lk(m->mu);
    // Fail every queued (never-started) op so waiters unblock; a
    // queued op whose waiters all cancelled has no owner left — free
    // it here (it is not in the tickets map the sweep below walks).
    for (auto& kv : m->queues) {
      for (PullOp* op : kv.second) {
        if (op->tickets.empty()) {
          delete op;
        } else {
          op->status.store(-6);
        }
      }
    }
    m->queues.clear();
    m->done_cv.notify_all();
    // Blocked rtp_wait callers woke on `stopping`; let them leave the
    // manager before it is freed.
    m->done_cv.wait(lk, [m] { return m->wait_refs == 0; });
    // Free every op still registered (never-waited tickets included —
    // after stop there is nothing left to wait on). Ops appear under
    // one ticket per waiter; delete each once.
    std::vector<PullOp*> unique_ops;
    for (auto& kv : m->tickets) {
      if (std::find(unique_ops.begin(), unique_ops.end(), kv.second) ==
          unique_ops.end())
        unique_ops.push_back(kv.second);
    }
    m->tickets.clear();
    for (PullOp* op : unique_ops) delete op;
  }
  rts_disconnect(m->store);
  delete m;
}

}  // extern "C"
