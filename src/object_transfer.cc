// Object transfer plane — node-to-node bulk object movement between
// per-node shared-memory stores.
//
// Capability-equivalent of the reference's object manager
// (reference: src/ray/object_manager/object_manager.h:117 — PullManager
// pull_manager.h:52, PushManager push_manager.h:30, chunked transfer
// over dedicated gRPC channels in object_manager.proto Push/Pull): each
// node runs a server thread bound to its shm arena; peers PULL objects
// (zero-copy read from the pinned arena mapping on the sending side,
// streamed in chunks, created+sealed into the receiving arena) or PUSH
// them proactively. Plain TCP instead of gRPC — the capability is the
// chunked bulk plane, not wire compatibility.
//
// Builds WITH the store core: #include "shm_store.cc" gives this
// library its own connection to the named arena; coordination with
// other processes happens through the arena's process-shared mutex.

#include "shm_store.cc"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <mutex>
#include <thread>
#include <vector>

extern "C" int rts_abort(void* handle, const uint8_t* id);

namespace {

constexpr uint64_t kChunk = 4ull << 20;  // 4 MiB write chunks
constexpr uint8_t OP_PULL = 1;
constexpr uint8_t OP_PUSH = 2;

bool send_all(int fd, const void* data, uint64_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = send(fd, p, n > kChunk ? kChunk : n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= w;
  }
  return true;
}

bool recv_all(int fd, void* data, uint64_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

struct TransferServer {
  void* store = nullptr;     // rts_connect handle (owned)
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread acceptor;
  // Connection workers run DETACHED (no zombie std::thread per
  // connection); stop() shuts their sockets down and waits for the
  // active count to drain.
  std::atomic<int> active_workers{0};
  std::mutex fd_mu;
  std::vector<int> conn_fds;

  ~TransferServer() = default;
};

void drain(int fd, uint64_t left) {
  std::vector<char> sink(left > kChunk ? kChunk : left);
  while (left > 0) {
    uint64_t n = left > sink.size() ? sink.size() : left;
    if (!recv_all(fd, sink.data(), n)) return;
    left -= n;
  }
}

void serve_conn(TransferServer* ts, int fd) {
  Store* st = reinterpret_cast<Store*>(ts->store);
  for (;;) {
    uint8_t op;
    if (!recv_all(fd, &op, 1)) break;
    uint8_t id[kIdLen];
    if (!recv_all(fd, id, kIdLen)) break;

    if (op == OP_PULL) {
      uint64_t off = 0, size = 0;
      int64_t rsize = -1;
      // Pin while sending so eviction can't pull the mapping out from
      // under the send (reference: object pinning during transfer).
      bool pinned = rts_get(ts->store, id, &off, &size, 1) == 0;
      if (pinned) rsize = static_cast<int64_t>(size);
      if (!send_all(fd, &rsize, 8)) {
        if (pinned) rts_release(ts->store, id);
        break;
      }
      bool ok = true;
      if (pinned) {
        ok = send_all(fd, st->base + off, size);
        rts_release(ts->store, id);
      }
      if (!ok) break;
    } else if (op == OP_PUSH) {
      uint64_t size = 0;
      if (!recv_all(fd, &size, 8)) break;
      uint64_t off = 0;
      uint8_t status = 0;
      int rc = rts_create(ts->store, id, size, &off);
      if (rc == 0) {
        if (!recv_all(fd, st->base + off, size)) {
          rts_abort(ts->store, id);
          break;
        }
        rts_seal(ts->store, id);
      } else {
        // Duplicate (-1, idempotent success) or store full (status 2):
        // either way the payload is in flight — drain it so the
        // persistent connection stays framed and the peer gets the
        // REAL status instead of a reset mid-send.
        drain(fd, size);
        if (rc != -1) status = 2;
      }
      if (!send_all(fd, &status, 1)) break;
    } else {
      break;
    }
  }
  close(fd);
}

}  // namespace

extern "C" {

// Abort a created-but-unsealed object (receiver-side failure path).
int rts_abort(void* handle, const uint8_t* id) {
  return rts_delete(handle, id);
}

// bind_all != 0 → 0.0.0.0 (real node-to-node topologies); 0 →
// loopback (same-host testing without exposing the arena).
void* rto_serve(const char* shm_name, uint64_t capacity, int port,
                int bind_all) {
  void* store = rts_connect(shm_name, capacity, 0);
  if (store == nullptr) return nullptr;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(bind_all ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    rts_disconnect(store);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  TransferServer* ts = new TransferServer();
  ts->store = store;
  ts->listen_fd = fd;
  ts->port = ntohs(addr.sin_port);
  ts->acceptor = std::thread([ts]() {
    for (;;) {
      int cfd = accept(ts->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (ts->stopping.load()) return;
        if (errno == EINTR) continue;
        return;
      }
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lock(ts->fd_mu);
        if (ts->stopping.load()) {
          close(cfd);
          continue;
        }
        ts->conn_fds.push_back(cfd);
      }
      ts->active_workers.fetch_add(1);
      std::thread([ts, cfd]() {
        serve_conn(ts, cfd);
        {
          std::lock_guard<std::mutex> lock(ts->fd_mu);
          auto& v = ts->conn_fds;
          v.erase(std::remove(v.begin(), v.end(), cfd), v.end());
        }
        ts->active_workers.fetch_sub(1);
      }).detach();
    }
  });
  return ts;
}

int rto_port(void* handle) {
  return reinterpret_cast<TransferServer*>(handle)->port;
}

void rto_stop(void* handle) {
  TransferServer* ts = reinterpret_cast<TransferServer*>(handle);
  ts->stopping.store(true);
  shutdown(ts->listen_fd, SHUT_RDWR);
  close(ts->listen_fd);
  if (ts->acceptor.joinable()) ts->acceptor.join();
  // Kick idle workers out of recv_all — an open-but-quiet client must
  // not wedge stop().
  {
    std::lock_guard<std::mutex> lock(ts->fd_mu);
    for (int fd : ts->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  while (ts->active_workers.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  rts_disconnect(ts->store);
  delete ts;
}

// Client-side persistent connection to a peer's transfer server.
void* rto_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return reinterpret_cast<void*>(static_cast<intptr_t>(fd) + 1);
}

void rto_close(void* conn) {
  close(static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1);
}

// Pull `id` from the peer into the local arena. Returns 0 on success,
// -1 remote miss, -2 local store full, -3 wire error, -4 local dup.
int rto_pull(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint8_t op = OP_PULL;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t size;
  if (!recv_all(fd, &size, 8)) return -3;
  if (size < 0) return -1;
  uint64_t off = 0;
  int rc = rts_create(local_store, id, size, &off);
  if (rc != 0) {
    // Duplicate (-1) or local store full: the server is already
    // streaming `size` bytes — drain them or the persistent
    // connection desyncs and every later request reads payload bytes
    // as headers.
    drain(fd, size);
    return rc == -1 ? -4 : -2;
  }
  if (!recv_all(fd, st->base + off, size)) {
    rts_abort(local_store, id);
    return -3;
  }
  rts_seal(local_store, id);
  return 0;
}

// Push a local object to the peer. Returns 0 ok, -1 local miss,
// -2 peer full, -3 wire error.
int rto_push(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint64_t off = 0, size = 0;
  if (rts_get(local_store, id, &off, &size, 1) != 0) return -1;
  uint8_t op = OP_PUSH;
  bool ok = send_all(fd, &op, 1) && send_all(fd, id, kIdLen) &&
            send_all(fd, &size, 8) && send_all(fd, st->base + off, size);
  rts_release(local_store, id);
  if (!ok) return -3;
  uint8_t status = 0;
  if (!recv_all(fd, &status, 1)) return -3;
  return status == 0 ? 0 : -2;
}

}  // extern "C"
