// Object transfer plane — node-to-node bulk object movement between
// per-node shared-memory stores.
//
// Capability-equivalent of the reference's object manager
// (reference: src/ray/object_manager/object_manager.h:117 — PullManager
// pull_manager.h:52, PushManager push_manager.h:30, chunked transfer
// over dedicated gRPC channels in object_manager.proto Push/Pull): each
// node runs a server thread bound to its shm arena; peers PULL objects
// (zero-copy read from the pinned arena mapping on the sending side,
// streamed in chunks, created+sealed into the receiving arena) or PUSH
// them proactively. Plain TCP instead of gRPC — the capability is the
// chunked bulk plane, not wire compatibility.
//
// Builds WITH the store core: #include "shm_store.cc" gives this
// library its own connection to the named arena; coordination with
// other processes happens through the arena's process-shared mutex.

#include "shm_store.cc"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" int rts_abort(void* handle, const uint8_t* id);

namespace {

constexpr uint64_t kChunk = 4ull << 20;  // 4 MiB write chunks
constexpr uint8_t OP_PULL = 1;
constexpr uint8_t OP_PUSH = 2;
constexpr uint8_t OP_STAT = 3;  // size query (no payload) — the pull
                                // manager's admission control needs the
                                // size BEFORE committing budget
constexpr uint8_t OP_PULL2 = 4;  // chunk-framed pull; the sender may
                                 // RELAY an object it is itself still
                                 // receiving (committed chunks stream
                                 // onward while the tail arrives)
constexpr uint32_t kErrFrame = 0xFFFFFFFFu;  // OP_PULL2 abort marker
constexpr int kRelayDrainMs = 60000;  // writer waits this long for
                                      // relay readers to leave the raw
                                      // span before seal/abort

// Chunk-sized kernel socket buffers on every transfer socket: with the
// default ~208 KiB buffers a 4 MiB chunk needs ~20 alternating
// sender/receiver wakeups, and on an oversubscribed host that
// context-switch ping-pong — multiplied down a relay pipeline — is the
// throughput floor, not the copies. A full chunk in flight lets each
// side run a whole chunk per scheduling quantum. Best-effort: the
// kernel clamps to {w,r}mem_max.
void set_socket_buffers(int fd) {
  int sz = static_cast<int>(kChunk);
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
}

bool send_all(int fd, const void* data, uint64_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = send(fd, p, n > kChunk ? kChunk : n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= w;
  }
  return true;
}

// Timed cv wait. Under TSAN this routes through a system_clock
// wait_until → pthread_cond_timedwait: gcc-10's libtsan has no
// interceptor for the pthread_cond_clockwait that libstdc++'s
// wait_for uses, so TSAN misses the wait's internal unlock and
// reports bogus double-locks/races on everything the lock guards.
template <typename Pred>
bool cv_wait_for_ms(std::condition_variable& cv,
                    std::unique_lock<std::mutex>& lk, int ms,
                    Pred pred) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(lk,
                       std::chrono::system_clock::now() +
                           std::chrono::milliseconds(ms),
                       pred);
#else
  return cv.wait_for(lk, std::chrono::milliseconds(ms), pred);
#endif
}

bool recv_all(int fd, void* data, uint64_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Relay registry — process-local directory of objects currently being
// PULLED into an arena of this process (reference: chunked transfer +
// in-flight chunk availability in object_manager's Push pipelining).
// The receiving side of an OP_PULL2 registers here; the SAME process's
// TransferServer (daemons and the driver both run server + pull manager
// in one process) finds the entry and streams committed chunks onward
// while the tail is still arriving — an N-node broadcast chains through
// mid-pull nodes at ~O(log N) producer bandwidth instead of O(N).
// Keyed by arena name + id: one process can host several arenas.
// ---------------------------------------------------------------------------

struct Relay {
  std::mutex mu;
  std::condition_variable cv;
  uint64_t off = 0;        // arena offset of the created span (immutable)
  uint64_t total = 0;      // full object size (immutable)
  uint64_t committed = 0;  // bytes received so far (monotonic, under mu)
  int readers = 0;         // relay streams currently on the raw span
  bool failed = false;     // writer's source died mid-stream
  bool done = false;       // all bytes landed (seal follows drain)
};

std::mutex g_relay_mu;
std::unordered_map<std::string, std::shared_ptr<Relay>> g_relay;

std::string relay_id_key(const std::string& arena, const uint8_t* id) {
  return arena + "/" +
         std::string(reinterpret_cast<const char*>(id), kIdLen);
}

void relay_register(const std::string& arena, const uint8_t* id,
                    std::shared_ptr<Relay> rel) {
  std::lock_guard<std::mutex> lk(g_relay_mu);
  g_relay[relay_id_key(arena, id)] = rel;
}

void relay_erase(const std::string& arena, const uint8_t* id) {
  std::lock_guard<std::mutex> lk(g_relay_mu);
  g_relay.erase(relay_id_key(arena, id));
}

// Reader acquisition increments `readers` while still holding the
// registry lock: the writer erases the entry (registry lock) BEFORE
// waiting on readers == 0, so a reader that found the entry is always
// counted before the writer's drain check can pass.
std::shared_ptr<Relay> relay_acquire_reader(const std::string& arena,
                                            const uint8_t* id) {
  std::lock_guard<std::mutex> lk(g_relay_mu);
  auto it = g_relay.find(relay_id_key(arena, id));
  if (it == g_relay.end()) return nullptr;
  std::lock_guard<std::mutex> lk2(it->second->mu);
  it->second->readers++;
  return it->second;
}

// Size of an in-flight relay object (-1 when none): OP_STAT treats a
// mid-pull object as present so the manager's admission control — and
// source selection at the next hop down a broadcast chain — works
// before the object seals.
int64_t relay_total(const std::string& arena, const uint8_t* id) {
  std::lock_guard<std::mutex> lk(g_relay_mu);
  auto it = g_relay.find(relay_id_key(arena, id));
  if (it == g_relay.end()) return -1;
  return static_cast<int64_t>(it->second->total);
}

struct TransferServer {
  void* store = nullptr;     // rts_connect handle (owned)
  std::string arena;         // shm name (relay registry key space)
  std::atomic<uint64_t> bytes_out{0};     // payload bytes served
  std::atomic<uint64_t> relay_served{0};  // OP_PULL2 answered mid-pull
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread acceptor;
  // Connection workers run DETACHED (no zombie std::thread per
  // connection); stop() shuts their sockets down and waits for the
  // active count to drain.
  std::atomic<int> active_workers{0};
  std::mutex fd_mu;
  std::vector<int> conn_fds;

  ~TransferServer() = default;
};

void drain(int fd, uint64_t left) {
  std::vector<char> sink(left > kChunk ? kChunk : left);
  while (left > 0) {
    uint64_t n = left > sink.size() ? sink.size() : left;
    if (!recv_all(fd, sink.data(), n)) return;
    left -= n;
  }
}

// OP_PULL2 service: sealed objects stream pinned from the arena; an
// object this process is still PULLING streams its committed chunks as
// they land (relay pipelining). Frames are [u32 len][payload]; a
// kErrFrame marker tells the receiver the upstream source died (the
// connection stays cleanly framed either way). Returns false when the
// connection itself is dead.
bool serve_pull2(TransferServer* ts, int fd, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(ts->store);
  uint64_t off = 0, size = 0;
  bool pinned = rts_get(ts->store, id, &off, &size, 1) == 0;
  std::shared_ptr<Relay> rel;
  if (!pinned) {
    rel = relay_acquire_reader(ts->arena, id);
    // The in-flight pull may have sealed between the two probes
    // (writer erases the entry before sealing) — re-check sealed.
    if (!rel) pinned = rts_get(ts->store, id, &off, &size, 1) == 0;
  }
  if (pinned) {
    int64_t rsize = static_cast<int64_t>(size);
    bool ok = send_all(fd, &rsize, 8);  // cxx-wire: rto-pull2-total <q
    uint64_t sent = 0;
    while (ok && sent < size) {
      uint32_t len = static_cast<uint32_t>(
          std::min(kChunk, size - sent));
      ok = send_all(fd, &len, 4) &&  // cxx-wire: rto-pull2-chunk <I
           send_all(fd, st->base + off + sent, len);
      if (ok) sent += len;
    }
    rts_release(ts->store, id);
    ts->bytes_out.fetch_add(sent);
    return ok;
  }
  if (rel == nullptr) {
    int64_t rsize = -1;
    return send_all(fd, &rsize, 8);
  }
  ts->relay_served.fetch_add(1);
  int64_t rsize = static_cast<int64_t>(rel->total);
  bool ok = send_all(fd, &rsize, 8);
  uint64_t sent = 0;
  bool src_failed = false;
  while (ok && sent < rel->total) {
    uint64_t avail = 0;
    {
      std::unique_lock<std::mutex> lk(rel->mu);
      cv_wait_for_ms(rel->cv, lk, 100, [&] {
        return rel->failed || rel->committed > sent;
      });
      if (rel->failed) {
        src_failed = true;
        break;
      }
      avail = rel->committed;
    }
    if (avail <= sent) {
      if (ts->stopping.load()) {  // poll keeps stop() from wedging
        src_failed = true;
        break;
      }
      continue;
    }
    // Bytes below `committed` are stable (the writer only appends and
    // publishes the watermark under rel->mu) — stream without the lock.
    while (ok && sent < avail) {
      uint32_t len = static_cast<uint32_t>(
          std::min(kChunk, avail - sent));
      ok = send_all(fd, &len, 4) &&
           send_all(fd, st->base + rel->off + sent, len);
      if (ok) sent += len;
    }
  }
  if (ok && src_failed) {
    uint32_t err = kErrFrame;
    ok = send_all(fd, &err, 4);
  }
  {
    std::lock_guard<std::mutex> lk(rel->mu);
    rel->readers--;
    rel->cv.notify_all();  // writer drains on readers == 0
  }
  ts->bytes_out.fetch_add(sent);
  return ok;
}

void serve_conn(TransferServer* ts, int fd) {
  Store* st = reinterpret_cast<Store*>(ts->store);
  for (;;) {
    uint8_t op;
    if (!recv_all(fd, &op, 1)) break;
    uint8_t id[kIdLen];
    if (!recv_all(fd, id, kIdLen)) break;

    if (op == OP_PULL) {
      uint64_t off = 0, size = 0;
      int64_t rsize = -1;
      // Pin while sending so eviction can't pull the mapping out from
      // under the send (reference: object pinning during transfer).
      bool pinned = rts_get(ts->store, id, &off, &size, 1) == 0;
      if (pinned) rsize = static_cast<int64_t>(size);
      if (!send_all(fd, &rsize, 8)) {
        if (pinned) rts_release(ts->store, id);
        break;
      }
      bool ok = true;
      if (pinned) {
        ok = send_all(fd, st->base + off, size);
        rts_release(ts->store, id);
        if (ok) ts->bytes_out.fetch_add(size);
      }
      if (!ok) break;
    } else if (op == OP_PULL2) {
      if (!serve_pull2(ts, fd, id)) break;
    } else if (op == OP_STAT) {
      uint64_t off = 0, size = 0;
      int64_t rsize = -1;
      if (rts_get(ts->store, id, &off, &size, 0) == 0)
        rsize = static_cast<int64_t>(size);
      else
        rsize = relay_total(ts->arena, id);  // mid-pull counts as held
      if (!send_all(fd, &rsize, 8)) break;
    } else if (op == OP_PUSH) {
      uint64_t size = 0;
      if (!recv_all(fd, &size, 8)) break;
      uint64_t off = 0;
      uint8_t status = 0;
      int rc = rts_create(ts->store, id, size, &off);
      if (rc == 0) {
        if (!recv_all(fd, st->base + off, size)) {
          rts_abort(ts->store, id);
          break;
        }
        rts_seal(ts->store, id);
      } else {
        // Duplicate (-1, idempotent success) or store full (status 2):
        // either way the payload is in flight — drain it so the
        // persistent connection stays framed and the peer gets the
        // REAL status instead of a reset mid-send.
        drain(fd, size);
        if (rc != -1) status = 2;
      }
      if (!send_all(fd, &status, 1)) break;
    } else {
      break;
    }
  }
  close(fd);
}

// Receiver side of OP_PULL2. Registers the in-flight object in the
// relay registry as chunks land, so this process's own TransferServer
// can stream them onward mid-pull. Returns rto_pull's codes: 0 ok,
// -1 remote miss, -2 local store full, -3 wire/source error, -4 dup.
int pull2_into(int fd, void* local_store, const std::string& arena,
               const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(local_store);
  uint8_t op = OP_PULL2;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t total;
  if (!recv_all(fd, &total, 8)) return -3;
  if (total < 0) return -1;
  uint64_t off = 0;
  int crc = rts_create(local_store, id, static_cast<uint64_t>(total),
                       &off);
  bool discard = crc != 0;
  std::shared_ptr<Relay> rel;
  if (!discard) {
    rel = std::make_shared<Relay>();
    rel->off = off;
    rel->total = static_cast<uint64_t>(total);
    relay_register(arena, id, rel);
  }
  uint64_t cum = 0;
  bool wire_ok = true, peer_err = false;
  std::vector<char> sink;
  while (cum < static_cast<uint64_t>(total)) {
    uint32_t len;
    if (!recv_all(fd, &len, 4)) {
      wire_ok = false;
      break;
    }
    if (len == kErrFrame) {  // upstream source died at the sender
      peer_err = true;
      break;
    }
    if (len == 0 || len > kChunk ||
        cum + len > static_cast<uint64_t>(total)) {
      wire_ok = false;
      break;
    }
    char* dst;
    if (discard) {
      // Duplicate / store-full: the frames are in flight — consume
      // them so the persistent connection stays framed.
      sink.resize(len);
      dst = sink.data();
    } else {
      dst = reinterpret_cast<char*>(st->base + off + cum);
    }
    if (!recv_all(fd, dst, len)) {
      wire_ok = false;
      break;
    }
    cum += len;
    if (rel) {
      std::lock_guard<std::mutex> lk(rel->mu);
      rel->committed = cum;
      rel->cv.notify_all();
    }
  }
  if (discard) {
    if (!wire_ok) return -3;
    if (peer_err) return -3;
    return crc == -1 ? -4 : -2;
  }
  if (wire_ok && !peer_err && cum == static_cast<uint64_t>(total)) {
    // Publish completion, close the entry to NEW readers, let the
    // in-flight ones leave the raw span, then seal: a sealed unpinned
    // object is evictable, and relay readers stream straight from the
    // arena offset without a pin.
    {
      std::lock_guard<std::mutex> lk(rel->mu);
      rel->done = true;
      rel->cv.notify_all();
    }
    relay_erase(arena, id);
    {
      std::unique_lock<std::mutex> lk(rel->mu);
      cv_wait_for_ms(rel->cv, lk, kRelayDrainMs,
                     [&] { return rel->readers == 0; });
    }
    rts_seal(local_store, id);
    return 0;
  }
  // Source died mid-stream: fail fast to relay readers (they forward
  // the error marker down the chain), let them drain, then abort the
  // partial object so a retry from another source can re-create it.
  {
    std::lock_guard<std::mutex> lk(rel->mu);
    rel->failed = true;
    rel->cv.notify_all();
  }
  relay_erase(arena, id);
  bool drained;
  {
    std::unique_lock<std::mutex> lk(rel->mu);
    drained = cv_wait_for_ms(rel->cv, lk, kRelayDrainMs,
                             [&] { return rel->readers == 0; });
  }
  // Not drained (wedged reader past its send timeout): leave the slot
  // CREATED — owner-death repair reclaims it; freeing the span under
  // a live reader would corrupt its stream.
  if (drained) rts_abort(local_store, id);
  return -3;
}

}  // namespace

extern "C" {

// Abort a created-but-unsealed object (receiver-side failure path).
// rts_delete refuses SLOT_CREATED because a foreign writer may still
// be mid-write into the span — but the abort caller IS that writer,
// declaring its write over. Free the span when this process owns the
// creation (otherwise a failed pull leaks the slot until owner-death
// repair, and a retry would find the stale CREATED slot and
// misreport the object as a local duplicate).
int rts_abort(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s && s->state == SLOT_CREATED &&
      s->owner_pid == static_cast<int32_t>(getpid()) &&
      s->owner_start == OwnStartTime()) {
    FreeLocked(st, s->offset, s->alloc_size);
    s->state = SLOT_TOMBSTONE;
    h->num_objects--;
    pthread_mutex_unlock(&h->mu);
    return 0;
  }
  pthread_mutex_unlock(&h->mu);
  return rts_delete(handle, id);
}

// bind_all != 0 → 0.0.0.0 (real node-to-node topologies); 0 →
// loopback (same-host testing without exposing the arena).
void* rto_serve(const char* shm_name, uint64_t capacity, int port,
                int bind_all) {
  void* store = rts_connect(shm_name, capacity, 0);
  if (store == nullptr) return nullptr;

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(bind_all ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    rts_disconnect(store);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  TransferServer* ts = new TransferServer();
  ts->store = store;
  ts->arena = shm_name;
  ts->listen_fd = fd;
  ts->port = ntohs(addr.sin_port);
  ts->acceptor = std::thread([ts]() {
    for (;;) {
      int cfd = accept(ts->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (ts->stopping.load()) return;
        if (errno == EINTR) continue;
        return;
      }
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      set_socket_buffers(cfd);
      // Send timeout only: a wedged receiver must not pin a relay
      // reader (and through it the relay writer's drain wait) forever.
      // NO receive timeout — idle persistent connections legitimately
      // block in the op-header recv between requests.
      timeval stv{};
      stv.tv_sec = 30;
      setsockopt(cfd, SOL_SOCKET, SO_SNDTIMEO, &stv, sizeof(stv));
      {
        std::lock_guard<std::mutex> lock(ts->fd_mu);
        if (ts->stopping.load()) {
          close(cfd);
          continue;
        }
        ts->conn_fds.push_back(cfd);
      }
      ts->active_workers.fetch_add(1);
      std::thread([ts, cfd]() {
        serve_conn(ts, cfd);
        {
          std::lock_guard<std::mutex> lock(ts->fd_mu);
          auto& v = ts->conn_fds;
          v.erase(std::remove(v.begin(), v.end(), cfd), v.end());
        }
        ts->active_workers.fetch_sub(1);
      }).detach();
    }
  });
  return ts;
}

int rto_port(void* handle) {
  return reinterpret_cast<TransferServer*>(handle)->port;
}

void rto_stop(void* handle) {
  TransferServer* ts = reinterpret_cast<TransferServer*>(handle);
  ts->stopping.store(true);
  shutdown(ts->listen_fd, SHUT_RDWR);
  close(ts->listen_fd);
  if (ts->acceptor.joinable()) ts->acceptor.join();
  // Kick idle workers out of recv_all — an open-but-quiet client must
  // not wedge stop().
  {
    std::lock_guard<std::mutex> lock(ts->fd_mu);
    for (int fd : ts->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  while (ts->active_workers.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  rts_disconnect(ts->store);
  delete ts;
}

// Client-side persistent connection to a peer's transfer server.
void* rto_connect(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  set_socket_buffers(fd);
  return reinterpret_cast<void*>(static_cast<intptr_t>(fd) + 1);
}

void rto_close(void* conn) {
  close(static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1);
}

// Pull `id` from the peer into the local arena. Returns 0 on success,
// -1 remote miss, -2 local store full, -3 wire error, -4 local dup.
int rto_pull(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint8_t op = OP_PULL;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t size;
  if (!recv_all(fd, &size, 8)) return -3;
  if (size < 0) return -1;
  uint64_t off = 0;
  int rc = rts_create(local_store, id, size, &off);
  if (rc != 0) {
    // Duplicate (-1) or local store full: the server is already
    // streaming `size` bytes — drain them or the persistent
    // connection desyncs and every later request reads payload bytes
    // as headers.
    drain(fd, size);
    return rc == -1 ? -4 : -2;
  }
  if (!recv_all(fd, st->base + off, size)) {
    rts_abort(local_store, id);
    return -3;
  }
  rts_seal(local_store, id);
  return 0;
}

// Size of `id` on the peer without transferring it. >=0 size, -1 miss,
// -3 wire error.
int64_t rto_stat(void* conn, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  uint8_t op = OP_STAT;
  if (!send_all(fd, &op, 1) || !send_all(fd, id, kIdLen)) return -3;
  int64_t size;
  if (!recv_all(fd, &size, 8)) return -3;
  return size;
}

// Push a local object to the peer. Returns 0 ok, -1 local miss,
// -2 peer full, -3 wire error.
int rto_push(void* conn, void* local_store, const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  Store* st = reinterpret_cast<Store*>(local_store);
  uint64_t off = 0, size = 0;
  if (rts_get(local_store, id, &off, &size, 1) != 0) return -1;
  uint8_t op = OP_PUSH;
  bool ok = send_all(fd, &op, 1) && send_all(fd, id, kIdLen) &&
            send_all(fd, &size, 8) && send_all(fd, st->base + off, size);
  rts_release(local_store, id);
  if (!ok) return -3;
  uint8_t status = 0;
  if (!recv_all(fd, &status, 1)) return -3;
  return status == 0 ? 0 : -2;
}

// Chunk-framed pull (OP_PULL2): like rto_pull, but the peer may relay
// an object it is itself still receiving, and THIS side registers the
// in-flight object so its own server can relay it onward. `shm_name`
// names the receiving arena in the process-local relay registry.
int rto_pull2(void* conn, void* local_store, const char* shm_name,
              const uint8_t* id) {
  int fd = static_cast<int>(reinterpret_cast<intptr_t>(conn)) - 1;
  return pull2_into(fd, local_store, shm_name, id);
}

// Server-side transfer counters (observability: bytes served and how
// many pulls were answered from a mid-pull relay entry).
void rto_serve_stats(void* handle, uint64_t* bytes_out,
                     uint64_t* relay_served) {
  TransferServer* ts = reinterpret_cast<TransferServer*>(handle);
  if (bytes_out) *bytes_out = ts->bytes_out.load();
  if (relay_served) *relay_served = ts->relay_served.load();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Pull/Push manager — the transfer-plane POLICY layer.
//
// Reference capabilities (re-designed, not translated):
//   pull_manager.h:52  — fair queueing across requesters, a global
//                        in-flight byte budget, retry, cancellation,
//                        sender-death abort surfaced to the puller;
//   push_manager.h:30  — chunked push scheduling under the same
//                        in-flight budget.
//
// Architecture: N worker threads drain per-requester FIFO queues in
// round-robin order (one requester's thousand pulls cannot starve
// another's one). Before streaming, a worker learns the object's size
// (OP_STAT) and blocks until the global in-flight byte total fits the
// budget (an oversized object is admitted only alone, so it can never
// deadlock). Wire errors retry with a fresh connection; every socket
// carries SO_RCVTIMEO/SO_SNDTIMEO so a dead or wedged sender turns
// into a timeout, the partially-created local object is aborted
// (rts_abort inside rto_pull) and the final status is surfaced to the
// waiter. Concurrent requests for the same id coalesce onto one
// transfer (reference: PullManager object deduplication).
// ---------------------------------------------------------------------------

namespace {

struct Cand {
  std::string host;
  int port;
  std::string ep;  // "host:port"
};

struct PullOp {
  uint64_t requester;
  std::string host;
  int port;
  std::string ep;                   // CURRENT "host:port" bucket
  std::vector<Cand> cands;          // fallback sources, [0] = current
  std::string ckey;                 // by_id key (pulls; covers all eps)
  std::string src;                  // winning source after success
  uint8_t id[kIdLen];
  bool is_push;
  std::atomic<int> status{1};       // 1 = pending/running
  std::vector<uint64_t> tickets;    // all waiters coalesced onto this op
  bool queued = true;
};

struct PullMgr {
  void* store = nullptr;            // local arena (owned)
  std::string arena;                // shm name (relay registry key)
  uint64_t budget;
  uint64_t inflight = 0;
  // Per-source accounting: admitted in-flight bytes drive least-loaded
  // source selection (reference: PullManager's location-aware pull
  // scheduling); cumulative bytes feed the transfer metrics.
  std::unordered_map<std::string, uint64_t> ep_inflight;
  std::unordered_map<std::string, uint64_t> ep_bytes;
  uint64_t bytes_in = 0;            // total payload bytes pulled
  int timeout_ms;
  int retries;
  int ep_cap = 3;  // max workers on ONE endpoint: a dead peer's
                   // timeouts must not occupy every worker and stall
                   // pulls from healthy peers
  std::mutex mu;
  std::condition_variable work_cv;  // queue -> workers
  std::condition_variable done_cv;  // op completion -> waiters
  std::condition_variable budget_cv;
  std::map<uint64_t, std::deque<PullOp*>> queues;  // per requester
  uint64_t rr_key = 0;              // fair cursor (next requester >=)
  std::unordered_map<std::string, int> ep_active;
  std::unordered_map<std::string, PullOp*> by_id;  // coalesce (pulls,
                                                   // keyed id+endpoint)
  std::unordered_map<uint64_t, PullOp*> tickets;
  uint64_t next_ticket = 1;
  uint64_t queued_ops = 0, active_ops = 0;
  int wait_refs = 0;  // rtp_wait callers inside the manager — rtp_stop
                      // must not free the manager under them
  bool stopping = false;
  std::vector<std::thread> workers;
};

std::string coalesce_key(const uint8_t* id, const std::string& ep) {
  // Endpoint is part of the identity: a pull naming a HEALTHY source
  // must not coalesce onto (and inherit the failure of) an in-flight
  // pull of the same object from a dead one.
  return std::string(reinterpret_cast<const char*>(id), kIdLen) + "@" +
         ep;
}

void set_socket_timeouts(int fd, int timeout_ms) {
  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

// Per-worker endpoint->connection cache. Keyed by "host:port".
struct WorkerConns {
  std::unordered_map<std::string, void*> conns;

  void* get(const std::string& host, int port, int timeout_ms) {
    std::string key = host + ":" + std::to_string(port);
    auto it = conns.find(key);
    if (it != conns.end()) return it->second;
    void* c = rto_connect(host.c_str(), port);
    if (c != nullptr) {
      int fd = static_cast<int>(reinterpret_cast<intptr_t>(c)) - 1;
      set_socket_timeouts(fd, timeout_ms);
      conns[key] = c;
    }
    return c;
  }

  void drop(const std::string& host, int port) {
    std::string key = host + ":" + std::to_string(port);
    auto it = conns.find(key);
    if (it != conns.end()) {
      rto_close(it->second);
      conns.erase(it);
    }
  }

  void close_all() {
    for (auto& kv : conns) rto_close(kv.second);
    conns.clear();
  }
};

// Fair pick: round-robin over requester queues, skipping ops whose
// endpoint already has ep_cap workers on it. Returns nullptr when no
// eligible op exists (caller re-waits).
PullOp* next_op_locked(PullMgr* m) {
  if (m->queues.empty()) return nullptr;
  // Walk the ordered map in place starting at the round-robin cursor
  // (lower_bound + wrap) instead of materializing a key vector per
  // pick — the pick runs under m->mu on every worker dispatch.
  const size_t n = m->queues.size();
  auto it = m->queues.lower_bound(m->rr_key);
  for (size_t k = 0; k < n; ++k, ++it) {
    if (it == m->queues.end()) it = m->queues.begin();
    if (it->second.empty()) continue;
    PullOp* op = it->second.front();
    // Least-loaded source selection: among the op's candidate
    // endpoints under the per-endpoint worker cap, pick the one with
    // the fewest admitted in-flight bytes (ties: fewer active workers,
    // then the submitter's preference order — for a relay chain that
    // is the assigned parent). Skip the op only when EVERY candidate
    // is saturated. find(), not operator[]: a saturation probe must
    // not plant permanent zero-count entries for endpoints it skips.
    int best = -1;
    uint64_t best_load = 0;
    int best_active = 0;
    for (size_t ci = 0; ci < op->cands.size(); ci++) {
      const std::string& ep = op->cands[ci].ep;
      auto ea = m->ep_active.find(ep);
      int act = ea == m->ep_active.end() ? 0 : ea->second;
      if (act >= m->ep_cap) continue;
      auto ei = m->ep_inflight.find(ep);
      uint64_t load = ei == m->ep_inflight.end() ? 0 : ei->second;
      if (best < 0 || load < best_load ||
          (load == best_load && act < best_active)) {
        best = static_cast<int>(ci);
        best_load = load;
        best_active = act;
      }
    }
    if (best < 0) continue;
    if (best != 0)
      std::swap(op->cands[0], op->cands[static_cast<size_t>(best)]);
    op->host = op->cands[0].host;
    op->port = op->cands[0].port;
    op->ep = op->cands[0].ep;
    it->second.pop_front();
    uint64_t key = it->first;
    if (it->second.empty()) m->queues.erase(it);
    m->rr_key = key + 1;
    m->ep_active[op->ep]++;
    return op;
  }
  return nullptr;
}

void finish_op_locked(PullMgr* m, PullOp* op, int status) {
  op->status.store(status);
  if (!op->is_push) {
    m->by_id.erase(op->ckey);
  }
  auto ea = m->ep_active.find(op->ep);
  if (ea != m->ep_active.end() && --ea->second <= 0)
    m->ep_active.erase(ea);
  m->active_ops--;
  m->done_cv.notify_all();
  m->work_cv.notify_all();  // endpoint slot freed — re-run the pick
  if (op->tickets.empty()) {
    // Every waiter cancelled (rtp_cancel) while the op ran: nobody
    // will ever rtp_wait it — free it now or it leaks for the
    // manager's lifetime.
    delete op;
  }
}

// Shared submit path: `cands` is the fallback-ordered source list
// (one entry = the classic single-source submit). Pulls coalesce on
// id + the full candidate list — a pull naming a DIFFERENT source set
// must not inherit another submit's failure, but identical broadcasts
// share one transfer (reference: PullManager object deduplication).
uint64_t submit_locked(PullMgr* m, uint64_t requester,
                       std::vector<Cand> cands, const uint8_t* id,
                       int is_push) {
  uint64_t t = m->next_ticket++;
  std::string ckey;
  if (!is_push) {
    std::string joined;
    for (const Cand& c : cands) {
      if (!joined.empty()) joined += ",";
      joined += c.ep;
    }
    ckey = coalesce_key(id, joined);
    auto it = m->by_id.find(ckey);
    if (it != m->by_id.end()) {
      it->second->tickets.push_back(t);
      m->tickets[t] = it->second;
      return t;
    }
  }
  PullOp* op = new PullOp();
  op->requester = requester;
  op->cands = std::move(cands);
  op->host = op->cands[0].host;
  op->port = op->cands[0].port;
  op->ep = op->cands[0].ep;
  op->ckey = ckey;
  memcpy(op->id, id, kIdLen);
  op->is_push = is_push != 0;
  op->tickets.push_back(t);
  if (!is_push) m->by_id[ckey] = op;
  m->tickets[t] = op;
  m->queues[requester].push_back(op);
  m->queued_ops++;
  m->work_cv.notify_one();
  return t;
}

void release_ep_inflight_locked(PullMgr* m, const std::string& ep,
                                uint64_t n) {
  auto it = m->ep_inflight.find(ep);
  if (it == m->ep_inflight.end()) return;
  it->second = it->second > n ? it->second - n : 0;
  if (it->second == 0) m->ep_inflight.erase(it);
}

// Move the op's active-worker slot to the next fallback candidate.
void switch_ep_locked(PullMgr* m, PullOp* op, const Cand& c) {
  auto ea = m->ep_active.find(op->ep);
  if (ea != m->ep_active.end() && --ea->second <= 0)
    m->ep_active.erase(ea);
  op->host = c.host;
  op->port = c.port;
  op->ep = c.ep;
  m->ep_active[op->ep]++;
  m->work_cv.notify_all();  // old endpoint's slot freed
}

void pull_worker(PullMgr* m) {
  WorkerConns conns;
  for (;;) {
    PullOp* op;
    {
      std::unique_lock<std::mutex> lk(m->mu);
      // wait_for (not wait): with work queued but every op's endpoint
      // saturated, the predicate is true yet nothing is runnable — the
      // timeout turns that state into a cheap poll; completions also
      // notify, so pickup is normally immediate.
      cv_wait_for_ms(m->work_cv, lk, 50, [m] {
        return m->stopping || m->queued_ops > 0;
      });
      if (m->stopping) break;
      op = next_op_locked(m);
      if (op == nullptr) continue;
      m->queued_ops--;
      m->active_ops++;
      op->queued = false;
    }

    int rc = -3;
    int64_t got_size = 0;
    uint64_t admitted = 0;
    std::string admitted_ep;
    std::string local_hit;  // op->src is written under m->mu at finish
    // Candidate fallback: run the retry loop against the selected
    // source; on a miss or exhausted wire retries, move to the next
    // registered location (a broadcast chain survives a dead or
    // already-evicted relay parent by falling back toward the
    // producer). next_op_locked put the least-loaded candidate first.
    for (size_t ci = 0; ci < op->cands.size(); ci++) {
      if (ci > 0) {
        std::lock_guard<std::mutex> lk(m->mu);
        switch_ep_locked(m, op, op->cands[ci]);
      }
      rc = -3;
      for (int attempt = 0; attempt <= m->retries; attempt++) {
        // Local-presence FIRST: an object already in the local arena
        // must succeed even when its source peer is dead (no connect).
        if (!op->is_push && rts_contains(m->store, op->id)) {
          rc = 0;
          local_hit = "local";
          break;
        }
        void* conn = conns.get(op->host, op->port, m->timeout_ms);
        if (conn == nullptr) {
          rc = -3;
          continue;  // connect refused/timed out — retry
        }
        int64_t size;
        if (op->is_push) {
          uint64_t off = 0, sz = 0;
          if (rts_get(m->store, op->id, &off, &sz, 0) != 0) {
            rc = -1;
            break;  // local miss: nothing to push, no retry will help
          }
          size = static_cast<int64_t>(sz);
        } else {
          size = rto_stat(conn, op->id);
          if (size == -1) {
            rc = -1;
            break;  // miss at THIS source — fall back to the next one
          }
          if (size < 0) {
            conns.drop(op->host, op->port);
            rc = -3;
            continue;
          }
        }
        {
          std::unique_lock<std::mutex> lk(m->mu);
          uint64_t need = static_cast<uint64_t>(size);
          m->budget_cv.wait(lk, [m, need] {
            return m->stopping || m->inflight + need <= m->budget ||
                   m->inflight == 0;  // oversized: admit alone
          });
          if (m->stopping) {
            rc = -6;
            break;
          }
          m->inflight += need;
          m->ep_inflight[op->ep] += need;
          admitted = need;
          admitted_ep = op->ep;
        }
        rc = op->is_push
                 ? rto_push(conn, m->store, op->id)
                 : pull2_into(static_cast<int>(
                                  reinterpret_cast<intptr_t>(conn)) -
                                  1,
                              m->store, m->arena, op->id);
        got_size = size;
        {
          std::lock_guard<std::mutex> lk(m->mu);
          m->inflight -= admitted;
          release_ep_inflight_locked(m, admitted_ep, admitted);
          admitted = 0;
          m->budget_cv.notify_all();
        }
        if (rc == -4) rc = 0;  // already present locally = success
        if (rc != -3) break;   // success or non-wire error: done
        // Wire error (sender died / timed out mid-transfer): the
        // partial local object was aborted inside pull2_into;
        // reconnect and retry.
        conns.drop(op->host, op->port);
      }
      // -2 (local store full) and -6 (stopping) won't improve at
      // another source; pushes are single-candidate.
      if (rc == 0 || rc == -2 || rc == -6 || op->is_push) break;
    }
    if (admitted) {
      std::lock_guard<std::mutex> lk(m->mu);
      m->inflight -= admitted;
      release_ep_inflight_locked(m, admitted_ep, admitted);
      m->budget_cv.notify_all();
    }
    {
      std::lock_guard<std::mutex> lk(m->mu);
      if (rc == 0 && !op->is_push) {
        if (local_hit.empty()) {
          op->src = op->ep;
          m->ep_bytes[op->ep] += static_cast<uint64_t>(got_size);
          m->bytes_in += static_cast<uint64_t>(got_size);
        } else {
          op->src = local_hit;
        }
      }
      finish_op_locked(m, op, rc);
    }
  }
  conns.close_all();
}

}  // namespace

extern "C" {

// budget_bytes: global in-flight byte cap (0 = half the arena — tied
// to the receiving arena's capacity so concurrent pulls cannot blow it
// out). timeout_ms guards every socket op; retries = extra attempts
// after a wire error.
void* rtp_start(const char* shm_name, uint64_t budget_bytes,
                int nworkers, int timeout_ms, int retries) {
  void* store = rts_connect(shm_name, 0, 0);
  if (store == nullptr) return nullptr;
  PullMgr* m = new PullMgr();
  m->store = store;
  m->arena = shm_name;
  m->budget = budget_bytes ? budget_bytes : rts_capacity(store) / 2;
  m->timeout_ms = timeout_ms > 0 ? timeout_ms : 30000;
  m->retries = retries >= 0 ? retries : 2;
  if (nworkers <= 0) nworkers = 4;
  // Leave at least one worker free of any single endpoint so a dead
  // peer's socket timeouts cannot stall pulls from healthy peers.
  m->ep_cap = nworkers > 1 ? nworkers - 1 : 1;
  for (int i = 0; i < nworkers; i++) {
    m->workers.emplace_back(pull_worker, m);
  }
  return m;
}

// Enqueue a pull (is_push=0) of `id` from host:port into the local
// arena, or a push (is_push=1) of local `id` to host:port. `requester`
// is the fairness key (per consumer). Returns a ticket for rtp_wait.
uint64_t rtp_submit(void* handle, uint64_t requester, const char* host,
                    int port, const uint8_t* id, int is_push) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::vector<Cand> cands;
  cands.push_back(
      {host, port, std::string(host) + ":" + std::to_string(port)});
  std::lock_guard<std::mutex> lk(m->mu);
  return submit_locked(m, requester, std::move(cands), id, is_push);
}

// Multi-source pull: `endpoints` is a comma-separated,
// fallback-ordered "host:port,host:port,..." list of registered
// locations (a relay parent first, the producer last). The manager
// picks the least-loaded source at dispatch and falls back through
// the rest on miss or wire failure. Returns 0 on a malformed or
// empty endpoint list, else a ticket for rtp_wait / rtp_wait_src.
uint64_t rtp_submit_multi(void* handle, uint64_t requester,
                          const char* endpoints, const uint8_t* id) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::vector<Cand> cands;
  std::string s = endpoints ? endpoints : "";
  size_t pos = 0;
  while (pos <= s.size()) {
    size_t comma = s.find(',', pos);
    std::string ep = s.substr(
        pos, comma == std::string::npos ? std::string::npos
                                        : comma - pos);
    size_t colon = ep.rfind(':');
    if (!ep.empty() && colon != std::string::npos && colon > 0) {
      int port = atoi(ep.c_str() + colon + 1);
      if (port > 0 && port < 65536)
        cands.push_back({ep.substr(0, colon), port, ep});
    }
    if (comma == std::string::npos) break;
    pos = comma + 1;
  }
  if (cands.empty()) return 0;
  std::lock_guard<std::mutex> lk(m->mu);
  return submit_locked(m, requester, std::move(cands), id, 0);
}

// Block until the ticket's transfer completes (or timeout_ms passes).
// Returns the transfer status (0 ok, -1 miss, -2 store full, -3 wire
// error after retries, -6 manager stopping) or -5 on wait timeout.
// A completed ticket is consumed; the op is freed with its last ticket.
static int rtp_wait_impl(PullMgr* m, uint64_t ticket, int timeout_ms,
                         char* src, int src_cap) {
  std::unique_lock<std::mutex> lk(m->mu);
  auto it = m->tickets.find(ticket);
  if (it == m->tickets.end()) return -7;  // unknown/already consumed
  PullOp* op = it->second;
  m->wait_refs++;
  auto pred = [m, op] {
    return m->stopping || op->status.load() != 1;
  };
  bool timed_out = false;
  if (timeout_ms < 0) {
    m->done_cv.wait(lk, pred);
  } else if (!cv_wait_for_ms(m->done_cv, lk, timeout_ms, pred)) {
    timed_out = true;
  }
  m->wait_refs--;
  m->done_cv.notify_all();  // rtp_stop waits on wait_refs == 0
  if (timed_out) return -5;
  int st = op->status.load();
  if (st == 1) st = -6;  // woken by stop while still pending
  if (src != nullptr && src_cap > 0) {
    // Winning source endpoint ("host:port", or "local" when the
    // object was already in the arena) — written by the worker under
    // m->mu before the status flipped, so this read is ordered.
    size_t n = std::min(op->src.size(),
                        static_cast<size_t>(src_cap - 1));
    memcpy(src, op->src.data(), n);
    src[n] = '\0';
  }
  m->tickets.erase(ticket);
  auto& tk = op->tickets;
  tk.erase(std::remove(tk.begin(), tk.end(), ticket), tk.end());
  if (tk.empty()) delete op;
  return st;
}

int rtp_wait(void* handle, uint64_t ticket, int timeout_ms) {
  return rtp_wait_impl(reinterpret_cast<PullMgr*>(handle), ticket,
                       timeout_ms, nullptr, 0);
}

// rtp_wait + the winning source endpoint (for the directory's
// pull_complete report and per-source pull counting).
int rtp_wait_src(void* handle, uint64_t ticket, int timeout_ms,
                 char* src, int src_cap) {
  return rtp_wait_impl(reinterpret_cast<PullMgr*>(handle), ticket,
                       timeout_ms, src, src_cap);
}

// Abandon a ticket (e.g. after a wait timeout the caller will not
// retry). The underlying transfer keeps running — other coalesced
// waiters still get it — but this ticket's registration is dropped so
// an abandoned op cannot accumulate for the manager's lifetime
// (review r5: each timed-out wait leaked its op + ticket entry).
void rtp_cancel(void* handle, uint64_t ticket) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::lock_guard<std::mutex> lk(m->mu);
  auto it = m->tickets.find(ticket);
  if (it == m->tickets.end()) return;
  PullOp* op = it->second;
  m->tickets.erase(it);
  auto& tk = op->tickets;
  tk.erase(std::remove(tk.begin(), tk.end(), ticket), tk.end());
  // Completed op with no waiters left: free now. A still-pending/
  // running op stays — the worker's finish_op_locked frees it when it
  // completes with no tickets (queued ops keep running: a coalesced
  // submit may still attach before completion).
  if (tk.empty() && op->status.load() != 1) delete op;
}

void rtp_stats(void* handle, uint64_t* inflight_bytes,
               uint64_t* queued, uint64_t* active) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::lock_guard<std::mutex> lk(m->mu);
  if (inflight_bytes) *inflight_bytes = m->inflight;
  if (queued) *queued = m->queued_ops;
  if (active) *active = m->active_ops;
}

// Per-source transfer stats as text, one line per source:
//   "total <bytes_in>\n" then "<ep> <inflight> <active> <bytes>\n".
// Returns the full length needed (snprintf-style; the caller retries
// with a bigger buffer if the return >= cap).
int rtp_ep_stats(void* handle, char* buf, int cap) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  std::string out;
  {
    std::lock_guard<std::mutex> lk(m->mu);
    out = "total " + std::to_string(m->bytes_in) + "\n";
    // Union of the maps: a source with historical bytes but nothing
    // in flight still reports (the bench's per-source pull spread).
    std::map<std::string, int> eps;
    for (const auto& kv : m->ep_bytes) eps[kv.first] = 1;
    for (const auto& kv : m->ep_inflight) eps[kv.first] = 1;
    for (const auto& kv : m->ep_active) eps[kv.first] = 1;
    for (const auto& kv : eps) {
      auto fi = m->ep_inflight.find(kv.first);
      auto fa = m->ep_active.find(kv.first);
      auto fb = m->ep_bytes.find(kv.first);
      out += kv.first + " " +
             std::to_string(
                 fi == m->ep_inflight.end() ? 0 : fi->second) +
             " " +
             std::to_string(
                 fa == m->ep_active.end() ? 0 : fa->second) +
             " " +
             std::to_string(
                 fb == m->ep_bytes.end() ? 0 : fb->second) +
             "\n";
    }
  }
  if (buf != nullptr && cap > 0) {
    size_t n = std::min(out.size(), static_cast<size_t>(cap - 1));
    memcpy(buf, out.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int>(out.size());
}

void rtp_stop(void* handle) {
  PullMgr* m = reinterpret_cast<PullMgr*>(handle);
  {
    std::lock_guard<std::mutex> lk(m->mu);
    m->stopping = true;
    m->work_cv.notify_all();
    m->budget_cv.notify_all();
  }
  for (auto& w : m->workers) w.join();
  {
    std::unique_lock<std::mutex> lk(m->mu);
    // Fail every queued (never-started) op so waiters unblock; a
    // queued op whose waiters all cancelled has no owner left — free
    // it here (it is not in the tickets map the sweep below walks).
    for (auto& kv : m->queues) {
      for (PullOp* op : kv.second) {
        if (op->tickets.empty()) {
          delete op;
        } else {
          op->status.store(-6);
        }
      }
    }
    m->queues.clear();
    m->done_cv.notify_all();
    // Blocked rtp_wait callers woke on `stopping`; let them leave the
    // manager before it is freed.
    m->done_cv.wait(lk, [m] { return m->wait_refs == 0; });
    // Free every op still registered (never-waited tickets included —
    // after stop there is nothing left to wait on). Ops appear under
    // one ticket per waiter; delete each once.
    std::vector<PullOp*> unique_ops;
    for (auto& kv : m->tickets) {
      if (std::find(unique_ops.begin(), unique_ops.end(), kv.second) ==
          unique_ops.end())
        unique_ops.push_back(kv.second);
    }
    m->tickets.clear();
    for (PullOp* op : unique_ops) delete op;
  }
  rts_disconnect(m->store);
  delete m;
}

}  // extern "C"
