// Sanitizer stress harness for the control-plane daemon (reference:
// the C++ core's ASAN CI over gcs_server tests, SURVEY.md §4.2).
//
// The daemon is a single-threaded epoll loop (no data races by
// construction — TSAN is moot), so the valuable coverage is ASAN over
// the FRAME PARSER and connection lifecycle under hostile concurrent
// load. This harness fork/execs the SANITIZED daemon binary (path in
// argv[1]), then hammers it from N client threads:
//   - valid traffic: KV put/get/del/keys, subscribe/publish,
//     register_node/heartbeat/list_nodes;
//   - hostile traffic: garbage frames, truncated frames, oversized
//     length prefixes, RST mid-frame.
// Afterwards it verifies the daemon still answers PING, SIGTERMs it,
// and requires death-by-SIGTERM (an ASAN abort exits differently).

#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>

#include <string>

namespace {

int g_port = 0;

bool write_all(int fd, const void* p, size_t n) {
  const char* c = static_cast<const char*>(p);
  while (n > 0) {
    ssize_t w = send(fd, c, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    c += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool read_all(int fd, void* p, size_t n) {
  char* c = static_cast<char*>(p);
  while (n > 0) {
    ssize_t r = recv(fd, c, n, 0);
    if (r <= 0) return false;
    c += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

int dial() {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in a{};
  a.sin_family = AF_INET;
  a.sin_port = htons(static_cast<uint16_t>(g_port));
  inet_pton(AF_INET, "127.0.0.1", &a.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void put_str(std::string& out, const std::string& s) {
  uint32_t n = static_cast<uint32_t>(s.size());
  out.append(reinterpret_cast<const char*>(&n), 4);
  out.append(s);
}

// One request; returns response body (after status byte position 0) or
// empty on error. Skips pubsub pushes.
bool request(int fd, uint64_t req_id, uint8_t op,
             const std::string& args, std::string* body) {
  std::string p;
  p.push_back(0);
  p.append(reinterpret_cast<const char*>(&req_id), 8);
  p.push_back(static_cast<char>(op));
  p.append(args);
  uint32_t len = static_cast<uint32_t>(p.size());
  if (!write_all(fd, &len, 4) || !write_all(fd, p.data(), p.size()))
    return false;
  for (;;) {
    uint32_t rlen;
    if (!read_all(fd, &rlen, 4) || rlen < 1 || rlen > (64u << 20))
      return false;
    std::string frame(rlen, '\0');
    if (!read_all(fd, frame.data(), rlen)) return false;
    if (frame[0] != 0) continue;  // pubsub push
    if (rlen < 9) return false;
    if (body != nullptr) body->assign(frame, 9, std::string::npos);
    return true;
  }
}

void* valid_client(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  int fd = dial();
  if (fd < 0) abort();
  unsigned seed = static_cast<unsigned>(tid) * 65521 + 11;
  uint64_t req = 1;
  char node_id[32];
  snprintf(node_id, sizeof(node_id), "stress-node-%ld", tid);
  {
    std::string args;
    put_str(args, node_id);
    put_str(args, "{}");
    if (!request(fd, req++, 20 /*REGISTER_NODE*/, args, nullptr))
      abort();
  }
  {
    std::string args;
    put_str(args, "stress-chan");
    if (!request(fd, req++, 10 /*SUBSCRIBE*/, args, nullptr)) abort();
  }
  for (int i = 0; i < 400; i++) {
    int op = rand_r(&seed) % 6;
    std::string args, body;
    char key[48];
    snprintf(key, sizeof(key), "k-%ld-%d", tid, rand_r(&seed) % 32);
    bool ok = true;
    if (op == 0) {
      put_str(args, key);
      put_str(args, std::string(1 + rand_r(&seed) % 900, 'v'));
      args.push_back(1);
      ok = request(fd, req++, 1 /*KV_PUT*/, args, nullptr);
    } else if (op == 1) {
      put_str(args, key);
      ok = request(fd, req++, 2 /*KV_GET*/, args, &body);
    } else if (op == 2) {
      put_str(args, key);
      ok = request(fd, req++, 3 /*KV_DEL*/, args, nullptr);
    } else if (op == 3) {
      put_str(args, node_id);
      ok = request(fd, req++, 21 /*HEARTBEAT*/, args, nullptr);
    } else if (op == 4) {
      put_str(args, "stress-chan");
      put_str(args, "payload");
      ok = request(fd, req++, 12 /*PUBLISH*/, args, nullptr);
    } else {
      ok = request(fd, req++, 22 /*LIST_NODES*/, args, &body);
    }
    if (!ok) abort();
  }
  close(fd);
  return nullptr;
}

void* hostile_client(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  unsigned seed = static_cast<unsigned>(tid) * 2 + 999;
  for (int i = 0; i < 80; i++) {
    int fd = dial();
    if (fd < 0) continue;
    int mode = rand_r(&seed) % 4;
    if (mode == 0) {
      // Random garbage (random "length" + junk).
      char junk[128];
      for (size_t j = 0; j < sizeof(junk); j++)
        junk[j] = static_cast<char>(rand_r(&seed));
      write_all(fd, junk, sizeof(junk));
    } else if (mode == 1) {
      // Oversized length prefix — server must reject, not allocate.
      uint32_t len = 0x7fffffff;
      write_all(fd, &len, 4);
    } else if (mode == 2) {
      // Truncated valid-looking frame, then RST.
      uint32_t len = 64;
      write_all(fd, &len, 4);
      char half[10] = {0};
      write_all(fd, half, sizeof(half));
      struct linger lg {1, 0};
      setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
    } else {
      // Frame whose inner strings overrun the frame (parser bounds).
      std::string p;
      p.push_back(0);
      uint64_t rid = 7;
      p.append(reinterpret_cast<const char*>(&rid), 8);
      p.push_back(1);  // KV_PUT
      uint32_t huge = 0x00ffffff;
      p.append(reinterpret_cast<const char*>(&huge), 4);  // key len lie
      p.append("short");
      uint32_t len = static_cast<uint32_t>(p.size());
      write_all(fd, &len, 4);
      write_all(fd, p.data(), p.size());
      char resp[4];
      recv(fd, resp, sizeof(resp), MSG_DONTWAIT);
    }
    close(fd);
  }
  return nullptr;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <control_plane_binary>\n", argv[0]);
    return 2;
  }
  int outpipe[2];
  if (pipe(outpipe) != 0) return 2;
  pid_t child = fork();
  if (child == 0) {
    dup2(outpipe[1], 1);
    close(outpipe[0]);
    close(outpipe[1]);
    execl(argv[1], argv[1], "--port", "0", "--health-timeout-ms",
          "2000", static_cast<char*>(nullptr));
    _exit(127);
  }
  close(outpipe[1]);
  {
    char line[128] = {0};
    size_t got = 0;
    while (got < sizeof(line) - 1) {
      ssize_t r = read(outpipe[0], line + got, 1);
      if (r <= 0 || line[got] == '\n') break;
      got++;
    }
    if (sscanf(line, "PORT=%d", &g_port) != 1 || g_port <= 0) {
      fprintf(stderr, "no PORT= from daemon: '%s'\n", line);
      kill(child, SIGKILL);
      return 1;
    }
  }

  pthread_t threads[6];
  for (long t = 0; t < 4; t++)
    pthread_create(&threads[t], nullptr, valid_client,
                   reinterpret_cast<void*>(t));
  for (long t = 4; t < 6; t++)
    pthread_create(&threads[t], nullptr, hostile_client,
                   reinterpret_cast<void*>(t));
  for (int t = 0; t < 6; t++) pthread_join(threads[t], nullptr);

  // Daemon must still be alive and answering.
  int fd = dial();
  if (fd < 0) {
    fprintf(stderr, "daemon unreachable after stress\n");
    kill(child, SIGKILL);
    return 1;
  }
  std::string body;
  if (!request(fd, 1, 0 /*PING*/, "", &body)) {
    fprintf(stderr, "daemon not answering PING after stress\n");
    kill(child, SIGKILL);
    return 1;
  }
  close(fd);

  kill(child, SIGTERM);
  int status = 0;
  waitpid(child, &status, 0);
  // Clean SIGTERM death (no handler installed) — an ASAN abort or
  // nonzero exit is a failure.
  if (!(WIFSIGNALED(status) && WTERMSIG(status) == SIGTERM) &&
      !(WIFEXITED(status) && WEXITSTATUS(status) == 0)) {
    fprintf(stderr, "daemon died badly: status=%d\n", status);
    return 1;
  }
  printf("OK control-plane stress\n");
  return 0;
}
