// Control-plane daemon — the GCS-equivalent native service.
//
// Capability-equivalent of the reference's GCS server
// (reference: src/ray/gcs/gcs_server/ — GcsKvManager/StoreClientKV,
// InternalPubSub, GcsNodeManager + GcsHealthCheckManager,
// GcsActorManager's actor table, GcsJobManager), re-designed for this
// runtime: one single-threaded epoll event loop (the reference's
// instrumented_io_context analog, with the same per-handler latency
// accounting as common/event_stats.cc) serving a length-prefixed binary
// protocol over TCP. No locks — all state is owned by the loop thread.
//
// Frame:    [u32 len][u8 type][body]     type 0 = request/response,
//                                        type 1 = pubsub push
// Request:  [u64 req_id][u8 op][args...]
// Response: [u64 req_id][u8 status][result...]   status 0 = OK
// Push:     [str channel][bytes payload]
// Strings/bytes are u32-length-prefixed; integers little-endian.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <chrono>
#include <deque>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Ops
// ---------------------------------------------------------------------------
enum Op : uint8_t {
  OP_PING = 0,
  OP_SNAPSHOT = 60,  // persist state tables to --persist file
  OP_KV_PUT = 1,
  OP_KV_GET = 2,
  OP_KV_DEL = 3,
  OP_KV_KEYS = 4,
  OP_KV_EXISTS = 5,
  OP_SUBSCRIBE = 10,
  OP_UNSUBSCRIBE = 11,
  OP_PUBLISH = 12,
  OP_REGISTER_NODE = 20,
  OP_HEARTBEAT = 21,
  OP_LIST_NODES = 22,
  OP_DRAIN_NODE = 23,
  OP_REGISTER_ACTOR = 30,
  OP_UPDATE_ACTOR = 31,
  OP_GET_ACTOR = 32,
  OP_LIST_ACTORS = 33,
  OP_GET_NAMED_ACTOR = 34,
  OP_ADD_JOB = 40,
  OP_LIST_JOBS = 41,
  OP_STATS = 50,
};

enum Status : uint8_t {
  ST_OK = 0,
  ST_NOT_FOUND = 1,
  ST_EXISTS = 2,
  ST_BAD_REQUEST = 3,
};

uint64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Wire helpers
// ---------------------------------------------------------------------------
struct Reader {
  const uint8_t* p;
  size_t left;
  bool ok = true;

  Reader(const uint8_t* data, size_t n) : p(data), left(n) {}

  uint8_t u8() {
    if (left < 1) { ok = false; return 0; }
    uint8_t v = *p; p += 1; left -= 1; return v;
  }
  uint32_t u32() {
    if (left < 4) { ok = false; return 0; }
    uint32_t v; memcpy(&v, p, 4); p += 4; left -= 4; return v;
  }
  uint64_t u64() {
    if (left < 8) { ok = false; return 0; }
    uint64_t v; memcpy(&v, p, 8); p += 8; left -= 8; return v;
  }
  std::string str() {
    uint32_t n = u32();
    if (!ok || left < n) { ok = false; return {}; }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n; left -= n;
    return s;
  }
};

struct Writer {
  std::vector<uint8_t> buf;

  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) {
    size_t n = buf.size(); buf.resize(n + 4); memcpy(&buf[n], &v, 4);
  }
  void u64(uint64_t v) {
    size_t n = buf.size(); buf.resize(n + 8); memcpy(&buf[n], &v, 8);
  }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    buf.insert(buf.end(), s.begin(), s.end());
  }
};

// ---------------------------------------------------------------------------
// Server state
// ---------------------------------------------------------------------------
struct Conn {
  int fd = -1;
  std::vector<uint8_t> inbuf;
  std::deque<std::vector<uint8_t>> outq;  // framed, pending write
  size_t out_off = 0;                     // offset into outq.front()
  std::set<std::string> subs;
};

struct NodeInfo {
  std::string meta;
  // Latest load report piggybacked on a heartbeat (resource-view sync:
  // the capability of the reference's ray_syncer.h — every scheduler
  // reads the merged per-node load from here instead of gossiping
  // raylet-to-raylet).
  std::string load;
  uint64_t last_heartbeat_ms = 0;
  bool alive = true;
  bool draining = false;
};

struct ActorInfo {
  std::string name;
  std::string state;  // PENDING/ALIVE/RESTARTING/DEAD (free-form)
  std::string meta;
};

struct OpStat {
  uint64_t count = 0;
  uint64_t total_us = 0;
};

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  std::unordered_map<int, Conn> conns;
  // State tables (reference: gcs_table_storage.h typed tables).
  std::map<std::string, std::string> kv;
  std::unordered_map<std::string, std::set<int>> channels;  // chan -> fds
  std::map<std::string, NodeInfo> nodes;
  std::map<std::string, ActorInfo> actors;
  std::unordered_map<std::string, std::string> named_actors;
  std::map<std::string, std::string> jobs;
  std::map<uint8_t, OpStat> stats;   // per-op event stats
  uint64_t health_timeout_ms = 5000;
  std::string persist_path;          // "" = no persistence
  bool dirty = false;                // state changed since last snapshot
  uint64_t last_snapshot_ms = 0;     // snapshot throttle
  // External-store mirroring (reference: store_client/redis_store_client.h
  // — GCS state lives in an external store so a FRESH control plane on
  // any host can take over after total host loss). The external store
  // is another control-plane daemon used in KV-only mode; the full
  // state snapshot is written through to one KV key, throttled.
  std::string mirror_host;
  int mirror_port = 0;
  int mirror_fd = -1;
  uint64_t mirror_interval_ms = 200;
  uint64_t last_mirror_ms = 0;
  uint64_t mirror_req_id = 1;
  bool mirror_dirty = true;  // push once at boot (baseline the store)
};

void mark_dirty(Server& s) {
  s.dirty = true;
  s.mirror_dirty = true;
}

// ---------------------------------------------------------------------------
// Persistence (reference: gcs persistence via store_client/ — Redis or
// in-memory; on restart gcs_init_data.cc reloads the tables. Here the
// durable backend is a length-prefixed snapshot file, rewritten
// atomically on a timer whenever state changed.)
// ---------------------------------------------------------------------------

void put_str(std::string& out, const std::string& s) {
  uint32_t n = static_cast<uint32_t>(s.size());
  out.append(reinterpret_cast<const char*>(&n), 4);
  out.append(s);
}

bool get_str(const std::string& in, size_t& off, std::string& s) {
  if (off + 4 > in.size()) return false;
  uint32_t n;
  memcpy(&n, in.data() + off, 4);
  off += 4;
  if (off + n > in.size()) return false;
  s.assign(in, off, n);
  off += n;
  return true;
}

std::string serialize_state(Server& s) {
  std::string out = "RTCP1";
  uint32_t n = static_cast<uint32_t>(s.kv.size());
  out.append(reinterpret_cast<const char*>(&n), 4);
  for (const auto& [k, v] : s.kv) { put_str(out, k); put_str(out, v); }
  n = static_cast<uint32_t>(s.actors.size());
  out.append(reinterpret_cast<const char*>(&n), 4);
  for (const auto& [aid, a] : s.actors) {
    put_str(out, aid);
    put_str(out, a.name);
    put_str(out, a.state);
    put_str(out, a.meta);
  }
  n = static_cast<uint32_t>(s.jobs.size());
  out.append(reinterpret_cast<const char*>(&n), 4);
  for (const auto& [j, m] : s.jobs) { put_str(out, j); put_str(out, m); }
  return out;
}

void snapshot_state(Server& s) {
  if (s.persist_path.empty()) return;
  std::string out = serialize_state(s);

  std::string tmp = s.persist_path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (f == nullptr) return;
  // A failed/short write must NOT clobber the last good snapshot.
  size_t wrote = fwrite(out.data(), 1, out.size(), f);
  bool ok = wrote == out.size();
  if (ok) ok = fflush(f) == 0 && fsync(fileno(f)) == 0;
  ok = (fclose(f) == 0) && ok;
  if (!ok) {
    remove(tmp.c_str());
    return;  // stay dirty; retry on the next tick
  }
  rename(tmp.c_str(), s.persist_path.c_str());
  s.dirty = false;
  s.last_snapshot_ms = now_ms();
}

void deserialize_state(Server& s, const std::string& in);

void restore_state(Server& s) {
  if (s.persist_path.empty()) return;
  FILE* f = fopen(s.persist_path.c_str(), "rb");
  if (f == nullptr) return;
  std::string in;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) in.append(buf, n);
  fclose(f);
  deserialize_state(s, in);
}

void deserialize_state(Server& s, const std::string& in) {
  if (in.compare(0, 5, "RTCP1") != 0) return;
  size_t off = 5;
  auto read_count = [&](uint32_t& c) {
    if (off + 4 > in.size()) return false;
    memcpy(&c, in.data() + off, 4);
    off += 4;
    return true;
  };
  uint32_t count;
  if (!read_count(count)) return;
  for (uint32_t i = 0; i < count; i++) {
    std::string k, v;
    if (!get_str(in, off, k) || !get_str(in, off, v)) return;
    s.kv[k] = v;
  }
  if (!read_count(count)) return;
  for (uint32_t i = 0; i < count; i++) {
    std::string aid, name, state, meta;
    if (!get_str(in, off, aid) || !get_str(in, off, name) ||
        !get_str(in, off, state) || !get_str(in, off, meta))
      return;
    ActorInfo& a = s.actors[aid];
    a.name = name;
    a.state = state;
    a.meta = meta;
    if (!name.empty() && state != "DEAD") s.named_actors[name] = aid;
  }
  if (!read_count(count)) return;
  for (uint32_t i = 0; i < count; i++) {
    std::string j, m;
    if (!get_str(in, off, j) || !get_str(in, off, m)) return;
    s.jobs[j] = m;
  }
}

// ---------------------------------------------------------------------------
// External-store mirror client (blocking, bounded by socket timeouts so
// a dead store can stall the loop by at most ~2s per throttled push).
// ---------------------------------------------------------------------------

static const char kMirrorKey[] = "_cp_mirror";

int mirror_dial(const std::string& host, int port) {
  // Hostnames allowed (getaddrinfo), not just numeric IPs.
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", port);
  if (getaddrinfo(host.c_str(), portbuf, &hints, &res) != 0 ||
      res == nullptr)
    return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) { freeaddrinfo(res); return -1; }
  timeval tv{2, 0};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  bool ok = connect(fd, res->ai_addr, res->ai_addrlen) == 0;
  freeaddrinfo(res);
  if (!ok) { close(fd); return -1; }
  return fd;
}

bool mirror_write_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool mirror_read_all(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Send one request frame and read its response body (skipping pubsub
// pushes). Returns false on any socket/protocol error.
bool mirror_request(int fd, uint64_t req_id, uint8_t op,
                    const std::string& args, std::string& resp_body) {
  std::string p;
  p.push_back(0);  // frame type: request
  p.append(reinterpret_cast<const char*>(&req_id), 8);
  p.push_back(static_cast<char>(op));
  p.append(args);
  uint32_t len = static_cast<uint32_t>(p.size());
  if (!mirror_write_all(fd, &len, 4) ||
      !mirror_write_all(fd, p.data(), p.size()))
    return false;
  for (;;) {
    uint32_t rlen;
    if (!mirror_read_all(fd, &rlen, 4) || rlen < 1 ||
        rlen > (256u << 20))
      return false;
    std::string frame(rlen, '\0');
    if (!mirror_read_all(fd, frame.data(), rlen)) return false;
    if (frame[0] != 0) continue;  // pubsub push — not for us
    if (rlen < 9) return false;
    resp_body.assign(frame, 9, std::string::npos);
    return true;
  }
}

void mirror_push(Server& s) {
  if (s.mirror_port == 0 || !s.mirror_dirty) return;
  s.last_mirror_ms = now_ms();
  if (s.mirror_fd < 0)
    s.mirror_fd = mirror_dial(s.mirror_host, s.mirror_port);
  if (s.mirror_fd < 0) {
    fprintf(stderr, "mirror %s:%d unreachable; state not mirrored\n",
            s.mirror_host.c_str(), s.mirror_port);
    return;  // stays dirty; retried next interval
  }
  std::string args;
  put_str(args, kMirrorKey);
  put_str(args, serialize_state(s));
  args.push_back(1);  // overwrite
  std::string resp;
  if (!mirror_request(s.mirror_fd, s.mirror_req_id++, OP_KV_PUT, args,
                      resp) ||
      resp.empty() || resp[0] != ST_OK) {
    fprintf(stderr, "mirror push to %s:%d failed; will retry\n",
            s.mirror_host.c_str(), s.mirror_port);
    close(s.mirror_fd);
    s.mirror_fd = -1;
  } else {
    s.mirror_dirty = false;
  }
}

bool mirror_restore(Server& s) {
  int fd = mirror_dial(s.mirror_host, s.mirror_port);
  if (fd < 0) return false;
  std::string args;
  put_str(args, kMirrorKey);
  std::string resp;
  bool ok = mirror_request(fd, 1, OP_KV_GET, args, resp);
  close(fd);
  if (!ok || resp.size() < 1 || resp[0] != ST_OK) return false;
  size_t off = 1;
  std::string blob;
  if (!get_str(resp, off, blob)) return false;
  deserialize_state(s, blob);
  fprintf(stderr, "restored state from mirror %s:%d (%zu bytes)\n",
          s.mirror_host.c_str(), s.mirror_port, blob.size());
  return true;
}

void set_nonblock(int fd) {
  // Edge cases aside, the loop never blocks on a socket.
  int flags = 0;
  flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void arm_events(Server& s, Conn& c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c.outq.empty() ? 0 : EPOLLOUT);
  ev.data.fd = c.fd;
  epoll_ctl(s.epfd, EPOLL_CTL_MOD, c.fd, &ev);
}

void queue_frame(Server& s, Conn& c, uint8_t type,
                 const std::vector<uint8_t>& body) {
  std::vector<uint8_t> frame(5 + body.size());
  uint32_t len = static_cast<uint32_t>(1 + body.size());
  memcpy(&frame[0], &len, 4);  // cxx-wire: cp-frame-len <I
  frame[4] = type;
  memcpy(frame.data() + 5, body.data(), body.size());
  c.outq.push_back(std::move(frame));
  arm_events(s, c);
}

void close_conn(Server& s, int fd) {
  auto it = s.conns.find(fd);
  if (it == s.conns.end()) return;
  for (const auto& ch : it->second.subs) {
    auto cit = s.channels.find(ch);
    if (cit != s.channels.end()) {
      cit->second.erase(fd);
      if (cit->second.empty()) s.channels.erase(cit);
    }
  }
  epoll_ctl(s.epfd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  s.conns.erase(it);
}

void publish(Server& s, const std::string& channel,
             const std::string& payload) {
  auto it = s.channels.find(channel);
  if (it == s.channels.end()) return;
  Writer w;
  w.str(channel);
  w.str(payload);
  // Copy the fd set: queue_frame may drop a dead conn via arm failure.
  std::vector<int> fds(it->second.begin(), it->second.end());
  for (int fd : fds) {
    auto cit = s.conns.find(fd);
    if (cit != s.conns.end()) queue_frame(s, cit->second, 1, w.buf);
  }
}

// ---------------------------------------------------------------------------
// Request dispatch
// ---------------------------------------------------------------------------
void dispatch(Server& s, Conn& c, Reader& r) {
  uint64_t req_id = r.u64();  // cxx-wire: cp-req-id <Q
  uint8_t op = r.u8();
  Writer w;
  w.u64(req_id);
  uint64_t t0 = now_us();

  auto finish = [&](void) {
    queue_frame(s, c, 0, w.buf);
    OpStat& st = s.stats[op];
    st.count += 1;
    st.total_us += now_us() - t0;
  };

  if (!r.ok) { w.u8(ST_BAD_REQUEST); finish(); return; }

  switch (op) {
    case OP_PING: {
      w.u8(ST_OK);
      w.u64(now_ms());
      break;
    }
    case OP_KV_PUT: {
      std::string key = r.str(), val = r.str();
      uint8_t overwrite = r.u8();
      if (!r.ok) { w.u8(ST_BAD_REQUEST); break; }
      auto it = s.kv.find(key);
      if (it != s.kv.end() && !overwrite) {
        w.u8(ST_EXISTS);
      } else {
        s.kv[key] = val;
        mark_dirty(s);
        w.u8(ST_OK);
      }
      break;
    }
    case OP_KV_GET: {
      std::string key = r.str();
      auto it = s.kv.find(key);
      if (it == s.kv.end()) { w.u8(ST_NOT_FOUND); }
      else { w.u8(ST_OK); w.str(it->second); }
      break;
    }
    case OP_KV_DEL: {
      std::string key = r.str();
      bool erased = s.kv.erase(key) > 0;
      if (erased) mark_dirty(s);
      w.u8(erased ? ST_OK : ST_NOT_FOUND);
      break;
    }
    case OP_KV_EXISTS: {
      std::string key = r.str();
      w.u8(ST_OK);
      w.u8(s.kv.count(key) ? 1 : 0);
      break;
    }
    case OP_KV_KEYS: {
      std::string prefix = r.str();
      std::vector<const std::string*> keys;
      for (auto it = s.kv.lower_bound(prefix); it != s.kv.end(); ++it) {
        if (it->first.compare(0, prefix.size(), prefix) != 0) break;
        keys.push_back(&it->first);
      }
      w.u8(ST_OK);
      w.u32(static_cast<uint32_t>(keys.size()));
      for (auto* k : keys) w.str(*k);
      break;
    }
    case OP_SUBSCRIBE: {
      std::string ch = r.str();
      c.subs.insert(ch);
      s.channels[ch].insert(c.fd);
      w.u8(ST_OK);
      break;
    }
    case OP_UNSUBSCRIBE: {
      std::string ch = r.str();
      c.subs.erase(ch);
      auto it = s.channels.find(ch);
      if (it != s.channels.end()) {
        it->second.erase(c.fd);
        if (it->second.empty()) s.channels.erase(it);
      }
      w.u8(ST_OK);
      break;
    }
    case OP_PUBLISH: {
      std::string ch = r.str(), payload = r.str();
      uint32_t n = 0;
      auto it = s.channels.find(ch);
      if (it != s.channels.end())
        n = static_cast<uint32_t>(it->second.size());
      publish(s, ch, payload);
      w.u8(ST_OK);
      w.u32(n);
      break;
    }
    case OP_REGISTER_NODE: {
      std::string node_id = r.str(), meta = r.str();
      NodeInfo& n = s.nodes[node_id];
      n.meta = meta;
      n.last_heartbeat_ms = now_ms();
      n.alive = true;
      n.draining = false;
      publish(s, "node_events", "ALIVE:" + node_id);
      w.u8(ST_OK);
      break;
    }
    case OP_HEARTBEAT: {
      std::string node_id = r.str();
      auto it = s.nodes.find(node_id);
      if (it == s.nodes.end()) { w.u8(ST_NOT_FOUND); break; }
      // Optional trailing load report (older clients omit it).
      if (r.left > 0) {
        std::string load = r.str();
        if (r.ok) it->second.load = std::move(load);
      }
      it->second.last_heartbeat_ms = now_ms();
      if (!it->second.alive) {
        it->second.alive = true;
        publish(s, "node_events", "ALIVE:" + node_id);
      }
      w.u8(ST_OK);
      break;
    }
    case OP_DRAIN_NODE: {
      std::string node_id = r.str();
      auto it = s.nodes.find(node_id);
      if (it == s.nodes.end()) { w.u8(ST_NOT_FOUND); break; }
      it->second.draining = true;
      publish(s, "node_events", "DRAINING:" + node_id);
      w.u8(ST_OK);
      break;
    }
    case OP_LIST_NODES: {
      w.u8(ST_OK);
      w.u32(static_cast<uint32_t>(s.nodes.size()));
      uint64_t now = now_ms();
      for (const auto& [nid, n] : s.nodes) {
        w.str(nid);
        w.str(n.meta);
        w.u8(n.alive ? 1 : 0);
        w.u8(n.draining ? 1 : 0);
        w.u64(now - n.last_heartbeat_ms);
        w.str(n.load);
      }
      break;
    }
    case OP_REGISTER_ACTOR: {
      std::string actor_id = r.str(), name = r.str(), meta = r.str();
      if (!name.empty()) {
        auto nit = s.named_actors.find(name);
        if (nit != s.named_actors.end() && nit->second != actor_id) {
          // Name taken by a DIFFERENT live actor → reject (reference:
          // GcsActorManager duplicate-name creation error). The same
          // actor may re-register to refresh its location metadata
          // (restart-with-replacement).
          auto ait = s.actors.find(nit->second);
          if (ait != s.actors.end() && ait->second.state != "DEAD") {
            w.u8(ST_EXISTS);
            break;
          }
        }
        s.named_actors[name] = actor_id;
      }
      ActorInfo& a = s.actors[actor_id];
      a.name = name;
      a.state = "PENDING";
      a.meta = meta;
      mark_dirty(s);
      publish(s, "actor_events", "PENDING:" + actor_id);
      w.u8(ST_OK);
      break;
    }
    case OP_UPDATE_ACTOR: {
      std::string actor_id = r.str(), state = r.str();
      auto it = s.actors.find(actor_id);
      if (it == s.actors.end()) { w.u8(ST_NOT_FOUND); break; }
      it->second.state = state;
      mark_dirty(s);
      if (state == "DEAD" && !it->second.name.empty()) {
        auto nit = s.named_actors.find(it->second.name);
        if (nit != s.named_actors.end() && nit->second == actor_id)
          s.named_actors.erase(nit);
      }
      publish(s, "actor_events", state + ":" + actor_id);
      w.u8(ST_OK);
      break;
    }
    case OP_GET_ACTOR: {
      std::string actor_id = r.str();
      auto it = s.actors.find(actor_id);
      if (it == s.actors.end()) { w.u8(ST_NOT_FOUND); break; }
      w.u8(ST_OK);
      w.str(it->second.name);
      w.str(it->second.state);
      w.str(it->second.meta);
      break;
    }
    case OP_GET_NAMED_ACTOR: {
      std::string name = r.str();
      auto it = s.named_actors.find(name);
      if (it == s.named_actors.end()) { w.u8(ST_NOT_FOUND); break; }
      w.u8(ST_OK);
      w.str(it->second);
      break;
    }
    case OP_LIST_ACTORS: {
      w.u8(ST_OK);
      w.u32(static_cast<uint32_t>(s.actors.size()));
      for (const auto& [aid, a] : s.actors) {
        w.str(aid);
        w.str(a.name);
        w.str(a.state);
      }
      break;
    }
    case OP_ADD_JOB: {
      std::string job_id = r.str(), meta = r.str();
      s.jobs[job_id] = meta;
      mark_dirty(s);
      w.u8(ST_OK);
      break;
    }
    case OP_LIST_JOBS: {
      w.u8(ST_OK);
      w.u32(static_cast<uint32_t>(s.jobs.size()));
      for (const auto& [jid, meta] : s.jobs) {
        w.str(jid);
        w.str(meta);
      }
      break;
    }
    case OP_SNAPSHOT: {
      snapshot_state(s);
      w.u8(ST_OK);
      break;
    }
    case OP_STATS: {
      w.u8(ST_OK);
      w.u32(static_cast<uint32_t>(s.stats.size()));
      for (const auto& [o, st] : s.stats) {
        w.u8(o);
        w.u64(st.count);
        w.u64(st.total_us);
      }
      break;
    }
    default:
      w.u8(ST_BAD_REQUEST);
  }
  finish();
}

// ---------------------------------------------------------------------------
// Event loop
// ---------------------------------------------------------------------------
void handle_readable(Server& s, int fd) {
  auto it = s.conns.find(fd);
  if (it == s.conns.end()) return;
  Conn& c = it->second;
  char buf[65536];
  for (;;) {
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n > 0) {
      c.inbuf.insert(c.inbuf.end(), buf, buf + n);
    } else if (n == 0) {
      close_conn(s, fd);
      return;
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      close_conn(s, fd);
      return;
    }
  }
  // Drain complete frames.
  size_t off = 0;
  while (c.inbuf.size() - off >= 4) {
    uint32_t len;
    memcpy(&len, c.inbuf.data() + off, 4);
    if (len > (256u << 20)) { close_conn(s, fd); return; }  // frame cap (fits mirror blobs)
    if (c.inbuf.size() - off - 4 < len) break;
    const uint8_t* body = c.inbuf.data() + off + 4;
    // body[0] = frame type (requests only from clients).
    if (len >= 1 && body[0] == 0) {
      Reader r(body + 1, len - 1);
      dispatch(s, c, r);
      // dispatch may close conns (never its own); re-find ours.
      if (s.conns.find(fd) == s.conns.end()) return;
    }
    off += 4 + len;
  }
  if (off > 0) c.inbuf.erase(c.inbuf.begin(), c.inbuf.begin() + off);
}

void handle_writable(Server& s, int fd) {
  auto it = s.conns.find(fd);
  if (it == s.conns.end()) return;
  Conn& c = it->second;
  while (!c.outq.empty()) {
    auto& front = c.outq.front();
    ssize_t n = send(fd, front.data() + c.out_off,
                     front.size() - c.out_off, MSG_NOSIGNAL);
    if (n > 0) {
      c.out_off += n;
      if (c.out_off == front.size()) {
        c.outq.pop_front();
        c.out_off = 0;
      }
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      close_conn(s, fd);
      return;
    }
  }
  arm_events(s, c);
}

void check_health(Server& s) {
  uint64_t now = now_ms();
  for (auto& [nid, n] : s.nodes) {
    if (n.alive && now - n.last_heartbeat_ms > s.health_timeout_ms) {
      n.alive = false;
      publish(s, "node_events", "DEAD:" + nid);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 0;
  uint64_t health_timeout_ms = 5000;
  const char* persist = nullptr;
  bool bind_all = false;  // 0.0.0.0 for multi-host clusters
  const char* mirror = nullptr;  // "host:port" of the external store
  uint64_t mirror_interval_ms = 200;
  for (int i = 1; i < argc; i++) {
    if (strcmp(argv[i], "--bind-all") == 0) bind_all = true;
    if (i >= argc - 1) continue;
    if (strcmp(argv[i], "--port") == 0) port = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--health-timeout-ms") == 0)
      health_timeout_ms = strtoull(argv[i + 1], nullptr, 10);
    if (strcmp(argv[i], "--persist") == 0) persist = argv[i + 1];
    if (strcmp(argv[i], "--mirror") == 0) mirror = argv[i + 1];
    if (strcmp(argv[i], "--mirror-interval-ms") == 0)
      mirror_interval_ms = strtoull(argv[i + 1], nullptr, 10);
  }

  Server s;
  s.health_timeout_ms = health_timeout_ms;
  if (persist != nullptr) {
    s.persist_path = persist;
    restore_state(s);  // reference: gcs_init_data.cc reload on restart
  }
  if (mirror != nullptr) {
    std::string m(mirror);
    size_t colon = m.rfind(':');
    if (colon == std::string::npos ||
        atoi(m.c_str() + colon + 1) <= 0) {
      fprintf(stderr, "--mirror must be host:port (got %s)\n", mirror);
      return 1;  // accepted != enforced: never run believing HA is on
    }
    s.mirror_host = m.substr(0, colon);
    s.mirror_port = atoi(m.c_str() + colon + 1);
    s.mirror_interval_ms = mirror_interval_ms;
    // Take over from the external store when local state is empty
    // (fresh host after losing the previous control plane).
    if (s.kv.empty() && s.actors.empty() && s.jobs.empty())
      mirror_restore(s);
  }
  s.listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(s.listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(bind_all ? INADDR_ANY : INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(s.listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s.listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen(s.listen_fd, 128);
  set_nonblock(s.listen_fd);

  s.epfd = epoll_create1(0);
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.fd = s.listen_fd;
  epoll_ctl(s.epfd, EPOLL_CTL_ADD, s.listen_fd, &lev);

  printf("PORT=%d\n", ntohs(addr.sin_port));
  fflush(stdout);

  epoll_event events[256];
  for (;;) {
    // Wake at least as often as the mirror interval — otherwise a
    // quiet cluster's last mutations sit unmirrored for up to 500ms.
    int wait_ms = 500;
    if (s.mirror_port != 0 && s.mirror_interval_ms < 500)
      wait_ms = static_cast<int>(s.mirror_interval_ms);
    int n = epoll_wait(s.epfd, events, 256, wait_ms);
    if (n < 0) {
      if (errno == EINTR) continue;
      perror("epoll_wait");
      return 1;
    }
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == s.listen_fd) {
        for (;;) {
          int cfd = accept(s.listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(s.epfd, EPOLL_CTL_ADD, cfd, &ev);
          s.conns[cfd].fd = cfd;
        }
        continue;
      }
      if (events[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, fd);
        continue;
      }
      if (events[i].events & EPOLLIN) handle_readable(s, fd);
      if (events[i].events & EPOLLOUT) handle_writable(s, fd);
    }
    check_health(s);
    // Throttled snapshots: full-state rewrites on every epoll tick
    // would be O(state) I/O per write under load.
    if (s.mirror_port != 0
        && now_ms() - s.last_mirror_ms >= s.mirror_interval_ms)
      mirror_push(s);
    if (s.dirty && now_ms() - s.last_snapshot_ms >= 1000)
      snapshot_state(s);
  }
  return 0;
}
