// Sanitizer stress harness for the object-transfer plane (server +
// pull/push manager), reference: the C++ core's TSAN/ASAN CI coverage
// over object_manager tests (SURVEY.md §4.2). Build + run via
// `make -C src sanitize`.
//
// Workload: two arenas (src serves, dst receives) on loopback.
//  - 4 submitter threads × pulls through ONE PullManager (fair queues,
//    budget admission, dedup) — ids mix present/missing objects;
//  - 2 raw-client threads doing rto_pull/rto_stat on their own
//    connections (concurrent with manager traffic);
//  - 1 disruptor thread that connects, writes garbage, half-frames,
//    and slams the connection shut (server must survive + stay framed);
//  - pushes from dst→src through the same manager;
//  - a final rtp_stop with requests still queued (stop-path coverage).

#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <arpa/inet.h>
#include <netinet/in.h>

extern "C" {
void* rts_connect(const char* name, uint64_t capacity, int create);
void rts_disconnect(void* handle);
int rts_unlink(const char* name);
int rts_create(void* h, const uint8_t* id, uint64_t size, uint64_t* off);
int rts_seal(void* h, const uint8_t* id);
uint8_t* rts_base(void* h);
void* rto_serve(const char* shm, uint64_t cap, int port, int bind_all);
int rto_port(void* h);
void rto_stop(void* h);
void* rto_connect(const char* host, int port);
void rto_close(void* conn);
int rto_pull(void* conn, void* store, const uint8_t* id);
int64_t rto_stat(void* conn, const uint8_t* id);
void* rtp_start(const char* shm, uint64_t budget, int workers,
                int timeout_ms, int retries);
uint64_t rtp_submit(void* h, uint64_t requester, const char* host,
                    int port, const uint8_t* id, int is_push);
uint64_t rtp_submit_multi(void* h, uint64_t requester,
                          const char* endpoints, const uint8_t* id);
int rtp_wait(void* h, uint64_t ticket, int timeout_ms);
void rtp_stats(void* h, uint64_t* inflight, uint64_t* queued,
               uint64_t* active);
void rtp_stop(void* h);
}

namespace {

char g_src[64], g_dst[64];
int g_src_port = 0, g_dst_port = 0;
void* g_mgr = nullptr;     // dst-bound manager (pull from src)
void* g_push_mgr = nullptr;  // src-bound? no: dst-local, pushes to dst? see main
constexpr int kObjects = 48;

void make_id(uint8_t* id, int tag) {
  memset(id, 0, 28);
  memcpy(id, &tag, sizeof(tag));
}

void* submitter(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  unsigned seed = static_cast<unsigned>(tid) * 104729 + 7;
  for (int i = 0; i < 120; i++) {
    uint8_t id[28];
    // 1 in 4 targets a missing object (error path).
    int tag = rand_r(&seed) % (kObjects + kObjects / 4);
    make_id(id, tag);
    uint64_t t;
    if (rand_r(&seed) % 3 == 0) {
      // Multi-endpoint submit: dead candidate first, so the worker
      // exercises the per-endpoint fallback before reaching src.
      char eps[64];
      snprintf(eps, sizeof(eps), "127.0.0.1:1,127.0.0.1:%d",
               g_src_port);
      t = rtp_submit_multi(g_mgr, static_cast<uint64_t>(tid), eps, id);
    } else {
      t = rtp_submit(g_mgr, static_cast<uint64_t>(tid),
                     "127.0.0.1", g_src_port, id, 0);
    }
    int rc = rtp_wait(g_mgr, t, 30000);
    if (rc != 0 && rc != -1 && rc != -2 && rc != -6) {
      fprintf(stderr, "pull rc=%d tag=%d\n", rc, tag);
      abort();
    }
  }
  return nullptr;
}

void* raw_client(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  void* dst = rts_connect(g_dst, 0, 0);
  void* conn = rto_connect("127.0.0.1", g_src_port);
  if (conn == nullptr || dst == nullptr) abort();
  unsigned seed = static_cast<unsigned>(tid) * 31337 + 1;
  for (int i = 0; i < 150; i++) {
    uint8_t id[28];
    make_id(id, rand_r(&seed) % (kObjects + 8));
    if (rand_r(&seed) % 2) {
      int64_t sz = rto_stat(conn, id);
      if (sz < -1) abort();
    } else {
      int rc = rto_pull(conn, dst, id);
      if (rc != 0 && rc != -1 && rc != -2 && rc != -4) abort();
    }
  }
  rto_close(conn);
  rts_disconnect(dst);
  return nullptr;
}

void* disruptor(void*) {
  unsigned seed = 42;
  for (int i = 0; i < 60; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(static_cast<uint16_t>(g_src_port));
    inet_pton(AF_INET, "127.0.0.1", &a.sin_addr);
    if (connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) == 0) {
      char junk[64];
      for (size_t j = 0; j < sizeof(junk); j++)
        junk[j] = static_cast<char>(rand_r(&seed));
      // Garbage op, half a frame, or nothing — then slam shut.
      int mode = rand_r(&seed) % 3;
      if (mode == 0) (void)!write(fd, junk, sizeof(junk));
      if (mode == 1) (void)!write(fd, junk, 3);
      struct linger lg {1, 0};  // RST on close
      setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
    }
    close(fd);
  }
  return nullptr;
}

void* pusher(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  unsigned seed = static_cast<unsigned>(tid) * 7 + 3;
  for (int i = 0; i < 60; i++) {
    uint8_t id[28];
    make_id(id, 1000 + (rand_r(&seed) % kObjects));  // src-side ids
    uint64_t t = rtp_submit(g_push_mgr, static_cast<uint64_t>(tid),
                            "127.0.0.1", g_dst_port, id, 1);
    int rc = rtp_wait(g_push_mgr, t, 30000);
    if (rc != 0 && rc != -1 && rc != -2 && rc != -6) {
      fprintf(stderr, "push rc=%d\n", rc);
      abort();
    }
  }
  return nullptr;
}

}  // namespace

int main() {
  snprintf(g_src, sizeof(g_src), "/rto_stress_s_%d", getpid());
  snprintf(g_dst, sizeof(g_dst), "/rto_stress_d_%d", getpid());
  void* src = rts_connect(g_src, 32ull << 20, 1);
  void* dst = rts_connect(g_dst, 32ull << 20, 1);
  if (src == nullptr || dst == nullptr) return 1;
  uint8_t* base = rts_base(src);
  unsigned seed = 1;
  for (int i = 0; i < kObjects; i++) {
    uint8_t id[28];
    make_id(id, i);
    uint64_t off = 0;
    uint64_t n = 256 + (rand_r(&seed) % (96 << 10));
    if (rts_create(src, id, n, &off) != 0) return 1;
    memset(base + off, i & 0xff, n);
    rts_seal(src, id);
  }
  // Push sources on the src arena under a distinct tag space.
  for (int i = 0; i < kObjects; i++) {
    uint8_t id[28];
    make_id(id, 1000 + i);
    uint64_t off = 0;
    uint64_t n = 128 + (rand_r(&seed) % (16 << 10));
    if (rts_create(src, id, n, &off) != 0) return 1;
    memset(base + off, 0x5a, n);
    rts_seal(src, id);
  }

  void* srv_src = rto_serve(g_src, 0, 0, 0);
  void* srv_dst = rto_serve(g_dst, 0, 0, 0);
  if (srv_src == nullptr || srv_dst == nullptr) return 1;
  g_src_port = rto_port(srv_src);
  g_dst_port = rto_port(srv_dst);
  g_mgr = rtp_start(g_dst, 4ull << 20, 3, 5000, 1);
  g_push_mgr = rtp_start(g_src, 4ull << 20, 2, 5000, 1);
  if (g_mgr == nullptr || g_push_mgr == nullptr) return 1;

  pthread_t threads[8];
  for (long t = 0; t < 4; t++)
    pthread_create(&threads[t], nullptr, submitter,
                   reinterpret_cast<void*>(t));
  for (long t = 4; t < 6; t++)
    pthread_create(&threads[t], nullptr, raw_client,
                   reinterpret_cast<void*>(t));
  pthread_create(&threads[6], nullptr, disruptor, nullptr);
  pthread_create(&threads[7], nullptr, pusher,
                 reinterpret_cast<void*>(7L));
  for (int t = 0; t < 8; t++) pthread_join(threads[t], nullptr);

  // Stop with work still queued: submit without waiting, then stop.
  for (int i = 0; i < 16; i++) {
    uint8_t id[28];
    make_id(id, i);
    rtp_submit(g_mgr, 99, "127.0.0.1", g_src_port, id, 0);
  }
  rtp_stop(g_mgr);
  rtp_stop(g_push_mgr);
  rto_stop(srv_src);
  rto_stop(srv_dst);
  rts_disconnect(src);
  rts_disconnect(dst);
  rts_unlink(g_src);
  rts_unlink(g_dst);
  printf("OK transfer stress\n");
  return 0;
}
