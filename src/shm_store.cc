// Shared-memory object store — the native per-host object plane.
//
// Capability-equivalent to the reference's plasma store
// (reference: src/ray/object_manager/plasma/ — store.h:55 PlasmaStore,
// object_lifecycle_manager.h, eviction_policy.h LRU,
// client.h ExperimentalMutableObjectWriteAcquire/Release): a POSIX
// shared-memory arena holding immutable sealed objects addressed by
// 28-byte ObjectIDs, with create/seal/get(pin)/release/delete, LRU
// eviction of unpinned sealed objects under memory pressure, and
// seqlock-style MUTABLE objects used as compiled-DAG channels.
//
// Design differences from the reference (TPU-first, simpler):
//  - one mmap'd arena per host, attached by every worker process
//    (fd-passing unnecessary: attach by name, offsets are stable)
//  - allocation: first-fit free list guarded by a process-shared mutex
//    (the store is the buffer plane; the hot compute path lives in HBM)
//  - buffers are 256-byte aligned so jax/numpy dlpack views stay aligned
//
// Built as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <climits>

#include <fcntl.h>
#include <linux/futex.h>
#include <pthread.h>
#include <signal.h>
#include <sys/syscall.h>
#include <sys/time.h>

#include <algorithm>
#include <string>
#include <vector>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52545055;  // "RTPU" (v3: pin records + futex channels)
constexpr uint32_t kIdLen = 28;
constexpr uint32_t kAlign = 256;
// Per-slot pin records: enough for the realistic concurrent-pinner
// count (driver + a few workers reading one object). Pins beyond this
// are still counted in `pins` but untracked — they leak if their
// process crashes (hdr->pin_overflows counts how often that risk
// existed).
constexpr int kPinnersPerSlot = 4;
constexpr uint32_t kMaxObjects = 1 << 16;  // hash slots

enum SlotState : uint32_t {
  SLOT_FREE = 0,
  SLOT_CREATED = 1,   // allocated, being written
  SLOT_SEALED = 2,    // immutable, readable
  SLOT_MUTABLE = 3,   // channel object (seqlock)
  SLOT_TOMBSTONE = 4, // deleted (keeps probe chains alive)
};

struct PinRec {        // per-process pin accounting (crash reclaim)
  int32_t pid;
  int32_t count;
  uint64_t start;      // /proc starttime: disambiguates recycled pids
};

struct Slot {
  uint8_t id[kIdLen];
  uint32_t state;
  uint64_t offset;     // data offset in arena
  uint64_t size;       // payload size
  uint64_t alloc_size; // rounded allocation size
  int64_t pins;        // pinned readers (not evictable while > 0)
  uint64_t seal_seq;   // LRU clock (monotonic seal/touch counter)
  uint64_t version;    // mutable-object version (seqlock: odd = writing)
  int32_t owner_pid;   // creator, while SLOT_CREATED (crash repair)
  uint64_t owner_start;  // creator's starttime (recycled-pid guard)
  PinRec pinners[kPinnersPerSlot];  // who holds the pins (by pid)
  // Channel wake counter (futex word): bumped + futex-woken on every
  // write_release so readers block in the kernel instead of polling —
  // on single-core hosts a polling reader starves the very writer it
  // waits for.
  uint32_t wake_seq;
};

struct FreeNode {           // free-list node stored at block start
  uint64_t size;            // block size (incl. node)
  uint64_t next;            // arena offset of next free block (0 = none)
};

struct Header {
  uint32_t magic;
  uint32_t id_len;
  uint64_t capacity;        // arena bytes
  uint64_t data_start;      // offset of first data byte
  uint64_t used;            // allocated bytes
  uint64_t free_head;       // offset of first free block (0 = none)
  uint64_t seq;             // LRU clock
  uint64_t num_objects;
  uint64_t map_size;        // total mapping bytes (free space ends here)
  uint64_t pin_overflows;   // pins taken beyond kPinnersPerSlot records
  pthread_mutex_t mu;
  Slot slots[kMaxObjects];
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t map_size;
  int fd;
};

uint64_t Align(uint64_t n) { return (n + kAlign - 1) & ~uint64_t(kAlign - 1); }

// Cross-process futex on a shared-memory word (NOT FUTEX_PRIVATE).
long FutexWait(uint32_t* addr, uint32_t expected, int timeout_ms) {
  struct timespec ts;
  struct timespec* tp = nullptr;
  if (timeout_ms >= 0) {
    ts.tv_sec = timeout_ms / 1000;
    ts.tv_nsec = static_cast<long>(timeout_ms % 1000) * 1000000L;
    tp = &ts;
  }
  return syscall(SYS_futex, addr, FUTEX_WAIT, expected, tp, nullptr, 0);
}

void FutexWakeAll(uint32_t* addr) {
  syscall(SYS_futex, addr, FUTEX_WAKE, INT_MAX, nullptr, nullptr, 0);
}

uint32_t Hash(const uint8_t* id) {
  // FNV-1a over the 28-byte id.
  uint32_t h = 2166136261u;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 16777619u;
  }
  return h;
}

Slot* FindSlot(Header* hdr, const uint8_t* id, bool for_insert) {
  uint32_t idx = Hash(id) & (kMaxObjects - 1);
  Slot* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kMaxObjects; probe++) {
    Slot* s = &hdr->slots[(idx + probe) & (kMaxObjects - 1)];
    if (s->state == SLOT_FREE) {
      if (for_insert) return first_tomb ? first_tomb : s;
      return nullptr;
    }
    if (s->state == SLOT_TOMBSTONE) {
      if (for_insert && !first_tomb) first_tomb = s;
      continue;
    }
    if (memcmp(s->id, id, kIdLen) == 0) return s;
  }
  return for_insert ? first_tomb : nullptr;
}

// -- allocator (first-fit free list; caller holds mu) -----------------------

// Allocates >= need bytes; *got_out receives the actual block size
// consumed (the whole free block when the remainder is too small to
// split) — callers must record and later free exactly *got_out bytes.
uint64_t AllocLocked(Store* st, uint64_t need, uint64_t* got_out) {
  Header* h = st->hdr;
  need = Align(need);
  uint64_t prev = 0, cur = h->free_head;
  while (cur) {
    FreeNode* node = reinterpret_cast<FreeNode*>(st->base + cur);
    if (node->size >= need) {
      uint64_t remain = node->size - need;
      if (remain >= kAlign * 2) {
        uint64_t tail = cur + need;
        FreeNode* tn = reinterpret_cast<FreeNode*>(st->base + tail);
        tn->size = remain;
        tn->next = node->next;
        if (prev) reinterpret_cast<FreeNode*>(st->base + prev)->next = tail;
        else h->free_head = tail;
      } else {
        need = node->size;
        if (prev) reinterpret_cast<FreeNode*>(st->base + prev)->next = node->next;
        else h->free_head = node->next;
      }
      h->used += need;
      *got_out = need;
      return cur;
    }
    prev = cur;
    cur = node->next;
  }
  return 0;
}

void FreeLocked(Store* st, uint64_t offset, uint64_t size) {
  // Insert sorted by offset and coalesce with neighbors.
  Header* h = st->hdr;
  size = Align(size);
  h->used -= size;
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < offset) {
    prev = cur;
    cur = reinterpret_cast<FreeNode*>(st->base + cur)->next;
  }
  FreeNode* node = reinterpret_cast<FreeNode*>(st->base + offset);
  node->size = size;
  node->next = cur;
  if (prev) {
    FreeNode* pn = reinterpret_cast<FreeNode*>(st->base + prev);
    pn->next = offset;
    if (prev + pn->size == offset) {  // coalesce with prev
      pn->size += node->size;
      pn->next = node->next;
      node = pn;
      offset = prev;
    }
  } else {
    h->free_head = offset;
  }
  if (node->next && offset + node->size == node->next) {  // coalesce next
    FreeNode* nn = reinterpret_cast<FreeNode*>(st->base + node->next);
    node->size += nn->size;
    node->next = nn->next;
  }
}

// Start time (clock ticks since boot) of a LIVE, non-zombie process;
// 0 when the process is gone or a zombie (a zombie holds no mappings
// and can't be mid-anything — its pins are reclaimable, and kill(pid,
// 0) alone would miss it: daemons observe worker crashes BEFORE the
// child is reaped). kNoProcFS on /proc-less systems — consistent
// between record and reclaim, degrading to pid-only matching.
constexpr uint64_t kNoProcFS = ~uint64_t(0);

uint64_t LiveStartTime(int32_t pid) {
  if (pid <= 0) return 0;
  if (kill(pid, 0) != 0) return 0;  // ESRCH or EPERM: not ours anyway
  char path[64];
  snprintf(path, sizeof(path), "/proc/%d/stat", pid);
  FILE* f = fopen(path, "r");
  if (!f) return kNoProcFS;
  char buf[1024];
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  buf[n] = 0;
  // Fields: pid (comm) state ...; comm may contain spaces/parens —
  // the state char follows the LAST ')'. starttime is field 22, i.e.
  // the 19th token after state.
  char* rp = strrchr(buf, ')');
  if (!rp) return kNoProcFS;
  while (*++rp == ' ') {
  }
  char state = *rp;
  if (state == 'Z' || state == 'X' || state == 0) return 0;
  unsigned long long start = 0;
  if (sscanf(rp,
             "%*c %*s %*s %*s %*s %*s %*s %*s %*s %*s %*s %*s %*s "
             "%*s %*s %*s %*s %*s %*s %llu",
             &start) != 1)
    return kNoProcFS;
  return static_cast<uint64_t>(start);
}

uint64_t OwnStartTime() {
  // Keyed on pid so a fork()ed child (Python multiprocessing default)
  // re-reads ITS OWN start time — a static surviving the fork would
  // record the parent's, making every liveness check see the child as
  // a recycled pid and reclaim a live reader's pins. Guarded by a
  // process-local mutex: arena mutexes are per-arena, and one process
  // can hold several arenas (in-process cluster fixtures), so they do
  // not serialize this cache.
  static pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  static int32_t cached_pid = 0;
  static uint64_t cached_start = 0;
  pthread_mutex_lock(&mu);
  int32_t pid = static_cast<int32_t>(getpid());
  if (pid != cached_pid) {
    cached_start = LiveStartTime(pid);
    cached_pid = pid;
  }
  uint64_t out = cached_start;
  pthread_mutex_unlock(&mu);
  return out;
}

void RecordPinLocked(Header* h, Slot* s, int32_t pid, uint64_t start) {
  for (int i = 0; i < kPinnersPerSlot; i++) {
    PinRec* p = &s->pinners[i];
    if (p->pid == pid && p->start == start) { p->count++; return; }
  }
  for (int i = 0; i < kPinnersPerSlot; i++) {
    PinRec* p = &s->pinners[i];
    if (p->pid == pid) {
      // Same pid, different incarnation: the old holder is dead and
      // its pid was recycled — reclaim its pins inline instead of
      // merging (merging would strand them under a "live" pid forever).
      s->pins -= p->count;
      if (s->pins < 0) s->pins = 0;
      *p = {pid, 1, start};
      return;
    }
  }
  for (int i = 0; i < kPinnersPerSlot; i++)
    if (s->pinners[i].pid == 0) { s->pinners[i] = {pid, 1, start}; return; }
  h->pin_overflows++;  // untracked: reclaim can't see this pin
}

void ReleasePinLocked(Slot* s, int32_t pid, uint64_t start) {
  for (int i = 0; i < kPinnersPerSlot; i++) {
    PinRec* p = &s->pinners[i];
    if (p->pid == pid && p->start == start) {
      if (--p->count <= 0) *p = {0, 0, 0};
      return;
    }
  }
}

// Drop pins recorded by processes that no longer exist (reference:
// plasma releasing a disconnected client's pins, store.h:55). A
// long-running daemon otherwise loses arena capacity to every crashed
// pinned-reader. Returns the number of pins reclaimed.
int64_t ReclaimDeadPinsLocked(Header* h) {
  int64_t reclaimed = 0;
  // Memoize pid -> starttime for the scan: it runs under the arena
  // mutex, and the same live pid (e.g. the daemon itself) can hold
  // pins on many slots — one /proc read each, not one per record.
  struct Memo { int32_t pid; uint64_t live; };
  std::vector<Memo> memo;
  auto live_of = [&memo](int32_t pid) {
    for (const Memo& m : memo)
      if (m.pid == pid) return m.live;
    uint64_t v = LiveStartTime(pid);
    memo.push_back({pid, v});
    return v;
  };
  for (uint32_t i = 0; i < kMaxObjects; i++) {
    Slot* s = &h->slots[i];
    if (s->pins <= 0) continue;
    if (s->state == SLOT_FREE || s->state == SLOT_TOMBSTONE) continue;
    for (int j = 0; j < kPinnersPerSlot; j++) {
      PinRec* p = &s->pinners[j];
      if (p->pid <= 0) continue;
      uint64_t live = live_of(p->pid);
      if (live == 0 || live != p->start) {  // gone, zombie or recycled
        s->pins -= p->count;
        reclaimed += p->count;
        *p = {0, 0, 0};
      }
    }
    if (s->pins < 0) s->pins = 0;
  }
  return reclaimed;
}

// Allocate `need` bytes, evicting least-recently-sealed unpinned objects
// until the allocation succeeds (reference: eviction_policy.h LRU).
// Returns the allocation offset (0 = full even after eviction); the
// consumed block size lands in *got_out.
uint64_t AllocOrEvictLocked(Store* st, uint64_t need, uint64_t* got_out) {
  Header* h = st->hdr;
  bool reclaimed_dead = false;
  for (;;) {
    uint64_t off = AllocLocked(st, need, got_out);
    if (off) return off;
    // Find LRU sealed, unpinned object.
    Slot* victim = nullptr;
    for (uint32_t i = 0; i < kMaxObjects; i++) {
      Slot* s = &h->slots[i];
      if (s->state == SLOT_SEALED && s->pins == 0) {
        if (!victim || s->seal_seq < victim->seal_seq) victim = s;
      }
    }
    if (!victim) {
      // Everything left is pinned: some pins may belong to crashed
      // processes — reclaim once and retry before declaring the arena
      // full (self-healing even if no one calls the explicit API).
      if (!reclaimed_dead && ReclaimDeadPinsLocked(h) > 0) {
        reclaimed_dead = true;
        continue;
      }
      return 0;
    }
    FreeLocked(st, victim->offset, victim->alloc_size);
    victim->state = SLOT_TOMBSTONE;
    h->num_objects--;
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle (or null). create=1 initializes a new arena
// if (and only if) this call creates the shm file; attaching to a live
// arena never re-initializes it — concurrent creators race via
// O_CREAT|O_EXCL, losers attach and wait for the winner's init to
// finish (magic is published last, with release semantics).
void* rts_connect(const char* name, uint64_t capacity, int create) {
  uint64_t map_size = sizeof(Header) + capacity;
  int fd = -1;
  bool init = false;
  if (create) {
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd >= 0) {
      init = true;
      if (ftruncate(fd, map_size) != 0) {
        close(fd);
        shm_unlink(name);
        return nullptr;
      }
    }
  }
  if (fd < 0) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    // Existing arena: adopt its size; wait for the creator's ftruncate.
    struct stat stbuf;
    for (int spin = 0; spin < 5000; spin++) {  // <= ~5s
      if (fstat(fd, &stbuf) != 0) { close(fd); return nullptr; }
      if (static_cast<uint64_t>(stbuf.st_size) >= sizeof(Header)) break;
      usleep(1000);
    }
    if (static_cast<uint64_t>(stbuf.st_size) < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_size = stbuf.st_size;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Store* st = new Store();
  st->hdr = reinterpret_cast<Header*>(mem);
  st->base = reinterpret_cast<uint8_t*>(mem);
  st->map_size = map_size;
  st->fd = fd;
  if (init) {
    memset(st->hdr, 0, sizeof(Header));
    st->hdr->id_len = kIdLen;
    st->hdr->capacity = capacity;
    st->hdr->data_start = Align(sizeof(Header));
    st->hdr->used = 0;
    st->hdr->seq = 1;
    st->hdr->map_size = map_size;
    // One big free block spanning the arena.
    uint64_t start = st->hdr->data_start;
    FreeNode* node = reinterpret_cast<FreeNode*>(st->base + start);
    node->size = map_size - start;
    node->next = 0;
    st->hdr->free_head = start;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&st->hdr->mu, &attr);
    __atomic_store_n(&st->hdr->magic, kMagic, __ATOMIC_RELEASE);
  } else {
    // Wait for the creator to publish the header.
    for (int spin = 0; spin < 5000; spin++) {
      if (__atomic_load_n(&st->hdr->magic, __ATOMIC_ACQUIRE) == kMagic)
        break;
      usleep(1000);
    }
    if (__atomic_load_n(&st->hdr->magic, __ATOMIC_ACQUIRE) != kMagic) {
      munmap(mem, map_size);
      close(fd);
      delete st;
      return nullptr;
    }
  }
  return st;
}

void rts_disconnect(void* handle) {
  Store* st = reinterpret_cast<Store*>(handle);
  munmap(st->base, st->map_size);
  close(st->fd);
  delete st;
}

int rts_unlink(const char* name) { return shm_unlink(name); }

// A process died while HOLDING the arena mutex: the free list may be
// mid-splice and its unsealed slots are garbage. pthread's robust-mutex
// recovery only makes the lock usable again — the shared state must be
// repaired too. The slot table is the authoritative record of
// allocated spans, so rebuild the free list (and `used`) from it,
// tombstone in-flight (SLOT_CREATED) slots, and reclaim pins recorded
// by dead processes (per-pid pin records in each slot).
static void RepairAfterOwnerDeath(Header* h) {
  uint8_t* base = reinterpret_cast<uint8_t*>(h);  // header sits at base
  struct Span { uint64_t off, size; };
  std::vector<Span> spans;
  spans.reserve(256);
  for (uint32_t i = 0; i < kMaxObjects; i++) {
    Slot* s = &h->slots[i];
    if (s->state == SLOT_CREATED) {
      // In-flight slot: reap it ONLY if its creator is gone — writers
      // fill their span without the lock, so a LIVE process may be
      // mid-write here. Zombies and recycled pids (different
      // starttime) count as gone.
      uint64_t live = s->owner_pid > 0 ? LiveStartTime(s->owner_pid) : 0;
      bool owner_dead = live == 0 || live != s->owner_start;
      if (owner_dead) {
        s->state = SLOT_TOMBSTONE;
        if (h->num_objects > 0) h->num_objects--;
        continue;  // its span returns to the free pool below
      }
    }
    if (s->state == SLOT_CREATED || s->state == SLOT_SEALED ||
        s->state == SLOT_MUTABLE)
      spans.push_back({s->offset, Align(s->alloc_size)});
  }
  std::sort(spans.begin(), spans.end(),
            [](const Span& a, const Span& b) { return a.off < b.off; });
  uint64_t used = 0;
  uint64_t cursor = h->data_start;
  uint64_t prev_free = 0;
  h->free_head = 0;
  auto add_free = [&](uint64_t off, uint64_t size) {
    if (size < sizeof(FreeNode)) return;  // unusable sliver
    FreeNode* node = reinterpret_cast<FreeNode*>(base + off);
    node->size = size;
    node->next = 0;
    if (prev_free)
      reinterpret_cast<FreeNode*>(base + prev_free)->next = off;
    else
      h->free_head = off;
    prev_free = off;
  };
  for (const Span& sp : spans) {
    if (sp.off > cursor) add_free(cursor, sp.off - cursor);
    cursor = sp.off + sp.size;
    used += sp.size;
  }
  if (cursor < h->map_size) add_free(cursor, h->map_size - cursor);
  h->used = used;
  ReclaimDeadPinsLocked(h);
}

static void Lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    RepairAfterOwnerDeath(h);
    pthread_mutex_consistent(&h->mu);
  }
}

// Create an object buffer. Returns 0 ok, -1 exists, -2 full, -3 table full.
int rts_create(void* handle, const uint8_t* id, uint64_t size,
               uint64_t* offset_out) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  if (FindSlot(h, id, false)) { pthread_mutex_unlock(&h->mu); return -1; }
  uint64_t need = Align(size ? size : 1);
  uint64_t got = 0;
  uint64_t off = AllocOrEvictLocked(st, need, &got);
  if (!off) { pthread_mutex_unlock(&h->mu); return -2; }
  Slot* s = FindSlot(h, id, true);
  if (!s) { FreeLocked(st, off, got); pthread_mutex_unlock(&h->mu); return -3; }
  memcpy(s->id, id, kIdLen);
  s->state = SLOT_CREATED;
  s->offset = off;
  s->size = size;
  s->alloc_size = got;
  s->pins = 0;
  s->version = 0;
  s->owner_pid = static_cast<int32_t>(getpid());
  s->owner_start = OwnStartTime();
  memset(s->pinners, 0, sizeof(s->pinners));
  h->num_objects++;
  *offset_out = off;
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Reclaim pins held by crashed processes (callable by the daemon when
// it observes a worker death; the allocator also does this lazily on
// pressure). Returns the number of pins reclaimed.
int64_t rts_reclaim_dead_pins(void* handle) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  int64_t n = ReclaimDeadPinsLocked(h);
  pthread_mutex_unlock(&h->mu);
  return n;
}

int rts_seal(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_CREATED) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  s->state = SLOT_SEALED;
  s->seal_seq = h->seq++;
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Get a sealed object. pin=1 increments the pin count (caller must
// rts_release). Returns 0 ok, -1 missing/unsealed.
int rts_get(void* handle, const uint8_t* id, uint64_t* offset_out,
            uint64_t* size_out, int pin) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_SEALED) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  s->seal_seq = h->seq++;  // LRU touch
  if (pin) {
    s->pins++;
    RecordPinLocked(h, s, static_cast<int32_t>(getpid()),
                    OwnStartTime());
  }
  *offset_out = s->offset;
  *size_out = s->size;
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int rts_release(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s && s->pins > 0) {
    s->pins--;
    ReleasePinLocked(s, static_cast<int32_t>(getpid()),
                     OwnStartTime());
  }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int rts_contains(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  int ok = (s && s->state == SLOT_SEALED) ? 1 : 0;
  pthread_mutex_unlock(&h->mu);
  return ok;
}

int rts_delete(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s) { pthread_mutex_unlock(&h->mu); return -1; }
  if (s->pins > 0) { pthread_mutex_unlock(&h->mu); return -2; }
  if (s->state == SLOT_CREATED) {
    // The creator (possibly another THREAD of this process) is
    // mid-write into this span — create→seal runs unlocked; freeing
    // it under the writer corrupts whoever reallocates the span.
    // (Crash cleanup of dead creators happens in
    // RepairAfterOwnerDeath, not here.)
    pthread_mutex_unlock(&h->mu);
    return -3;
  }
  bool was_channel = s->state == SLOT_MUTABLE;
  FreeLocked(st, s->offset, s->alloc_size);
  s->state = SLOT_TOMBSTONE;
  h->num_objects--;
  if (was_channel) {
    // Unpark blocked readers so they observe the deletion now
    // instead of waiting out their timeout.
    __atomic_fetch_add(&s->wake_seq, 1, __ATOMIC_ACQ_REL);
    FutexWakeAll(&s->wake_seq);
  }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

uint64_t rts_used(void* handle) {
  return reinterpret_cast<Store*>(handle)->hdr->used;
}

// Arena base pointer — offsets from rts_get/rts_ch_read are relative
// to this (the C++ client reads in-process; Python mmaps separately).
void* rts_base(void* handle) {
  return reinterpret_cast<Store*>(handle)->base;
}

uint64_t rts_capacity(void* handle) {
  return reinterpret_cast<Store*>(handle)->hdr->capacity;
}

uint64_t rts_num_objects(void* handle) {
  return reinterpret_cast<Store*>(handle)->hdr->num_objects;
}

// Per-process arena holdings, from the slot table's pin records (the
// same data crash reclaim walks): for every live slot, each recorded
// pinner is charged the slot's full alloc_size (pins are shares of the
// whole object, not byte ranges), and SLOT_CREATED spans are charged
// to their writer. Written as JSON into buf:
//   {"pin_overflows":N,
//    "pids":{"<pid>":{"pinned_bytes":B,"pinned_objects":O,"pins":P,
//                     "creating_bytes":C,"creating_objects":M}, ...}}
// Returns bytes written (excluding NUL), or -1 if cap is too small.
int rts_pin_stats_json(void* handle, char* buf, int cap) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  struct Agg {
    int32_t pid;
    uint64_t pinned_bytes, pinned_objects, pins;
    uint64_t creating_bytes, creating_objects;
  };
  std::vector<Agg> aggs;
  auto agg_of = [&aggs](int32_t pid) -> Agg* {
    for (Agg& a : aggs)
      if (a.pid == pid) return &a;
    aggs.push_back({pid, 0, 0, 0, 0, 0});
    return &aggs.back();
  };
  Lock(h);
  uint64_t overflows = h->pin_overflows;
  for (uint32_t i = 0; i < kMaxObjects; i++) {
    Slot* s = &h->slots[i];
    if (s->state == SLOT_FREE || s->state == SLOT_TOMBSTONE) continue;
    if (s->state == SLOT_CREATED && s->owner_pid > 0) {
      Agg* a = agg_of(s->owner_pid);
      a->creating_bytes += s->alloc_size;
      a->creating_objects++;
    }
    for (int j = 0; j < kPinnersPerSlot; j++) {
      const PinRec& p = s->pinners[j];
      if (p.pid <= 0 || p.count <= 0) continue;
      Agg* a = agg_of(p.pid);
      a->pinned_bytes += s->alloc_size;
      a->pinned_objects++;
      a->pins += static_cast<uint64_t>(p.count);
    }
  }
  pthread_mutex_unlock(&h->mu);
  std::string out;
  char num[256];
  snprintf(num, sizeof(num), "{\"pin_overflows\":%llu,\"pids\":{",
           static_cast<unsigned long long>(overflows));
  out.append(num);
  bool first = true;
  for (const Agg& a : aggs) {
    if (!first) out.push_back(',');
    first = false;
    snprintf(num, sizeof(num),
             "\"%d\":{\"pinned_bytes\":%llu,\"pinned_objects\":%llu,"
             "\"pins\":%llu,\"creating_bytes\":%llu,"
             "\"creating_objects\":%llu}",
             a.pid, static_cast<unsigned long long>(a.pinned_bytes),
             static_cast<unsigned long long>(a.pinned_objects),
             static_cast<unsigned long long>(a.pins),
             static_cast<unsigned long long>(a.creating_bytes),
             static_cast<unsigned long long>(a.creating_objects));
    out.append(num);
  }
  out.append("}}");
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// ---------------------------------------------------------------------------
// Mutable objects (compiled-DAG channels) — seqlock protocol
// (reference: plasma client.h:98 ExperimentalMutableObjectWriteAcquire/
// Release; experimental/channel.py builds Channels on these).
// version is even when stable, odd while a write is in progress.
// ---------------------------------------------------------------------------

int rts_ch_create(void* handle, const uint8_t* id, uint64_t max_size,
                  uint64_t* offset_out) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  if (FindSlot(h, id, false)) { pthread_mutex_unlock(&h->mu); return -1; }
  uint64_t need = Align(max_size ? max_size : 1);
  uint64_t got = 0;
  uint64_t off = AllocOrEvictLocked(st, need, &got);
  if (!off) { pthread_mutex_unlock(&h->mu); return -2; }
  Slot* s = FindSlot(h, id, true);
  if (!s) { FreeLocked(st, off, got); pthread_mutex_unlock(&h->mu); return -3; }
  memcpy(s->id, id, kIdLen);
  s->state = SLOT_MUTABLE;
  s->offset = off;
  s->size = 0;
  s->alloc_size = got;
  s->pins = 0;
  s->version = 0;
  s->wake_seq = 0;
  memset(s->pinners, 0, sizeof(s->pinners));
  h->num_objects++;
  *offset_out = off;
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int rts_ch_write_acquire(void* handle, const uint8_t* id, uint64_t size,
                         uint64_t* offset_out) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_MUTABLE || size > s->alloc_size) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  __atomic_fetch_add(&s->version, 1, __ATOMIC_ACQ_REL);  // odd: writing
  s->size = size;
  *offset_out = s->offset;
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int rts_ch_write_release(void* handle, const uint8_t* id) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_MUTABLE || (s->version % 2) == 0) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  __atomic_fetch_add(&s->version, 1, __ATOMIC_ACQ_REL);  // even: stable
  __atomic_fetch_add(&s->wake_seq, 1, __ATOMIC_ACQ_REL);
  FutexWakeAll(&s->wake_seq);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Block until the channel's wake counter departs from `seen` (or
// timeout_ms elapses; negative = wait forever). Returns the current
// counter, or -1 if the channel is missing. Readers loop
// read→wait(seen)→read: `seen` is sampled from THIS call's return, so
// a write landing between the read and the wait flips the counter and
// FUTEX_WAIT returns immediately (no missed wakeup). The caller's
// ctypes FFI releases the GIL, so a blocked reader burns no CPU and
// the writer's wake hands the core straight over.
int64_t rts_ch_wait(void* handle, const uint8_t* id, uint32_t seen,
                    int timeout_ms) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_MUTABLE) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint32_t* addr = &s->wake_seq;  // slot table is stable storage
  pthread_mutex_unlock(&h->mu);
  uint32_t cur = __atomic_load_n(addr, __ATOMIC_ACQUIRE);
  if (cur == seen) {
    FutexWait(addr, seen, timeout_ms);
    cur = __atomic_load_n(addr, __ATOMIC_ACQUIRE);
  }
  return static_cast<int64_t>(cur);
}

// Snapshot read: returns version (even) + offset/size, or -1 if missing,
// -2 if a write is in progress (caller retries).
int64_t rts_ch_read(void* handle, const uint8_t* id, uint64_t* offset_out,
                    uint64_t* size_out) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  Slot* s = FindSlot(h, id, false);
  if (!s || s->state != SLOT_MUTABLE) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint64_t v = __atomic_load_n(&s->version, __ATOMIC_ACQUIRE);
  if (v % 2 == 1) { pthread_mutex_unlock(&h->mu); return -2; }
  *offset_out = s->offset;
  *size_out = s->size;
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(v);
}

// Test-only fault injection (crash-window coverage — reference: the
// plasma store's crash tests): allocate a span + an UNSEALED slot,
// poison the free-list head, then die WHILE HOLDING the arena mutex.
// The next peer to lock must take the EOWNERDEAD path and repair
// (RepairAfterOwnerDeath): recovered free list, tombstoned slot, no
// leaked capacity, no deadlock.
int rts_debug_die_locked(void* handle, const uint8_t* id, uint64_t size) {
  Store* st = reinterpret_cast<Store*>(handle);
  Header* h = st->hdr;
  Lock(h);
  uint64_t got = 0;
  uint64_t off = AllocOrEvictLocked(st, Align(size ? size : 1), &got);
  if (off) {
    Slot* s = FindSlot(h, id, true);
    if (s) {
      memcpy(s->id, id, kIdLen);
      s->state = SLOT_CREATED;  // never sealed: mid-write crash
      s->offset = off;
      s->size = size;
      s->alloc_size = got;
      s->pins = 0;
      s->owner_pid = 0;  // "creator unknown": repair reaps the slot
      s->owner_start = 0;
      memset(s->pinners, 0, sizeof(s->pinners));
      h->num_objects++;
    }
  }
  h->free_head = 12345;  // poison: repair must rebuild, not trust it
  _exit(42);             // mutex still held
}

}  // extern "C"
