// Native metrics registry — counters / gauges / histograms with
// Prometheus text exposition.
//
// Capability-equivalent of the reference's native stats layer
// (reference: src/ray/stats/metric.h:103 Metric/Gauge/Count/Histogram +
// metric_defs.cc, exported through the per-node agent to Prometheus via
// _private/metrics_agent.py). Process-global registry guarded by one
// mutex; Python binds via ctypes (ray_tpu/_native/metrics.py) and keeps
// tag validation / help text on its side, passing pre-rendered
// Prometheus label strings down.

#include <math.h>
#include <stdio.h>
#include <string.h>

#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

enum Kind { KIND_COUNTER = 0, KIND_GAUGE = 1, KIND_HISTOGRAM = 2 };

struct Series {
  Kind kind = KIND_COUNTER;
  double value = 0.0;                 // counter / gauge
  std::vector<double> bounds;         // histogram
  std::vector<uint64_t> buckets;      // size = bounds + 1 (+Inf)
  double sum = 0.0;
  uint64_t count = 0;
};

struct MetricMeta {
  Kind kind;
  std::string help;
};

std::mutex g_mu;
// (metric name, label string) -> series. std::map keeps exposition
// output deterministic.
std::map<std::pair<std::string, std::string>, Series> g_series;
std::map<std::string, MetricMeta> g_meta;

Series& series(const char* name, const char* labels, Kind kind) {
  auto key = std::make_pair(std::string(name),
                            std::string(labels ? labels : ""));
  Series& s = g_series[key];
  s.kind = kind;
  return s;
}

}  // namespace

extern "C" {

void rtm_declare(const char* name, int kind, const char* help) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_meta[name] = MetricMeta{static_cast<Kind>(kind),
                            help ? help : ""};
}

void rtm_counter_add(const char* name, const char* labels, double v) {
  if (v < 0) return;  // counters are monotone
  std::lock_guard<std::mutex> lock(g_mu);
  series(name, labels, KIND_COUNTER).value += v;
}

void rtm_gauge_set(const char* name, const char* labels, double v) {
  std::lock_guard<std::mutex> lock(g_mu);
  series(name, labels, KIND_GAUGE).value = v;
}

void rtm_series_remove(const char* name, const char* labels) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_series.erase(std::make_pair(std::string(name),
                                std::string(labels ? labels : "")));
}

void rtm_hist_observe(const char* name, const char* labels, double v,
                      const double* bounds, int nb) {
  std::lock_guard<std::mutex> lock(g_mu);
  Series& s = series(name, labels, KIND_HISTOGRAM);
  if (s.buckets.empty()) {
    s.bounds.assign(bounds, bounds + nb);
    s.buckets.assign(nb + 1, 0);
  }
  size_t i = 0;
  for (; i < s.bounds.size(); i++) {
    if (v <= s.bounds[i]) break;
  }
  s.buckets[i] += 1;
  s.sum += v;
  s.count += 1;
}

// Render the whole registry in Prometheus exposition format. Returns
// the number of bytes required (excluding NUL); writes up to cap-1
// bytes + NUL into buf. Call with cap=0 to size, then again.
long rtm_collect(char* buf, long cap) {
  std::lock_guard<std::mutex> lock(g_mu);
  std::string out;
  out.reserve(4096);
  std::string last_name;
  char line[512];
  for (const auto& [key, s] : g_series) {
    const std::string& name = key.first;
    const std::string& labels = key.second;
    if (name != last_name) {
      last_name = name;
      auto mit = g_meta.find(name);
      const char* type =
          s.kind == KIND_COUNTER ? "counter"
          : s.kind == KIND_GAUGE ? "gauge" : "histogram";
      if (mit != g_meta.end() && !mit->second.help.empty()) {
        out += "# HELP " + name + " " + mit->second.help + "\n";
      }
      out += "# TYPE " + name + " " + type + "\n";
    }
    auto wrap = [&](const std::string& extra) -> std::string {
      if (labels.empty() && extra.empty()) return "";
      if (labels.empty()) return "{" + extra + "}";
      if (extra.empty()) return "{" + labels + "}";
      return "{" + labels + "," + extra + "}";
    };
    if (s.kind == KIND_HISTOGRAM) {
      uint64_t cum = 0;
      for (size_t i = 0; i < s.bounds.size(); i++) {
        cum += s.buckets[i];
        snprintf(line, sizeof(line), "%.12g", s.bounds[i]);
        out += name + "_bucket" +
               wrap(std::string("le=\"") + line + "\"") + " " +
               std::to_string(cum) + "\n";
      }
      cum += s.buckets.empty() ? 0 : s.buckets.back();
      out += name + "_bucket" + wrap("le=\"+Inf\"") + " " +
             std::to_string(cum) + "\n";
      snprintf(line, sizeof(line), "%.12g", s.sum);
      out += name + "_sum" + wrap("") + " " + line + "\n";
      out += name + "_count" + wrap("") + " " +
             std::to_string(s.count) + "\n";
    } else {
      snprintf(line, sizeof(line), "%.12g", s.value);
      out += name + wrap("") + " " + line + "\n";
    }
  }
  // Declared-but-never-sampled metrics still expose HELP/TYPE (parity
  // with the python fallback; absent() alerting depends on it).
  for (const auto& [name, meta] : g_meta) {
    bool has_series = false;
    auto it = g_series.lower_bound(std::make_pair(name, std::string()));
    if (it != g_series.end() && it->first.first == name)
      has_series = true;
    if (has_series) continue;
    const char* type =
        meta.kind == KIND_COUNTER ? "counter"
        : meta.kind == KIND_GAUGE ? "gauge" : "histogram";
    if (!meta.help.empty())
      out += "# HELP " + name + " " + meta.help + "\n";
    out += "# TYPE " + name + " " + type + "\n";
  }
  long needed = static_cast<long>(out.size());
  if (buf != nullptr && cap > 0) {
    long n = needed < cap - 1 ? needed : cap - 1;
    memcpy(buf, out.data(), n);
    buf[n] = '\0';
  }
  return needed;
}

// Read back a single scalar series (tests / introspection).
// Returns 1 if found.
int rtm_read(const char* name, const char* labels, double* value) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_series.find(std::make_pair(
      std::string(name), std::string(labels ? labels : "")));
  if (it == g_series.end()) return 0;
  *value = it->second.kind == KIND_HISTOGRAM
               ? static_cast<double>(it->second.count)
               : it->second.value;
  return 1;
}

void rtm_reset() {
  std::lock_guard<std::mutex> lock(g_mu);
  g_series.clear();
  g_meta.clear();
}

}  // extern "C"
