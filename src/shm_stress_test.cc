// Sanitizer stress harness for the shm arena (reference: the C++ core's
// TSAN/ASAN CI coverage, SURVEY.md §5 — plasma store tested under
// sanitizers). Build via `make -C src sanitize` (asan + tsan variants)
// and run; any data race / heap error fails the process.
//
// Workload: N threads over ONE arena handle each (cross-"process" via
// separate rts_connect attachments), hammering create→write→seal→
// get(pin)→verify→release→delete with per-thread id spaces plus a
// shared id space for contention. The seqlock CHANNEL path is excluded
// here: its readers intentionally race the writer's buffer and resolve
// via version validation, which TSAN would flag by design.

#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

extern "C" {
void* rts_connect(const char* name, uint64_t capacity, int create);
void rts_disconnect(void* handle);
int rts_unlink(const char* name);
int rts_create(void* h, const uint8_t* id, uint64_t size, uint64_t* off);
int rts_seal(void* h, const uint8_t* id);
int rts_get(void* h, const uint8_t* id, uint64_t* off, uint64_t* size,
            int pin);
int rts_release(void* h, const uint8_t* id);
int rts_delete(void* h, const uint8_t* id);
uint8_t* rts_base(void* h);
}

namespace {

constexpr int kThreads = 8;
constexpr int kOps = 4000;
constexpr uint64_t kCapacity = 8ull << 20;

char g_name[64];

void make_id(uint8_t* id, int thread, int slot) {
  memset(id, 0, 28);
  id[0] = static_cast<uint8_t>(thread);
  memcpy(id + 1, &slot, sizeof(slot));
}

void* worker(void* arg) {
  long tid = reinterpret_cast<long>(arg);
  void* h = rts_connect(g_name, 0, 0);
  if (h == nullptr) {
    fprintf(stderr, "thread %ld: connect failed\n", tid);
    abort();
  }
  uint8_t* base = rts_base(h);
  unsigned seed = static_cast<unsigned>(tid) * 7919 + 13;
  for (int i = 0; i < kOps; i++) {
    int slot = rand_r(&seed) % 64;
    // Thread 0..5 use private id spaces; 6..7 contend on a shared one.
    int owner = (tid < 6) ? static_cast<int>(tid) : 99;
    uint8_t id[28];
    make_id(id, owner, slot);
    uint64_t off = 0, size = 0;
    int op = rand_r(&seed) % 4;
    if (op == 0) {
      uint64_t n = 64 + (rand_r(&seed) % 2048);
      if (rts_create(h, id, n, &off) == 0) {
        memset(base + off, static_cast<int>(id[0] ^ id[1]), n);
        if (rts_seal(h, id) != 0) {
          fprintf(stderr, "seal failed after create\n");
          abort();
        }
      }
    } else if (op == 1) {
      if (rts_get(h, id, &off, &size, 1) == 0) {
        uint8_t expect = static_cast<uint8_t>(id[0] ^ id[1]);
        for (uint64_t j = 0; j < size; j += 97) {
          if (base[off + j] != expect) {
            fprintf(stderr, "payload corruption at %lu\n",
                    static_cast<unsigned long>(off + j));
            abort();
          }
        }
        rts_release(h, id);
      }
    } else if (op == 2) {
      rts_delete(h, id);  // -2 (pinned) and -1 (missing) are fine
    } else {
      uint64_t ignored_off = 0, ignored_sz = 0;
      rts_get(h, id, &ignored_off, &ignored_sz, 0);
    }
  }
  rts_disconnect(h);
  return nullptr;
}

}  // namespace

int main() {
  snprintf(g_name, sizeof(g_name), "/rts_stress_%d", getpid());
  void* h = rts_connect(g_name, kCapacity, 1);
  if (h == nullptr) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  pthread_t threads[kThreads];
  for (long t = 0; t < kThreads; t++)
    pthread_create(&threads[t], nullptr, worker,
                   reinterpret_cast<void*>(t));
  for (int t = 0; t < kThreads; t++)
    pthread_join(threads[t], nullptr);
  rts_disconnect(h);
  rts_unlink(g_name);
  printf("OK %d threads x %d ops\n", kThreads, kOps);
  return 0;
}
