// Sanitizer stress harness for the RELAY path of the object-transfer
// plane: daemons that are mid-pull serve committed chunks onward, so a
// broadcast forms a tree instead of a star (see serve_pull2's relay
// branch in object_transfer.cc). Build + run via `make -C src asan`
// / `make -C src tsan`.
//
// Topology (all loopback, in-process): one producer arena seeds
// multi-chunk objects; two relay nodes pull them through their own
// PullManagers while four consumers concurrently pull the SAME ids
// from the relays — racing the relays' in-flight pulls so serve_pull2
// alternates between the sealed fast path and relay_acquire_reader.
// Chaos on top:
//  - relay submissions list a dead endpoint first (fallback path);
//  - a disruptor opens OP_PULL2 streams against relay 1, reads a few
//    bytes, and slams the connection shut (reader teardown while the
//    relay entry is still filling);
//  - a stopper kills the producer's server mid-traffic, so relays see
//    src_failed and their downstream readers get kErrFrame, forcing
//    consumers onto the surviving relay (multi-source fallback).
// Every successful consumer pull is integrity-checked byte-for-byte.

#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <arpa/inet.h>
#include <netinet/in.h>

extern "C" {
void* rts_connect(const char* name, uint64_t capacity, int create);
void rts_disconnect(void* handle);
int rts_unlink(const char* name);
int rts_create(void* h, const uint8_t* id, uint64_t size, uint64_t* off);
int rts_seal(void* h, const uint8_t* id);
int rts_get(void* h, const uint8_t* id, uint64_t* off, uint64_t* size,
            int pin);
int rts_release(void* h, const uint8_t* id);
uint8_t* rts_base(void* h);
void* rto_serve(const char* shm, uint64_t cap, int port, int bind_all);
int rto_port(void* h);
void rto_stop(void* h);
void rto_serve_stats(void* h, uint64_t* bytes_out, uint64_t* relay_served);
void* rtp_start(const char* shm, uint64_t budget, int workers,
                int timeout_ms, int retries);
uint64_t rtp_submit_multi(void* h, uint64_t requester,
                          const char* endpoints, const uint8_t* id);
int rtp_wait(void* h, uint64_t ticket, int timeout_ms);
void rtp_stop(void* h);
}

namespace {

constexpr int kObjects = 10;
constexpr int kRelays = 2;
constexpr int kConsumers = 4;
// Multi-chunk objects (chunk = 4 MiB): 5..8 MiB so every pull streams
// at least two frames and relays spend real time mid-pull.
constexpr uint64_t kMinObj = 5ull << 20;

char g_producer[64];
char g_relay[kRelays][64];
char g_cons[kConsumers][64];
int g_producer_port = 0;
int g_relay_port[kRelays];
void* g_relay_mgr[kRelays];
void* g_cons_mgr[kConsumers];
uint64_t g_obj_size[kObjects];

void make_id(uint8_t* id, int tag) {
  memset(id, 0, 28);
  memcpy(id, &tag, sizeof(tag));
}

uint8_t pattern_byte(int tag, uint64_t i) {
  return static_cast<uint8_t>((tag * 131 + i * 2654435761ull) & 0xff);
}

// Relay node: pull every object from {dead endpoint, producer}. After
// the stopper kills the producer these legitimately fail (-1/-3).
void* relay_puller(void* arg) {
  long r = reinterpret_cast<long>(arg);
  unsigned seed = static_cast<unsigned>(r) * 7919 + 11;
  char eps[128];
  snprintf(eps, sizeof(eps), "127.0.0.1:1,127.0.0.1:%d",
           g_producer_port);
  for (int i = 0; i < kObjects; i++) {
    uint8_t id[28];
    make_id(id, (i + static_cast<int>(r) * 3) % kObjects);
    uint64_t t = rtp_submit_multi(g_relay_mgr[r], 100 + r, eps, id);
    if (t == 0) abort();
    int rc = rtp_wait(g_relay_mgr[r], t, 60000);
    if (rc != 0 && rc != -1 && rc != -2 && rc != -3 && rc != -6) {
      fprintf(stderr, "relay pull rc=%d\n", rc);
      abort();
    }
    if (rand_r(&seed) % 4 == 0) usleep(1000 * (rand_r(&seed) % 5));
  }
  return nullptr;
}

// Consumer: pull every object preferring the relays; verify payload.
void* consumer(void* arg) {
  long c = reinterpret_cast<long>(arg);
  unsigned seed = static_cast<unsigned>(c) * 31337 + 5;
  void* store = rts_connect(g_cons[c], 0, 0);
  if (store == nullptr) abort();
  char eps[192];
  snprintf(eps, sizeof(eps), "127.0.0.1:%d,127.0.0.1:%d,127.0.0.1:%d",
           g_relay_port[c % kRelays], g_relay_port[(c + 1) % kRelays],
           g_producer_port);
  for (int i = 0; i < kObjects; i++) {
    int tag = (i + static_cast<int>(c)) % kObjects;
    uint8_t id[28];
    make_id(id, tag);
    uint64_t t = rtp_submit_multi(g_cons_mgr[c], 200 + c, eps, id);
    if (t == 0) abort();
    int rc = rtp_wait(g_cons_mgr[c], t, 60000);
    if (rc != 0 && rc != -1 && rc != -2 && rc != -3 && rc != -6) {
      fprintf(stderr, "consumer pull rc=%d tag=%d\n", rc, tag);
      abort();
    }
    if (rc == 0) {
      uint64_t off = 0, size = 0;
      // pin: the payload scan below must not race an LRU eviction,
      // and the rts_release after it pairs with this pin
      if (rts_get(store, id, &off, &size, 1) != 0) abort();
      if (size != g_obj_size[tag]) abort();
      const uint8_t* base = rts_base(store);
      for (uint64_t j = 0; j < size; j += 4093)
        if (base[off + j] != pattern_byte(tag, j)) {
          fprintf(stderr, "payload corrupt tag=%d at %llu\n", tag,
                  static_cast<unsigned long long>(j));
          abort();
        }
      rts_release(store, id);
    }
    if (rand_r(&seed) % 3 == 0) usleep(1000 * (rand_r(&seed) % 3));
  }
  rts_disconnect(store);
  return nullptr;
}

// Disruptor: open a raw OP_PULL2 stream against relay 1, read only the
// header + a sliver of the first frame, then RST the connection —
// tearing a relay reader down while the entry may still be filling.
void* disruptor(void*) {
  unsigned seed = 99;
  for (int i = 0; i < 40; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons(static_cast<uint16_t>(g_relay_port[0]));
    inet_pton(AF_INET, "127.0.0.1", &a.sin_addr);
    if (connect(fd, reinterpret_cast<sockaddr*>(&a), sizeof(a)) == 0) {
      uint8_t req[29];
      req[0] = 4;  // OP_PULL2
      make_id(req + 1, rand_r(&seed) % kObjects);
      if (write(fd, req, sizeof(req)) == sizeof(req)) {
        char sink[512];
        (void)!read(fd, sink, sizeof(sink));
      }
      struct linger lg {1, 0};  // RST on close
      setsockopt(fd, SOL_SOCKET, SO_LINGER, &lg, sizeof(lg));
    }
    close(fd);
    usleep(1000 * (rand_r(&seed) % 8));
  }
  return nullptr;
}

// Stopper: kill the producer's server mid-traffic. In-flight relay
// pulls observe src_failed; their downstream readers get kErrFrame
// and fall back to the other relay.
void* stopper(void* arg) {
  usleep(150 * 1000);
  rto_stop(arg);
  return nullptr;
}

}  // namespace

int main() {
  snprintf(g_producer, sizeof(g_producer), "/rto_relay_p_%d",
           getpid());
  void* prod = rts_connect(g_producer, 128ull << 20, 1);
  if (prod == nullptr) return 1;
  uint8_t* base = rts_base(prod);
  unsigned seed = 2;
  for (int i = 0; i < kObjects; i++) {
    uint8_t id[28];
    make_id(id, i);
    uint64_t off = 0;
    uint64_t n = kMinObj + (rand_r(&seed) % (3ull << 20));
    g_obj_size[i] = n;
    if (rts_create(prod, id, n, &off) != 0) return 1;
    for (uint64_t j = 0; j < n; j++)
      base[off + j] = pattern_byte(i, j);
    rts_seal(prod, id);
  }
  void* srv_prod = rto_serve(g_producer, 0, 0, 0);
  if (srv_prod == nullptr) return 1;
  g_producer_port = rto_port(srv_prod);

  void* relay_store[kRelays];
  void* srv_relay[kRelays];
  for (int r = 0; r < kRelays; r++) {
    snprintf(g_relay[r], sizeof(g_relay[r]), "/rto_relay_r%d_%d", r,
             getpid());
    relay_store[r] = rts_connect(g_relay[r], 128ull << 20, 1);
    if (relay_store[r] == nullptr) return 1;
    srv_relay[r] = rto_serve(g_relay[r], 0, 0, 0);
    if (srv_relay[r] == nullptr) return 1;
    g_relay_port[r] = rto_port(srv_relay[r]);
    g_relay_mgr[r] = rtp_start(g_relay[r], 32ull << 20, 3, 10000, 1);
    if (g_relay_mgr[r] == nullptr) return 1;
  }
  void* cons_store[kConsumers];
  for (int c = 0; c < kConsumers; c++) {
    snprintf(g_cons[c], sizeof(g_cons[c]), "/rto_relay_c%d_%d", c,
             getpid());
    cons_store[c] = rts_connect(g_cons[c], 128ull << 20, 1);
    if (cons_store[c] == nullptr) return 1;
    g_cons_mgr[c] = rtp_start(g_cons[c], 32ull << 20, 3, 10000, 1);
    if (g_cons_mgr[c] == nullptr) return 1;
  }

  pthread_t threads[kRelays + kConsumers + 2];
  int t = 0;
  for (long r = 0; r < kRelays; r++)
    pthread_create(&threads[t++], nullptr, relay_puller,
                   reinterpret_cast<void*>(r));
  for (long c = 0; c < kConsumers; c++)
    pthread_create(&threads[t++], nullptr, consumer,
                   reinterpret_cast<void*>(c));
  pthread_create(&threads[t++], nullptr, disruptor, nullptr);
  pthread_create(&threads[t++], nullptr, stopper, srv_prod);
  for (int i = 0; i < t; i++) pthread_join(threads[i], nullptr);

  uint64_t relay_served_total = 0;
  for (int r = 0; r < kRelays; r++) {
    uint64_t out = 0, served = 0;
    rto_serve_stats(srv_relay[r], &out, &served);
    relay_served_total += served;
  }

  // Stop with work still queued on a relay manager (stop-path races).
  for (int i = 0; i < 8; i++) {
    uint8_t id[28];
    make_id(id, i);
    char eps[64];
    snprintf(eps, sizeof(eps), "127.0.0.1:%d", g_relay_port[1]);
    rtp_submit_multi(g_relay_mgr[0], 999, eps, id);
  }
  for (int r = 0; r < kRelays; r++) rtp_stop(g_relay_mgr[r]);
  for (int c = 0; c < kConsumers; c++) rtp_stop(g_cons_mgr[c]);
  for (int r = 0; r < kRelays; r++) rto_stop(srv_relay[r]);
  for (int r = 0; r < kRelays; r++) rts_disconnect(relay_store[r]);
  for (int c = 0; c < kConsumers; c++) rts_disconnect(cons_store[c]);
  rts_disconnect(prod);
  rts_unlink(g_producer);
  for (int r = 0; r < kRelays; r++) rts_unlink(g_relay[r]);
  for (int c = 0; c < kConsumers; c++) rts_unlink(g_cons[c]);
  printf("OK relay stress (relay_served=%llu)\n",
         static_cast<unsigned long long>(relay_served_total));
  return 0;
}
