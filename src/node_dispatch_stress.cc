// Stress harness for the native dispatch plane (node_dispatch.cc),
// built in-process under ASAN and TSAN (see src/Makefile).
//
// Shape (mirrors shm_stress_test / transfer_stress_test): responder
// threads drain the ready queue like the daemon's drainer pool while
// valid clients push JSON pings, hybrid admission frames and opaque
// frames — concurrently with hostile clients (mid-frame disconnects,
// oversized frames, slow-loris dribble) and a config thread hammering
// the ledger / load-tail / peers / stats surfaces the heartbeat and
// handlers touch from other threads. Three full create→stop→destroy
// cycles stress lifecycle teardown with events still queued.
//
// The native hand-off plane rides the same cycles: fake workers on
// socketpairs (registered via nd_worker_register, answering framed
// task bodies like worker_main's serve loop) absorb plain-task
// frames end-to-end with no responder involvement, a checkout-churn
// thread races nd_worker_acquire/nd_worker_release against the
// loop's own hand-off picks (the daemon's cold-path pool analog),
// and one worker is wired to die mid-task — the driver connection
// must still get exactly one (crashed) reply and Python must see the
// typed worker-dead event.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* nd_create(int port, int bind_all, unsigned long long max_frame,
                int queue_cap);
int nd_port(void* h);
int nd_start(void* h);
int nd_next(void* h, int timeout_ms, unsigned long long* conn_id,
            int* kind, unsigned int* flags, char** data,
            unsigned long long* len);
void nd_free(char* data);
int nd_send(void* h, unsigned long long conn_id, const char* data,
            unsigned long long len);
void nd_set_node_id(void* h, const char* node_id);
void nd_set_load_tail(void* h, const char* tail);
int nd_set_peers_json(void* h, const char* json);
void nd_set_ping_native(void* h, int enabled);
int nd_ledger_set(void* h, const char* json_res);
int nd_ledger_try_charge(void* h, const char* json_res);
int nd_ledger_charge(void* h, const char* json_res);
int nd_ledger_release(void* h, const char* json_res);
int nd_ledger_get(void* h, char* buf, int cap);
unsigned long long nd_spilled(void* h);
int nd_stats_json(void* h, char* buf, int cap);
int nd_worker_register(void* h, unsigned long long wid, int fd, int pid,
                       const char* fids_csv);
int nd_worker_unregister(void* h, unsigned long long wid);
long long nd_worker_acquire(void* h, int timeout_ms);
int nd_worker_release(void* h, unsigned long long wid,
                      const char* fids_csv);
int nd_workers_json(void* h, char* buf, int cap);
int nd_handoff_json(void* h, char* buf, int cap);
void nd_stop(void* h);
void nd_destroy(void* h);
}

namespace {

constexpr unsigned kFlagPrecharged = 1;
constexpr int kEvClosed = 1;
constexpr int kEvWorkerDead = 2;
constexpr unsigned long long kMaxFrame = 1ull << 20;

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

int dial(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    close(fd);
    return -1;
  }
  return fd;
}

std::string frame(const std::string& payload) {
  std::string out;
  uint64_t n = payload.size();
  for (int i = 7; i >= 0; i--)
    out.push_back(static_cast<char>((n >> (8 * i)) & 0xff));
  out.append(payload);
  return out;
}

// 0x01 | u32-LE header len | JSON header | body (client.hybrid_frame).
std::string hybrid(const std::string& header, const std::string& body) {
  std::string payload;
  payload.push_back(0x01);
  uint32_t hlen = static_cast<uint32_t>(header.size());
  payload.append(reinterpret_cast<const char*>(&hlen), 4);
  payload.append(header);
  payload.append(body);
  return frame(payload);
}

bool read_reply(int fd, std::string* out) {
  unsigned char hdr[8];
  if (!read_all(fd, hdr, 8)) return false;
  uint64_t n = 0;
  for (int i = 0; i < 8; i++) n = (n << 8) | hdr[i];
  if (n > kMaxFrame) return false;
  out->resize(n);
  return read_all(fd, out->empty() ? nullptr : &(*out)[0], n);
}

struct Counters {
  std::atomic<uint64_t> pongs{0};
  std::atomic<uint64_t> admitted{0};
  std::atomic<uint64_t> refused{0};
  std::atomic<uint64_t> echoes{0};
  std::atomic<uint64_t> closes_seen{0};
  std::atomic<uint64_t> native_ok{0};
  std::atomic<uint64_t> crashed{0};
  std::atomic<uint64_t> cold{0};
  std::atomic<uint64_t> worker_dead{0};
};

// The daemon's drainer-pool analog: pop events, release admission
// charges, echo message bodies back as the "reply".
void responder(void* h, Counters* ctr) {
  for (;;) {
    unsigned long long conn_id = 0, len = 0;
    int kind = 0;
    unsigned flags = 0;
    char* data = nullptr;
    int rc = nd_next(h, 50, &conn_id, &kind, &flags, &data, &len);
    if (rc < 0) return;  // stopped
    if (rc == 0) continue;
    if (kind == kEvClosed) {
      ctr->closes_seen.fetch_add(1);
      continue;
    }
    if (kind == kEvWorkerDead) {
      // conn_id carries the worker id; the daemon discards + respawns
      // here. Counting it proves the typed event reaches Python.
      ctr->worker_dead.fetch_add(1);
      continue;
    }
    if ((flags & kFlagPrecharged) != 0) {
      nd_ledger_release(h, "{\"CPU\": 1.0}");
      ctr->admitted.fetch_add(1);
    }
    std::string reply(data, static_cast<size_t>(len));
    nd_free(data);
    nd_send(h, conn_id, reply.data(), reply.size());
  }
}

// A fake worker process on one end of a socketpair: reads framed task
// bodies (the loop's start_native_task forwards the pickle verbatim
// under a fresh length prefix) and answers each with a framed result,
// like worker_main's serve loop. The socket stays BLOCKING — the
// daemon's Python side never sets O_NONBLOCK on its copy, and the
// loop's dup shares file-status flags, so this mirrors production.
// die_after >= 0 injects a mid-task death: read the frame, then close
// without replying.
void fake_worker(int fd, int die_after, std::atomic<uint64_t>* served) {
  int answered = 0;
  for (;;) {
    std::string task;
    if (!read_reply(fd, &task)) break;
    if (die_after >= 0 && answered >= die_after) break;
    std::string reply = frame(
        "{\"type\": \"result\", \"tid\": \"ab12\", "
        "\"marker\": \"native-ok\"}");
    if (!write_all(fd, reply.data(), reply.size())) break;
    answered++;
    served->fetch_add(1);
  }
  close(fd);
}

// Plain-task client: every frame is hand-off eligible, so the common
// case is a fake worker's reply forwarded with zero responder
// involvement. Cold fall-through (all workers checked out or pending
// overflow) gets the responder's body echo instead — one reply either
// way, so the serial protocol holds under both paths.
void native_client(int port, int rounds, Counters* ctr) {
  int fd = dial(port);
  if (fd < 0) return;
  const std::string hdr =
      "{\"type\": \"task\", \"tid\": \"ab12\", \"plain\": true, "
      "\"fid\": \"cafe\", \"has_fn\": true, "
      "\"res\": {\"CPU\": 1.0}, \"spillable\": true}";
  for (int i = 0; i < rounds; i++) {
    std::string body(48 + (i % 32), static_cast<char>(0x81));
    std::string t = hybrid(hdr, body);
    std::string reply;
    if (!write_all(fd, t.data(), t.size()) || !read_reply(fd, &reply))
      break;
    if (reply.find("native-ok") != std::string::npos)
      ctr->native_ok.fetch_add(1);
    else if (reply.find("crashed") != std::string::npos)
      ctr->crashed.fetch_add(1);
    else if (reply == body ||
             reply.find("\"spillback\"") != std::string::npos)
      ctr->cold.fetch_add(1);
  }
  close(fd);
}

// Targets the death-wired worker (unique fid → fid-warm preference
// picks it whenever idle) until the crash surfaces: the worker dies
// mid-task and the driver connection must still get exactly one
// reply, typed crashed, with the ledger charge released.
void death_client(int port, Counters* ctr) {
  int fd = dial(port);
  if (fd < 0) return;
  const std::string hdr =
      "{\"type\": \"task\", \"tid\": \"ab12\", \"plain\": true, "
      "\"fid\": \"dead\", \"has_fn\": true, "
      "\"res\": {\"CPU\": 1.0}, \"spillable\": true}";
  for (int i = 0; i < 200 && ctr->crashed.load() == 0; i++) {
    std::string body(32, static_cast<char>(0x82));
    std::string t = hybrid(hdr, body);
    std::string reply;
    if (!write_all(fd, t.data(), t.size()) || !read_reply(fd, &reply))
      break;
    if (reply.find("crashed") != std::string::npos)
      ctr->crashed.fetch_add(1);
    else if (reply.find("native-ok") != std::string::npos)
      ctr->native_ok.fetch_add(1);
    else if (reply == body ||
             reply.find("\"spillback\"") != std::string::npos)
      ctr->cold.fetch_add(1);
  }
  close(fd);
}

// The daemon's cold-path pool analog: check workers out of the native
// registry (py-owned, epoll-DELed) and hand them back, racing the
// loop's own hand-off picks and the injected death.
void checkout_churn(void* h, std::atomic<bool>* done) {
  while (!done->load()) {
    long long wid = nd_worker_acquire(h, 5);
    if (wid == -2) return;  // stopped
    if (wid >= 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      nd_worker_release(h, static_cast<unsigned long long>(wid),
                        "cafe");
    }
  }
}

void valid_client(int port, int rounds, Counters* ctr) {
  int fd = dial(port);
  if (fd < 0) return;
  const std::string task_hdr =
      "{\"type\": \"task\", \"tid\": \"ab12\", "
      "\"res\": {\"CPU\": 1.0}, \"spillable\": true, "
      "\"exclude\": [\"node-x\"]}";
  for (int i = 0; i < rounds; i++) {
    std::string reply;
    // Natively-answered ping.
    std::string ping = frame("{\"type\": \"ping\"}");
    if (!write_all(fd, ping.data(), ping.size()) ||
        !read_reply(fd, &reply))
      break;
    if (reply.find("\"pong\"") != std::string::npos)
      ctr->pongs.fetch_add(1);
    // Hybrid admission frame: either charged + echoed by a responder
    // or refused natively with a spillback reply — one reply either
    // way, so the serial protocol holds.
    std::string body(64 + (i % 64), static_cast<char>(0x80));
    std::string t = hybrid(task_hdr, body);
    if (!write_all(fd, t.data(), t.size()) || !read_reply(fd, &reply))
      break;
    if (reply.find("\"spillback\"") != std::string::npos)
      ctr->refused.fetch_add(1);
    else if (reply == body)
      ctr->echoes.fetch_add(1);
    // Opaque frame → straight passthrough echo.
    std::string op = frame(std::string(32, '\x02'));
    if (!write_all(fd, op.data(), op.size()) || !read_reply(fd, &reply))
      break;
    if (reply == std::string(32, '\x02')) ctr->echoes.fetch_add(1);
  }
  close(fd);
}

void midframe_disconnector(int port, int rounds) {
  for (int i = 0; i < rounds; i++) {
    int fd = dial(port);
    if (fd < 0) return;
    // Partial header, partial payload, or header promising more bytes
    // than ever arrive — then vanish.
    std::string full = frame("{\"type\": \"ping\"}");
    size_t cut = 1 + static_cast<size_t>(i) % (full.size() - 1);
    write_all(fd, full.data(), cut);
    close(fd);
  }
}

void oversize_sender(int port, int rounds) {
  for (int i = 0; i < rounds; i++) {
    int fd = dial(port);
    if (fd < 0) return;
    uint64_t n = kMaxFrame + 1 + static_cast<uint64_t>(i);
    unsigned char hdr[8];
    for (int b = 7; b >= 0; b--) {
      hdr[7 - b] = static_cast<unsigned char>((n >> (8 * b)) & 0xff);
    }
    write_all(fd, hdr, 8);
    // The loop must close on the header alone; reading EOF proves it.
    char c;
    read(fd, &c, 1);
    close(fd);
  }
}

void slow_loris(int port, std::atomic<bool>* done) {
  int fd = dial(port);
  if (fd < 0) return;
  std::string full = frame("{\"type\": \"ping\"}");
  size_t off = 0;
  while (!done->load() && off < full.size()) {
    write_all(fd, full.data() + off, 1);
    off++;
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  close(fd);
}

// Heartbeat analog: hammer every config/ledger/stats surface the
// Python side touches while the loop thread reads them.
void config_churn(void* h, std::atomic<bool>* done) {
  int i = 0;
  char buf[1 << 16];
  while (!done->load()) {
    nd_set_load_tail(h, "\"queued\": 0, \"running\": 1}");
    nd_set_peers_json(
        h,
        "[{\"id\": \"peer-a\", \"queued\": 1, \"headroom\": 0.5, "
        "\"avail\": {\"CPU\": 2.0}}, "
        "{\"id\": \"peer-b\", \"queued\": 0, \"headroom\": 0.25, "
        "\"avail\": {\"CPU\": 1.0}}]");
    if (nd_ledger_try_charge(h, "{\"CPU\": 0.5}") == 1)
      nd_ledger_release(h, "{\"CPU\": 0.5}");
    if (i % 4 == 0 && nd_ledger_try_charge(h, "{\"CPU\": 3.5}") == 1) {
      // Hold nearly the whole ledger briefly: concurrent admission
      // frames race into the native refusal path.
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
      nd_ledger_release(h, "{\"CPU\": 3.5}");
    }
    nd_ledger_get(h, buf, sizeof(buf));
    nd_stats_json(h, buf, sizeof(buf));
    nd_workers_json(h, buf, sizeof(buf));
    nd_handoff_json(h, buf, sizeof(buf));
    nd_spilled(h);
    nd_set_ping_native(h, (i++ % 8) != 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  nd_set_ping_native(h, 1);
}

int run_cycle(int cycle) {
  // Small queue cap so backpressure pausing gets exercised too.
  void* h = nd_create(0, 0, kMaxFrame, 64);
  if (h == nullptr) {
    fprintf(stderr, "nd_create failed\n");
    return 1;
  }
  nd_set_node_id(h, "stress-node");
  nd_ledger_set(h, "{\"CPU\": 4.0}");
  nd_set_load_tail(h, "\"queued\": 0}");
  if (nd_start(h) != 0) {
    fprintf(stderr, "nd_start failed\n");
    return 1;
  }
  int port = nd_port(h);

  Counters ctr;
  std::atomic<bool> done{false};

  // Native hand-off plane: fake workers on socketpairs, the daemon's
  // end registered with the loop (which dups it, like production
  // against the pool's Python-held sockets). Worker 2 is wired to
  // die after two replies; it registers an extra fid so the death
  // client can target it through fid-warm preference.
  std::atomic<uint64_t> served{0};
  std::vector<std::thread> workers;
  std::vector<int> wfds;
  for (int i = 0; i < 3; i++) {
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
      fprintf(stderr, "socketpair failed\n");
      return 1;
    }
    int die_after = (i == 2) ? 2 : -1;
    workers.emplace_back(fake_worker, sv[0], die_after, &served);
    const char* fids = (i == 2) ? "cafe,dead" : "cafe";
    if (nd_worker_register(h, static_cast<unsigned long long>(i),
                           sv[1], 1000 + i, fids) != 0) {
      fprintf(stderr, "nd_worker_register failed\n");
      return 1;
    }
    wfds.push_back(sv[1]);
  }

  std::vector<std::thread> threads;
  threads.emplace_back(responder, h, &ctr);
  threads.emplace_back(responder, h, &ctr);
  threads.emplace_back(config_churn, h, &done);
  threads.emplace_back(checkout_churn, h, &done);
  std::vector<std::thread> clients;
  for (int i = 0; i < 4; i++)
    clients.emplace_back(valid_client, port, 40, &ctr);
  for (int i = 0; i < 3; i++)
    clients.emplace_back(native_client, port, 40, &ctr);
  clients.emplace_back(death_client, port, &ctr);
  clients.emplace_back(midframe_disconnector, port, 20);
  clients.emplace_back(oversize_sender, port, 10);
  clients.emplace_back(slow_loris, port, &done);

  for (size_t i = 0; i + 1 < clients.size(); i++) clients[i].join();
  done.store(true);
  clients.back().join();

  // The worker-dead event is queued at death time (before the death
  // client's crashed reply is even read); give the responders a
  // bounded window to pop it before stop.
  for (int i = 0; i < 200 && ctr.worker_dead.load() == 0; i++)
    std::this_thread::sleep_for(std::chrono::milliseconds(5));

  char hjson[512];
  hjson[0] = '\0';
  nd_handoff_json(h, hjson, sizeof(hjson));

  // Unregister before stop (the live path); the dead worker's id is
  // already gone, so its unregister exercising the unknown-wid return
  // is deliberate. Closing our fd copies after unregister drops the
  // last reference and EOFs the fake workers.
  for (int i = 0; i < 3; i++)
    nd_worker_unregister(h, static_cast<unsigned long long>(i));
  for (int fd : wfds) close(fd);
  for (auto& w : workers) w.join();

  // Stop with the responders possibly mid-nd_next and with whatever
  // the loris left half-buffered: teardown must free it all.
  nd_stop(h);
  for (auto& t : threads) t.join();
  nd_destroy(h);

  uint64_t pongs = ctr.pongs.load();
  uint64_t handled = ctr.admitted.load() + ctr.refused.load();
  uint64_t echoes = ctr.echoes.load();
  printf("cycle %d: pongs=%llu admitted=%llu refused=%llu echoes=%llu "
         "closes=%llu native_ok=%llu crashed=%llu cold=%llu "
         "worker_dead=%llu served=%llu handoff=%s\n",
         cycle, (unsigned long long)pongs,
         (unsigned long long)ctr.admitted.load(),
         (unsigned long long)ctr.refused.load(),
         (unsigned long long)echoes,
         (unsigned long long)ctr.closes_seen.load(),
         (unsigned long long)ctr.native_ok.load(),
         (unsigned long long)ctr.crashed.load(),
         (unsigned long long)ctr.cold.load(),
         (unsigned long long)ctr.worker_dead.load(),
         (unsigned long long)served.load(), hjson);
  // Hostile traffic must not have starved the valid clients: every
  // ping got a pong and every task frame was admitted or refused.
  if (pongs < 4 * 40 / 2 || handled == 0 || echoes == 0) {
    fprintf(stderr, "FAIL: valid traffic starved\n");
    return 1;
  }
  // The hand-off plane must have carried real traffic: warm-path
  // replies flowed, the injected death surfaced as a typed crashed
  // reply, and the worker-dead event reached the event queue.
  if (ctr.native_ok.load() == 0 || ctr.crashed.load() == 0 ||
      ctr.worker_dead.load() == 0) {
    fprintf(stderr, "FAIL: native hand-off plane not exercised\n");
    return 1;
  }
  return 0;
}

}  // namespace

int main() {
  for (int cycle = 0; cycle < 3; cycle++) {
    int rc = run_cycle(cycle);
    if (rc != 0) return rc;
  }
  printf("node_dispatch_stress: PASS\n");
  return 0;
}
