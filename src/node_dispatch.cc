// node_dispatch.cc — native dispatch front end for the node daemon.
//
// Grows control_plane.cc's single-threaded epoll substrate into the
// daemon's dispatch-socket hot loop (reference: the raylet keeps accept/
// frame/admission in C++ and calls Python only for policy,
// src/ray/raylet/node_manager.cc). The loop owns:
//
//   - accept + nonblocking conn lifecycle (one epoll thread, no
//     thread-per-connection, nothing here touches the GIL);
//   - wire framing: 8-byte big-endian length + payload, same protocol
//     the Python daemon speaks (worker_proc._LEN);
//   - payload classification: '{' = JSON message (cross-language
//     clients), 0x01 = hybrid frame (u32-LE header length + JSON
//     admission header + opaque cloudpickle body — the Python driver's
//     NodeConn emits these), anything else = opaque legacy pickle;
//   - task-queue admission: check-and-charge against the resource
//     ledger (same 1/10000 fixed-point model as core/resources.py) for
//     driver-marked spillable tasks, with the refusal reply — peer
//     redirect hint + authoritative load — written natively;
//   - "ping" answered natively from the Python-pushed load report;
//   - a bounded ready queue the Python side drains (nd_next), with
//     EPOLLIN backpressure when Python falls behind: paused conns stop
//     being read, so TCP pushes back on the drivers instead of the
//     queue growing without bound;
//   - per-(loop,handler) count/total/max/p95 latency stats (the
//     event_stats.h analog), measured from frame arrival to the first
//     reply byte queued for that request.
//
// Everything Python needs crosses a narrow C ABI (nd_*) loaded via
// ctypes — every call releases the GIL for its duration.

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

double seconds_since(Clock::time_point t0, Clock::time_point t1) {
  return std::chrono::duration<double>(t1 - t0).count();
}

// ---------------------------------------------------------------------
// Minimal JSON: enough for admission headers, resource dicts and the
// peer digest. Parses into a tagged value; no exceptions escape.
// ---------------------------------------------------------------------

struct JValue {
  enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  double num = 0.0;
  std::string str;
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;

  const JValue* get(const char* key) const {
    for (const auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
};

class JParser {
 public:
  JParser(const char* p, size_t n) : p_(p), end_(p + n) {}

  bool parse(JValue* out) {
    skip_ws();
    if (!value(out)) return false;
    skip_ws();
    return p_ == end_;
  }

 private:
  const char* p_;
  const char* end_;

  void skip_ws() {
    while (p_ < end_ && (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' ||
                         *p_ == '\r'))
      p_++;
  }

  bool lit(const char* s, size_t n) {
    if (static_cast<size_t>(end_ - p_) < n || memcmp(p_, s, n) != 0)
      return false;
    p_ += n;
    return true;
  }

  bool value(JValue* out) {
    skip_ws();
    if (p_ >= end_) return false;
    switch (*p_) {
      case '{':
        return object(out);
      case '[':
        return array(out);
      case '"':
        out->kind = JValue::STR;
        return string(&out->str);
      case 't':
        out->kind = JValue::BOOL;
        out->b = true;
        return lit("true", 4);
      case 'f':
        out->kind = JValue::BOOL;
        out->b = false;
        return lit("false", 5);
      case 'n':
        out->kind = JValue::NUL;
        return lit("null", 4);
      default:
        return number(out);
    }
  }

  bool number(JValue* out) {
    const char* start = p_;
    if (p_ < end_ && (*p_ == '-' || *p_ == '+')) p_++;
    while (p_ < end_ && ((*p_ >= '0' && *p_ <= '9') || *p_ == '.' ||
                         *p_ == 'e' || *p_ == 'E' || *p_ == '-' ||
                         *p_ == '+'))
      p_++;
    if (p_ == start) return false;
    std::string tmp(start, p_ - start);
    char* endp = nullptr;
    out->num = strtod(tmp.c_str(), &endp);
    out->kind = JValue::NUM;
    return endp == tmp.c_str() + tmp.size();
  }

  bool hex4(unsigned* out) {
    if (end_ - p_ < 4) return false;
    unsigned v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p_[i];
      v <<= 4;
      if (c >= '0' && c <= '9')
        v |= static_cast<unsigned>(c - '0');
      else if (c >= 'a' && c <= 'f')
        v |= static_cast<unsigned>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F')
        v |= static_cast<unsigned>(c - 'A' + 10);
      else
        return false;
    }
    p_ += 4;
    *out = v;
    return true;
  }

  void append_utf8(std::string* s, unsigned cp) {
    if (cp < 0x80) {
      s->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      s->push_back(static_cast<char>(0xC0 | (cp >> 6)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(static_cast<char>(0xE0 | (cp >> 12)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(static_cast<char>(0xF0 | (cp >> 18)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  bool string(std::string* out) {
    if (p_ >= end_ || *p_ != '"') return false;
    p_++;
    out->clear();
    while (p_ < end_) {
      char c = *p_++;
      if (c == '"') return true;
      if (c != '\\') {
        out->push_back(c);
        continue;
      }
      if (p_ >= end_) return false;
      char e = *p_++;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          unsigned cp = 0;
          if (!hex4(&cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF && end_ - p_ >= 6 &&
              p_[0] == '\\' && p_[1] == 'u') {
            p_ += 2;
            unsigned lo = 0;
            if (!hex4(&lo)) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          }
          append_utf8(out, cp);
          break;
        }
        default:
          return false;
      }
    }
    return false;
  }

  bool array(JValue* out) {
    out->kind = JValue::ARR;
    p_++;  // '['
    skip_ws();
    if (p_ < end_ && *p_ == ']') {
      p_++;
      return true;
    }
    while (p_ < end_) {
      out->arr.emplace_back();
      if (!value(&out->arr.back())) return false;
      skip_ws();
      if (p_ < end_ && *p_ == ',') {
        p_++;
        continue;
      }
      if (p_ < end_ && *p_ == ']') {
        p_++;
        return true;
      }
      return false;
    }
    return false;
  }

  bool object(JValue* out) {
    out->kind = JValue::OBJ;
    p_++;  // '{'
    skip_ws();
    if (p_ < end_ && *p_ == '}') {
      p_++;
      return true;
    }
    while (p_ < end_) {
      skip_ws();
      std::string key;
      if (!string(&key)) return false;
      skip_ws();
      if (p_ >= end_ || *p_ != ':') return false;
      p_++;
      out->obj.emplace_back(std::move(key), JValue());
      if (!value(&out->obj.back().second)) return false;
      skip_ws();
      if (p_ < end_ && *p_ == ',') {
        p_++;
        continue;
      }
      if (p_ < end_ && *p_ == '}') {
        p_++;
        return true;
      }
      return false;
    }
    return false;
  }
};

void json_escape(const std::string& s, std::string* out) {
  out->push_back('"');
  for (char c : s) {
    switch (c) {
      case '"': out->append("\\\""); break;
      case '\\': out->append("\\\\"); break;
      case '\n': out->append("\\n"); break;
      case '\r': out->append("\\r"); break;
      case '\t': out->append("\\t"); break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x",
                   static_cast<unsigned char>(c));
          out->append(buf);
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

void json_number(double v, std::string* out) {
  char buf[40];
  if (v == static_cast<int64_t>(v) && v > -1e15 && v < 1e15) {
    snprintf(buf, sizeof(buf), "%lld.0",
             static_cast<long long>(v));
  } else {
    snprintf(buf, sizeof(buf), "%.12g", v);
  }
  out->append(buf);
}

// ---------------------------------------------------------------------
// Resource ledger: 1/10000 fixed-point, exactly core/resources.py.
// ---------------------------------------------------------------------

constexpr int64_t kGranularity = 10000;

int64_t to_fixed(double v) {
  return static_cast<int64_t>(v * kGranularity + (v >= 0 ? 0.5 : -0.5));
}

using ResMap = std::map<std::string, int64_t>;

bool parse_res(const JValue& obj, ResMap* out) {
  if (obj.kind != JValue::OBJ) return false;
  for (const auto& kv : obj.obj) {
    if (kv.second.kind != JValue::NUM) return false;
    int64_t f = to_fixed(kv.second.num);
    if (f != 0) (*out)[kv.first] = f;
  }
  return true;
}

bool parse_res_str(const char* s, ResMap* out) {
  if (s == nullptr) return false;
  JValue v;
  JParser p(s, strlen(s));
  if (!p.parse(&v)) return false;
  return parse_res(v, out);
}

void res_to_json(const ResMap& r, std::string* out) {
  out->push_back('{');
  bool first = true;
  for (const auto& kv : r) {
    if (kv.second == 0) continue;  // to_dict() drops zero entries
    if (!first) out->push_back(',');
    first = false;
    json_escape(kv.first, out);
    out->push_back(':');
    json_number(static_cast<double>(kv.second) / kGranularity, out);
  }
  out->push_back('}');
}

bool res_fits(const ResMap& req, const ResMap& avail) {
  for (const auto& kv : req) {
    auto it = avail.find(kv.first);
    if ((it == avail.end() ? 0 : it->second) < kv.second) return false;
  }
  return true;
}

// ---------------------------------------------------------------------
// Per-handler latency stats (event_stats.py registry shape).
// ---------------------------------------------------------------------

struct Stat {
  uint64_t count = 0;
  double total_s = 0.0;
  double max_s = 0.0;
  uint32_t ring_us[256];
  int ring_n = 0;
  int ring_pos = 0;
};

// ---------------------------------------------------------------------
// Loop state.
// ---------------------------------------------------------------------

constexpr uint32_t kFlagPrecharged = 1u;
constexpr uint32_t kFlagJson = 2u;

struct Event {
  uint64_t conn_id = 0;
  int kind = 0;  // 0 = message, 1 = conn closed
  uint32_t flags = 0;
  char* data = nullptr;  // malloc'd; freed by nd_free (Python side)
  uint64_t len = 0;
};

struct Conn {
  uint64_t id = 0;
  int fd = -1;
  std::string inbuf;
  size_t in_off = 0;
  std::deque<std::string> outq;
  size_t out_off = 0;
  bool want_write = false;
  bool paused = false;
  // Request timer: set when a frame is admitted, closed by the first
  // reply queued for this conn (the protocol is one request in flight
  // per connection, so first-reply attribution is exact for unary
  // requests and time-to-first-frame for streams).
  bool timing = false;
  std::string timing_handler;
  Clock::time_point timing_t0;
};

struct Outgoing {
  uint64_t conn_id;
  std::string payload;  // unframed; the loop adds the length prefix
  Clock::time_point t;
};

struct Peer {
  std::string id;
  int64_t queued = 0;
  double headroom = 0.0;
  ResMap avail;
};

struct NdServer {
  int listen_fd = -1;
  int ep_fd = -1;
  int event_fd = -1;
  int port = 0;
  uint64_t max_frame = 1ull << 31;
  size_t queue_cap = 1024;
  std::thread loop_thread;
  std::atomic<bool> stop{false};
  std::atomic<bool> ping_native{true};
  std::atomic<int> paused_count{0};
  std::atomic<uint64_t> spilled{0};

  // Ready queue (Python drains via nd_next).
  std::mutex qmu;
  std::condition_variable qcv;
  std::deque<Event> queue;

  // Replies queued by Python (nd_send); the loop owns the conns.
  std::mutex omu;
  std::vector<Outgoing> outbox;

  // Resource ledger.
  std::mutex lmu;
  ResMap avail;

  // Stats.
  std::mutex smu;
  std::map<std::string, Stat> stats;

  // Python-pushed context for natively-written replies. load_tail is
  // the daemon's load report serialized WITHOUT its "available" entry
  // and without the leading '{' — the loop splices in the ledger's
  // own (always-fresh) availability when it builds a pong/refusal.
  std::mutex cfgmu;
  std::string node_id;
  std::string load_tail = "}";
  std::vector<Peer> peers;

  // Loop-thread-only state.
  std::unordered_map<int, Conn*> conns;
  uint64_t next_conn_id = 1;
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl >= 0) fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void record_stat(NdServer* s, const std::string& handler, double dt_s) {
  std::lock_guard<std::mutex> g(s->smu);
  Stat& st = s->stats[handler];
  st.count++;
  st.total_s += dt_s;
  if (dt_s > st.max_s) st.max_s = dt_s;
  uint32_t us = dt_s >= 4294.0
                    ? 0xFFFFFFFFu
                    : static_cast<uint32_t>(dt_s * 1e6);
  st.ring_us[st.ring_pos] = us;
  st.ring_pos = (st.ring_pos + 1) % 256;
  if (st.ring_n < 256) st.ring_n++;
}

void arm_events(NdServer* s, Conn* c) {
  epoll_event ev{};
  ev.events = (c->paused ? 0u : static_cast<uint32_t>(EPOLLIN)) |
              (c->outq.empty() ? 0u : static_cast<uint32_t>(EPOLLOUT)) |
              EPOLLRDHUP;
  ev.data.fd = c->fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_MOD, c->fd, &ev);
}

void push_event(NdServer* s, Event&& e) {
  {
    std::lock_guard<std::mutex> g(s->qmu);
    s->queue.push_back(std::move(e));
  }
  s->qcv.notify_one();
}

bool queue_full(NdServer* s) {
  std::lock_guard<std::mutex> g(s->qmu);
  return s->queue.size() >= s->queue_cap;
}

void close_conn(NdServer* s, Conn* c) {
  epoll_ctl(s->ep_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  s->conns.erase(c->fd);
  if (c->paused) s->paused_count.fetch_sub(1);
  // The close event always lands (never subject to the queue cap):
  // Python cleans up conn-scoped state (actors created over the conn,
  // live stream relays) from it.
  Event e;
  e.conn_id = c->id;
  e.kind = 1;
  push_event(s, std::move(e));
  delete c;
}

// Flush as much of the outq as the socket accepts. Returns false when
// the conn died (already closed + freed).
bool handle_writable(NdServer* s, Conn* c) {
  while (!c->outq.empty()) {
    const std::string& front = c->outq.front();
    ssize_t w = send(c->fd, front.data() + c->out_off,
                     front.size() - c->out_off, MSG_NOSIGNAL);
    if (w > 0) {
      c->out_off += static_cast<size_t>(w);
      if (c->out_off == front.size()) {
        c->outq.pop_front();
        c->out_off = 0;
      }
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    close_conn(s, c);
    return false;
  }
  bool want = !c->outq.empty();
  if (want != c->want_write) {
    c->want_write = want;
    arm_events(s, c);
  }
  return true;
}

// Queue one reply frame (adds the 8-byte big-endian length prefix) and
// try an immediate opportunistic write.
bool queue_frame(NdServer* s, Conn* c, const char* payload, size_t n) {
  std::string buf;
  buf.reserve(8 + n);
  for (int i = 7; i >= 0; i--)
    buf.push_back(static_cast<char>((static_cast<uint64_t>(n) >>
                                     (8 * i)) & 0xFF));
  buf.append(payload, n);
  c->outq.push_back(std::move(buf));
  return handle_writable(s, c);
}

std::string header_str(const JValue* header, const char* key) {
  if (header == nullptr) return std::string();
  const JValue* v = header->get(key);
  return (v != nullptr && v->kind == JValue::STR) ? v->str
                                                  : std::string();
}

// Build the natively-written spillback refusal / pong payloads. The
// load report is spliced from the Python-pushed tail with the ledger's
// live availability, so a refusal always carries an authoritative
// "available" even between heartbeats.
void append_load(NdServer* s, std::string* out) {
  std::string avail_json;
  {
    std::lock_guard<std::mutex> g(s->lmu);
    res_to_json(s->avail, &avail_json);
  }
  out->append("{\"available\":");
  out->append(avail_json);
  std::lock_guard<std::mutex> g(s->cfgmu);
  if (s->load_tail != "}") out->push_back(',');
  out->append(s->load_tail);
}

std::string pick_spill_target(NdServer* s, const ResMap& res,
                              const std::set<std::string>& exclude) {
  std::lock_guard<std::mutex> g(s->cfgmu);
  const Peer* best = nullptr;
  for (const Peer& p : s->peers) {
    if (exclude.count(p.id) != 0) continue;
    if (!res_fits(res, p.avail)) continue;
    if (best == nullptr || p.queued < best->queued ||
        (p.queued == best->queued && p.headroom > best->headroom))
      best = &p;
  }
  return best != nullptr ? best->id : std::string();
}

// Classify + handle one complete frame payload. Returns false when the
// conn was closed (malformed frame).
bool handle_frame(NdServer* s, Conn* c, const char* payload, size_t n) {
  Clock::time_point now = Clock::now();
  const char* body = payload;
  size_t body_len = n;
  JValue header;
  bool has_header = false;
  uint32_t flags = 0;

  if (n > 0 && payload[0] == '{') {
    // Cross-language JSON frame: the whole payload is the message.
    JParser p(payload, n);
    if (!p.parse(&header) || header.kind != JValue::OBJ) {
      close_conn(s, c);
      return false;
    }
    has_header = true;
    flags |= kFlagJson;
  } else if (n > 0 && payload[0] == 0x01) {
    // Hybrid frame: 0x01 | u32-LE header len | JSON header | body.
    if (n < 5) {
      close_conn(s, c);
      return false;
    }
    uint32_t hlen = 0;
    memcpy(&hlen, payload + 1, 4);  // cxx-wire: nd-hybrid-hlen <I
    if (5 + static_cast<uint64_t>(hlen) > n) {
      close_conn(s, c);
      return false;
    }
    JParser p(payload + 5, hlen);
    if (!p.parse(&header) || header.kind != JValue::OBJ) {
      close_conn(s, c);
      return false;
    }
    has_header = true;
    body = payload + 5 + hlen;
    body_len = n - 5 - hlen;
  }
  // else: opaque legacy pickle — Python handles everything.

  std::string mtype =
      has_header ? header_str(&header, "type") : std::string("opaque");

  // -- natively-handled fast paths ------------------------------------
  if (has_header && mtype == "ping" && s->ping_native.load()) {
    std::string reply = "{\"type\":\"pong\",\"node_id\":";
    {
      std::lock_guard<std::mutex> g(s->cfgmu);
      json_escape(s->node_id, &reply);
    }
    reply.append(",\"load\":");
    append_load(s, &reply);
    reply.push_back('}');
    record_stat(s, "ping", seconds_since(now, Clock::now()));
    return queue_frame(s, c, reply.data(), reply.size());
  }

  if (has_header && mtype == "task") {
    const JValue* sp = header.get("spillable");
    const JValue* resv = header.get("res");
    ResMap res;
    if (sp != nullptr && sp->kind == JValue::BOOL && sp->b &&
        resv != nullptr && parse_res(*resv, &res) && !res.empty()) {
      // Atomic check-and-charge (the Python daemon's admission block,
      // verbatim semantics): refusal never queues the task here.
      bool ok;
      {
        std::lock_guard<std::mutex> g(s->lmu);
        ok = res_fits(res, s->avail);
        if (ok)
          for (const auto& kv : res) s->avail[kv.first] -= kv.second;
      }
      if (!ok) {
        s->spilled.fetch_add(1);
        std::set<std::string> exclude;
        {
          std::lock_guard<std::mutex> g(s->cfgmu);
          exclude.insert(s->node_id);
        }
        const JValue* ex = header.get("exclude");
        if (ex != nullptr && ex->kind == JValue::ARR)
          for (const JValue& v : ex->arr)
            if (v.kind == JValue::STR) exclude.insert(v.str);
        std::string reply = "{\"type\":\"result\",\"task_id\":";
        std::string tid = header_str(&header, "tid");
        if (tid.empty())
          reply.append("null");
        else
          json_escape(tid, &reply);
        reply.append(",\"spillback\":true,\"retry_at\":");
        std::string target = pick_spill_target(s, res, exclude);
        if (target.empty())
          reply.append("null");
        else
          json_escape(target, &reply);
        reply.append(",\"load\":");
        append_load(s, &reply);
        reply.push_back('}');
        record_stat(s, "spill_refusal",
                    seconds_since(now, Clock::now()));
        return queue_frame(s, c, reply.data(), reply.size());
      }
      flags |= kFlagPrecharged;
    }
  }

  // -- hand off to Python ---------------------------------------------
  // Request timing: close on the first reply nd_send queues for this
  // conn. Credit/notification types never get a reply — no timer.
  if (mtype != "gen_ack" && mtype != "pull_complete") {
    c->timing = true;
    c->timing_handler = mtype;
    c->timing_t0 = now;
  }
  Event e;
  e.conn_id = c->id;
  e.kind = 0;
  e.flags = flags;
  e.data = static_cast<char*>(malloc(body_len > 0 ? body_len : 1));
  if (e.data == nullptr) {
    close_conn(s, c);
    return false;
  }
  memcpy(e.data, body, body_len);
  e.len = body_len;
  push_event(s, std::move(e));
  return true;
}

// Extract complete frames from the conn's inbuf. Pauses the conn
// (EPOLLIN off → TCP backpressure on the driver) when the ready queue
// is full. Returns false when the conn died.
bool parse_frames(NdServer* s, Conn* c) {
  for (;;) {
    size_t have = c->inbuf.size() - c->in_off;
    if (have < 8) break;
    const unsigned char* hp = reinterpret_cast<const unsigned char*>(
        c->inbuf.data() + c->in_off);
    uint64_t flen = 0;  // cxx-wire: nd-frame-len >Q
    for (int i = 0; i < 8; i++) flen = (flen << 8) | hp[i];
    if (flen == 0 || flen > s->max_frame) {
      close_conn(s, c);
      return false;
    }
    if (have < 8 + flen) break;
    if (queue_full(s)) {
      if (!c->paused) {
        c->paused = true;
        s->paused_count.fetch_add(1);
        arm_events(s, c);
      }
      return true;  // frame stays buffered until Python catches up
    }
    // Consume the frame before handling: handle_frame may close the
    // conn (and free c) on malformed input.
    size_t off = c->in_off;
    c->in_off += 8 + flen;
    bool alive = handle_frame(s, c, c->inbuf.data() + off + 8,
                              static_cast<size_t>(flen));
    if (!alive) return false;
  }
  if (c->in_off > 0 && c->in_off == c->inbuf.size()) {
    c->inbuf.clear();
    c->in_off = 0;
  } else if (c->in_off > (1u << 20)) {
    c->inbuf.erase(0, c->in_off);
    c->in_off = 0;
  }
  return true;
}

void handle_readable(NdServer* s, Conn* c) {
  char buf[65536];
  for (;;) {
    if (c->paused) return;  // stop pulling bytes while Python is behind
    ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
    if (r > 0) {
      c->inbuf.append(buf, static_cast<size_t>(r));
      if (!parse_frames(s, c)) return;
      if (static_cast<size_t>(r) < sizeof(buf)) return;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    close_conn(s, c);  // EOF or hard error
    return;
  }
}

void process_outbox(NdServer* s) {
  std::vector<Outgoing> batch;
  {
    std::lock_guard<std::mutex> g(s->omu);
    batch.swap(s->outbox);
  }
  for (Outgoing& o : batch) {
    Conn* c = nullptr;
    for (auto& kv : s->conns)
      if (kv.second->id == o.conn_id) {
        c = kv.second;
        break;
      }
    if (c == nullptr) continue;  // conn gone; reply dropped (as today)
    if (c->timing) {
      c->timing = false;
      record_stat(s, c->timing_handler,
                  seconds_since(c->timing_t0, o.t));
    }
    queue_frame(s, c, o.payload.data(), o.payload.size());
  }
}

void resume_paused(NdServer* s) {
  if (s->paused_count.load() == 0 || queue_full(s)) return;
  // Collect first: parse_frames may close (and erase) conns.
  std::vector<Conn*> paused;
  for (auto& kv : s->conns)
    if (kv.second->paused) paused.push_back(kv.second);
  for (Conn* c : paused) {
    if (queue_full(s)) break;
    c->paused = false;
    s->paused_count.fetch_sub(1);
    arm_events(s, c);
    parse_frames(s, c);
  }
}

void accept_ready(NdServer* s) {
  for (;;) {
    int fd = accept4(s->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Conn* c = new Conn();
    c->fd = fd;
    c->id = s->next_conn_id++;
    s->conns[fd] = c;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLRDHUP;
    ev.data.fd = fd;
    epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, fd, &ev);
  }
}

void loop_main(NdServer* s) {
  epoll_event evs[64];
  while (!s->stop.load()) {
    int n = epoll_wait(s->ep_fd, evs, 64, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == s->listen_fd) {
        accept_ready(s);
        continue;
      }
      if (fd == s->event_fd) {
        uint64_t junk;
        while (read(s->event_fd, &junk, 8) == 8) {
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = it->second;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        if (!handle_writable(s, c)) continue;
      }
      if (evs[i].events & (EPOLLIN | EPOLLRDHUP)) handle_readable(s, c);
    }
    process_outbox(s);
    resume_paused(s);
  }
  // Drain: wake any nd_next waiters so drainers exit.
  s->qcv.notify_all();
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI (ctypes; every call releases the GIL while it runs).
// ---------------------------------------------------------------------

extern "C" {

void* nd_create(int port, int bind_all, unsigned long long max_frame,
                int queue_cap) {
  NdServer* s = new NdServer();
  if (max_frame > 0) s->max_frame = max_frame;
  if (queue_cap > 0) s->queue_cap = static_cast<size_t>(queue_cap);
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = bind_all ? htonl(INADDR_ANY)
                                  : htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) < 0 ||
      listen(s->listen_fd, 128) < 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);
  s->ep_fd = epoll_create1(0);
  s->event_fd = eventfd(0, EFD_NONBLOCK);
  if (s->ep_fd < 0 || s->event_fd < 0) {
    if (s->ep_fd >= 0) close(s->ep_fd);
    if (s->event_fd >= 0) close(s->event_fd);
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = s->listen_fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  ev.events = EPOLLIN;
  ev.data.fd = s->event_fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, s->event_fd, &ev);
  return s;
}

int nd_port(void* h) {
  return h != nullptr ? static_cast<NdServer*>(h)->port : -1;
}

int nd_start(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -1;
  s->loop_thread = std::thread(loop_main, s);
  return 0;
}

void nd_wake(NdServer* s) {
  uint64_t one = 1;
  ssize_t rc = write(s->event_fd, &one, 8);
  (void)rc;
}

int nd_next(void* h, int timeout_ms, unsigned long long* conn_id,
            int* kind, unsigned int* flags, char** data,
            unsigned long long* len) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -1;
  Event e;
  {
    std::unique_lock<std::mutex> g(s->qmu);
    // system_clock deadline on purpose: with a steady_clock wait_for,
    // libstdc++ uses pthread_cond_clockwait, which gcc's TSAN runtime
    // does not intercept — every wait would look like a held mutex. A
    // clock jump only stretches one 200ms poll tick.
    if (!s->qcv.wait_until(
            g,
            std::chrono::system_clock::now() +
                std::chrono::milliseconds(timeout_ms),
            [&] { return s->stop.load() || !s->queue.empty(); }))
      return 0;  // timeout
    if (s->queue.empty()) return -1;  // stopped
    e = std::move(s->queue.front());
    s->queue.pop_front();
  }
  if (s->paused_count.load() > 0) nd_wake(s);  // room freed: resume
  *conn_id = e.conn_id;
  *kind = e.kind;
  *flags = e.flags;
  *data = e.data;
  *len = e.len;
  return 1;
}

void nd_free(char* data) { free(data); }

int nd_send(void* h, unsigned long long conn_id, const char* data,
            unsigned long long len) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.load()) return -1;
  Outgoing o;
  o.conn_id = conn_id;
  o.payload.assign(data, static_cast<size_t>(len));
  o.t = Clock::now();
  {
    std::lock_guard<std::mutex> g(s->omu);
    s->outbox.push_back(std::move(o));
  }
  nd_wake(s);
  return 0;
}

void nd_set_node_id(void* h, const char* node_id) {
  NdServer* s = static_cast<NdServer*>(h);
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->node_id = node_id != nullptr ? node_id : "";
}

void nd_set_load_tail(void* h, const char* tail) {
  NdServer* s = static_cast<NdServer*>(h);
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->load_tail = (tail != nullptr && tail[0] != '\0') ? tail : "}";
}

int nd_set_peers_json(void* h, const char* json) {
  NdServer* s = static_cast<NdServer*>(h);
  JValue v;
  JParser p(json, json != nullptr ? strlen(json) : 0);
  if (json == nullptr || !p.parse(&v) || v.kind != JValue::ARR)
    return -1;
  std::vector<Peer> peers;
  for (const JValue& pv : v.arr) {
    if (pv.kind != JValue::OBJ) return -1;
    Peer peer;
    const JValue* id = pv.get("id");
    if (id == nullptr || id->kind != JValue::STR) return -1;
    peer.id = id->str;
    const JValue* q = pv.get("queued");
    if (q != nullptr && q->kind == JValue::NUM)
      peer.queued = static_cast<int64_t>(q->num);
    const JValue* hr = pv.get("headroom");
    if (hr != nullptr && hr->kind == JValue::NUM) peer.headroom = hr->num;
    const JValue* av = pv.get("avail");
    if (av != nullptr && !parse_res(*av, &peer.avail)) return -1;
    peers.push_back(std::move(peer));
  }
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->peers.swap(peers);
  return 0;
}

void nd_set_ping_native(void* h, int enabled) {
  static_cast<NdServer*>(h)->ping_native.store(enabled != 0);
}

// -- resource ledger ---------------------------------------------------

int nd_ledger_set(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  s->avail.swap(r);
  return 0;
}

int nd_ledger_try_charge(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  if (!res_fits(r, s->avail)) return 0;
  for (const auto& kv : r) s->avail[kv.first] -= kv.second;
  return 1;
}

// Unconditional subtract — except it must not drive availability
// negative silently: ResourceSet.subtract raises, so the Python
// wrapper turns -1 into the same ValueError.
int nd_ledger_charge(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -2;
  std::lock_guard<std::mutex> g(s->lmu);
  for (const auto& kv : r) {
    auto it = s->avail.find(kv.first);
    if ((it == s->avail.end() ? 0 : it->second) < kv.second) return -1;
  }
  for (const auto& kv : r) s->avail[kv.first] -= kv.second;
  return 0;
}

int nd_ledger_release(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  for (const auto& kv : r) s->avail[kv.first] += kv.second;
  return 0;
}

int nd_ledger_get(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  std::string out;
  {
    std::lock_guard<std::mutex> g(s->lmu);
    res_to_json(s->avail, &out);
  }
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// -- stats -------------------------------------------------------------

unsigned long long nd_spilled(void* h) {
  return static_cast<NdServer*>(h)->spilled.load();
}

int nd_stats_json(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  std::string out = "{";
  {
    std::lock_guard<std::mutex> g(s->smu);
    bool first = true;
    for (const auto& kv : s->stats) {
      if (!first) out.push_back(',');
      first = false;
      json_escape(kv.first, &out);
      char num[160];
      uint32_t ring[256];
      const Stat& st = kv.second;
      memcpy(ring, st.ring_us,
             sizeof(uint32_t) * static_cast<size_t>(st.ring_n));
      double p95 = 0.0;
      if (st.ring_n > 0) {
        std::sort(ring, ring + st.ring_n);
        int idx = static_cast<int>(0.95 * (st.ring_n - 1) + 0.5);
        p95 = ring[idx] / 1e6;
      }
      snprintf(num, sizeof(num),
               ":{\"count\":%llu,\"total_s\":%.9g,\"max_s\":%.9g,"
               "\"p95_s\":%.9g}",
               static_cast<unsigned long long>(st.count), st.total_s,
               st.max_s, p95);
      out.append(num);
    }
  }
  out.push_back('}');
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// -- lifecycle ---------------------------------------------------------

void nd_stop(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.exchange(true)) return;
  nd_wake(s);
  if (s->loop_thread.joinable()) s->loop_thread.join();
  for (auto& kv : s->conns) {
    close(kv.second->fd);
    delete kv.second;
  }
  s->conns.clear();
  close(s->listen_fd);
  close(s->ep_fd);
  close(s->event_fd);
  // Free any undrained message bodies.
  std::lock_guard<std::mutex> g(s->qmu);
  for (Event& e : s->queue) free(e.data);
  s->queue.clear();
  s->qcv.notify_all();
}

// Safe only after nd_stop AND after every drainer thread has returned
// from nd_next — the Python side joins its drainers first.
void nd_destroy(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return;
#if defined(__SANITIZE_THREAD__)
  // libstdc++'s std::mutex / condition_variable destructors are
  // trivial on Linux, so TSAN never sees them die; a later server
  // allocated at the same address would inherit their sync state and
  // report phantom double-locks. Make the destruction visible.
  pthread_cond_destroy(s->qcv.native_handle());
  pthread_mutex_destroy(s->qmu.native_handle());
  pthread_mutex_destroy(s->omu.native_handle());
  pthread_mutex_destroy(s->lmu.native_handle());
  pthread_mutex_destroy(s->smu.native_handle());
  pthread_mutex_destroy(s->cfgmu.native_handle());
#endif
  delete s;
}

}  // extern "C"

