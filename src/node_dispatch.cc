// node_dispatch.cc — native dispatch front end for the node daemon.
//
// Grows control_plane.cc's single-threaded epoll substrate into the
// daemon's dispatch-socket hot loop (reference: the raylet keeps accept/
// frame/admission in C++ and calls Python only for policy,
// src/ray/raylet/node_manager.cc). The loop owns:
//
//   - accept + nonblocking conn lifecycle (one epoll thread, no
//     thread-per-connection, nothing here touches the GIL);
//   - wire framing: 8-byte big-endian length + payload, same protocol
//     the Python daemon speaks (worker_proc._LEN);
//   - payload classification: '{' = JSON message (cross-language
//     clients), 0x01 = hybrid frame (u32-LE header length + JSON
//     admission header + opaque cloudpickle body — the Python driver's
//     NodeConn emits these), anything else = opaque legacy pickle;
//   - task-queue admission: check-and-charge against the resource
//     ledger (same 1/10000 fixed-point model as core/resources.py) for
//     driver-marked spillable tasks, with the refusal reply — peer
//     redirect hint + authoritative load — written natively;
//   - "ping" answered natively from the Python-pushed load report;
//   - a bounded ready queue the Python side drains (nd_next), with
//     EPOLLIN backpressure when Python falls behind: paused conns stop
//     being read, so TCP pushes back on the drivers instead of the
//     queue growing without bound;
//   - per-(loop,handler) count/total/max/p95 latency stats (the
//     event_stats.h analog), measured from frame arrival to the first
//     reply byte queued for that request;
//   - an idle-worker registry (nd_worker_*): Python registers worker
//     sockets + their cached fn ids, and the loop hands admitted
//     "plain" task frames straight onto an idle worker's socket (the
//     wire body is forwarded, never re-encoded) and forwards the
//     worker's single result frame back to the driver conn with the
//     ledger released first — the warm path runs zero Python
//     bytecode. Cold paths (fn spreading, actors, streaming, fetch
//     hints, spawn/scale-up, every error) still flow through the
//     ready queue to the Python drainers.
//
// Everything Python needs crosses a narrow C ABI (nd_*) loaded via
// ctypes — every call releases the GIL for its duration.

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

double seconds_since(Clock::time_point t0, Clock::time_point t1) {
  return std::chrono::duration<double>(t1 - t0).count();
}

// Wall-clock epoch seconds (CLOCK_REALTIME): directly comparable with
// the driver's time.time() lifecycle stamps, so warm-path dispatch
// timestamps slot into the same timeline as Python-stamped phases.
double wall_now() {
  return std::chrono::duration<double>(
      std::chrono::system_clock::now().time_since_epoch()).count();
}

// ---------------------------------------------------------------------
// Minimal JSON: enough for admission headers, resource dicts and the
// peer digest. Parses into a tagged value; no exceptions escape.
// ---------------------------------------------------------------------

struct JValue {
  enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  double num = 0.0;
  std::string str;
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;

  const JValue* get(const char* key) const {
    for (const auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
};

class JParser {
 public:
  JParser(const char* p, size_t n) : p_(p), end_(p + n) {}

  bool parse(JValue* out) {
    skip_ws();
    if (!value(out)) return false;
    skip_ws();
    return p_ == end_;
  }

 private:
  const char* p_;
  const char* end_;

  void skip_ws() {
    while (p_ < end_ && (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' ||
                         *p_ == '\r'))
      p_++;
  }

  bool lit(const char* s, size_t n) {
    if (static_cast<size_t>(end_ - p_) < n || memcmp(p_, s, n) != 0)
      return false;
    p_ += n;
    return true;
  }

  bool value(JValue* out) {
    skip_ws();
    if (p_ >= end_) return false;
    switch (*p_) {
      case '{':
        return object(out);
      case '[':
        return array(out);
      case '"':
        out->kind = JValue::STR;
        return string(&out->str);
      case 't':
        out->kind = JValue::BOOL;
        out->b = true;
        return lit("true", 4);
      case 'f':
        out->kind = JValue::BOOL;
        out->b = false;
        return lit("false", 5);
      case 'n':
        out->kind = JValue::NUL;
        return lit("null", 4);
      default:
        return number(out);
    }
  }

  bool number(JValue* out) {
    const char* start = p_;
    if (p_ < end_ && (*p_ == '-' || *p_ == '+')) p_++;
    while (p_ < end_ && ((*p_ >= '0' && *p_ <= '9') || *p_ == '.' ||
                         *p_ == 'e' || *p_ == 'E' || *p_ == '-' ||
                         *p_ == '+'))
      p_++;
    if (p_ == start) return false;
    std::string tmp(start, p_ - start);
    char* endp = nullptr;
    out->num = strtod(tmp.c_str(), &endp);
    out->kind = JValue::NUM;
    return endp == tmp.c_str() + tmp.size();
  }

  bool hex4(unsigned* out) {
    if (end_ - p_ < 4) return false;
    unsigned v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p_[i];
      v <<= 4;
      if (c >= '0' && c <= '9')
        v |= static_cast<unsigned>(c - '0');
      else if (c >= 'a' && c <= 'f')
        v |= static_cast<unsigned>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F')
        v |= static_cast<unsigned>(c - 'A' + 10);
      else
        return false;
    }
    p_ += 4;
    *out = v;
    return true;
  }

  void append_utf8(std::string* s, unsigned cp) {
    if (cp < 0x80) {
      s->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      s->push_back(static_cast<char>(0xC0 | (cp >> 6)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(static_cast<char>(0xE0 | (cp >> 12)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(static_cast<char>(0xF0 | (cp >> 18)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  bool string(std::string* out) {
    if (p_ >= end_ || *p_ != '"') return false;
    p_++;
    out->clear();
    while (p_ < end_) {
      char c = *p_++;
      if (c == '"') return true;
      if (c != '\\') {
        out->push_back(c);
        continue;
      }
      if (p_ >= end_) return false;
      char e = *p_++;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          unsigned cp = 0;
          if (!hex4(&cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF && end_ - p_ >= 6 &&
              p_[0] == '\\' && p_[1] == 'u') {
            p_ += 2;
            unsigned lo = 0;
            if (!hex4(&lo)) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          }
          append_utf8(out, cp);
          break;
        }
        default:
          return false;
      }
    }
    return false;
  }

  bool array(JValue* out) {
    out->kind = JValue::ARR;
    p_++;  // '['
    skip_ws();
    if (p_ < end_ && *p_ == ']') {
      p_++;
      return true;
    }
    while (p_ < end_) {
      out->arr.emplace_back();
      if (!value(&out->arr.back())) return false;
      skip_ws();
      if (p_ < end_ && *p_ == ',') {
        p_++;
        continue;
      }
      if (p_ < end_ && *p_ == ']') {
        p_++;
        return true;
      }
      return false;
    }
    return false;
  }

  bool object(JValue* out) {
    out->kind = JValue::OBJ;
    p_++;  // '{'
    skip_ws();
    if (p_ < end_ && *p_ == '}') {
      p_++;
      return true;
    }
    while (p_ < end_) {
      skip_ws();
      std::string key;
      if (!string(&key)) return false;
      skip_ws();
      if (p_ >= end_ || *p_ != ':') return false;
      p_++;
      out->obj.emplace_back(std::move(key), JValue());
      if (!value(&out->obj.back().second)) return false;
      skip_ws();
      if (p_ < end_ && *p_ == ',') {
        p_++;
        continue;
      }
      if (p_ < end_ && *p_ == '}') {
        p_++;
        return true;
      }
      return false;
    }
    return false;
  }
};

void json_escape(const std::string& s, std::string* out) {
  out->push_back('"');
  for (char c : s) {
    switch (c) {
      case '"': out->append("\\\""); break;
      case '\\': out->append("\\\\"); break;
      case '\n': out->append("\\n"); break;
      case '\r': out->append("\\r"); break;
      case '\t': out->append("\\t"); break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x",
                   static_cast<unsigned char>(c));
          out->append(buf);
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

void json_number(double v, std::string* out) {
  char buf[40];
  if (v == static_cast<int64_t>(v) && v > -1e15 && v < 1e15) {
    snprintf(buf, sizeof(buf), "%lld.0",
             static_cast<long long>(v));
  } else {
    snprintf(buf, sizeof(buf), "%.12g", v);
  }
  out->append(buf);
}

// ---------------------------------------------------------------------
// Resource ledger: 1/10000 fixed-point, exactly core/resources.py.
// ---------------------------------------------------------------------

constexpr int64_t kGranularity = 10000;

int64_t to_fixed(double v) {
  return static_cast<int64_t>(v * kGranularity + (v >= 0 ? 0.5 : -0.5));
}

using ResMap = std::map<std::string, int64_t>;

bool parse_res(const JValue& obj, ResMap* out) {
  if (obj.kind != JValue::OBJ) return false;
  for (const auto& kv : obj.obj) {
    if (kv.second.kind != JValue::NUM) return false;
    int64_t f = to_fixed(kv.second.num);
    if (f != 0) (*out)[kv.first] = f;
  }
  return true;
}

bool parse_res_str(const char* s, ResMap* out) {
  if (s == nullptr) return false;
  JValue v;
  JParser p(s, strlen(s));
  if (!p.parse(&v)) return false;
  return parse_res(v, out);
}

void res_to_json(const ResMap& r, std::string* out) {
  out->push_back('{');
  bool first = true;
  for (const auto& kv : r) {
    if (kv.second == 0) continue;  // to_dict() drops zero entries
    if (!first) out->push_back(',');
    first = false;
    json_escape(kv.first, out);
    out->push_back(':');
    json_number(static_cast<double>(kv.second) / kGranularity, out);
  }
  out->push_back('}');
}

bool res_fits(const ResMap& req, const ResMap& avail) {
  for (const auto& kv : req) {
    auto it = avail.find(kv.first);
    if ((it == avail.end() ? 0 : it->second) < kv.second) return false;
  }
  return true;
}

// ---------------------------------------------------------------------
// Per-handler latency stats (event_stats.py registry shape).
// ---------------------------------------------------------------------

struct Stat {
  uint64_t count = 0;
  double total_s = 0.0;
  double max_s = 0.0;
  uint32_t ring_us[256];
  int ring_n = 0;
  int ring_pos = 0;
};

// ---------------------------------------------------------------------
// Loop state.
// ---------------------------------------------------------------------

constexpr uint32_t kFlagPrecharged = 1u;
constexpr uint32_t kFlagJson = 2u;

struct Event {
  uint64_t conn_id = 0;
  // 0 = message, 1 = conn closed, 2 = registered worker died (conn_id
  // carries the worker id; Python respawns it).
  int kind = 0;
  uint32_t flags = 0;
  char* data = nullptr;  // malloc'd; freed by nd_free (Python side)
  uint64_t len = 0;
};

constexpr int kEvWorkerDead = 2;

struct Conn {
  uint64_t id = 0;
  int fd = -1;
  std::string inbuf;
  size_t in_off = 0;
  std::deque<std::string> outq;
  size_t out_off = 0;
  bool want_write = false;
  bool paused = false;
  // Request timer: set when a frame is admitted, closed by the first
  // reply queued for this conn (the protocol is one request in flight
  // per connection, so first-reply attribution is exact for unary
  // requests and time-to-first-frame for streams).
  bool timing = false;
  std::string timing_handler;
  Clock::time_point timing_t0;
};

struct Outgoing {
  uint64_t conn_id;
  std::string payload;  // unframed; the loop adds the length prefix
  Clock::time_point t;
};

struct Peer {
  std::string id;
  int64_t queued = 0;
  double headroom = 0.0;
  ResMap avail;
};

// ---------------------------------------------------------------------
// Idle-worker registry: the native hand-off substrate. A registered
// worker's socket (a dup of Python's fd — the registry owns its copy)
// is epoll'd by the same loop; an IDLE worker can take a plain task
// frame directly, a PY_OWNED worker was checked out via
// nd_worker_acquire and its fd is NOT watched (Python speaks on the
// socket until nd_worker_release).
// ---------------------------------------------------------------------

constexpr int kWIdle = 0;
constexpr int kWBusy = 1;
constexpr int kWPyOwned = 2;

// An admitted plain task waiting for a capable idle worker. Holds the
// raw cloudpickle body (forwarded verbatim) and, when precharged, the
// ledger charge to release on completion/death.
struct PendingTask {
  uint64_t conn_id = 0;
  std::string tid;  // hex task id (for the typed death error)
  std::string fid;  // hex fn id (capability matching)
  bool has_fn = false;  // body carries the fn: any worker can take it
  ResMap res;
  std::string body;
  Clock::time_point t0;  // frame arrival (latency attribution)
  // Driver asked for dispatch timestamps ("tm" admission-header key):
  // the result forward is preceded by a dispatch_timing frame carrying
  // wall-clock arrival/worker-write/forward stamps.
  bool want_tm = false;
  double recv_wall = 0.0;
};

struct Worker {
  uint64_t wid = 0;
  int fd = -1;  // dup'd from Python; closed on unregister/death/stop
  int pid = 0;
  int state = kWIdle;
  // Stamp of the last state transition: the outstanding-resource
  // ledger reads it back as the busy/checkout acquire-age.
  Clock::time_point state_t0 = Clock::now();
  std::set<std::string> fids;  // hex fn ids this worker has cached
  // In-flight native task (state == kWBusy).
  uint64_t task_conn = 0;
  std::string task_tid;
  ResMap task_res;
  Clock::time_point task_t0;
  // Wall-clock dispatch stamps for the in-flight task (only filled
  // when the driver sent "tm" in the admission header).
  bool task_tm = false;
  double task_recv_wall = 0.0;
  double task_write_wall = 0.0;
  // Socket buffers. ALL worker-socket IO happens under wmu (loop
  // thread for epoll events, a Python thread inside nd_worker_release
  // when serving the pending queue) — the lock is the serializer.
  std::string inbuf;
  size_t in_off = 0;
  std::deque<std::string> outq;
  size_t out_off = 0;
};

struct NdServer {
  int listen_fd = -1;
  int ep_fd = -1;
  int event_fd = -1;
  int port = 0;
  uint64_t max_frame = 1ull << 31;
  size_t queue_cap = 1024;
  std::thread loop_thread;
  std::atomic<bool> stop{false};
  std::atomic<bool> ping_native{true};
  std::atomic<int> paused_count{0};
  std::atomic<uint64_t> spilled{0};

  // Ready queue (Python drains via nd_next).
  std::mutex qmu;
  std::condition_variable qcv;
  std::deque<Event> queue;

  // Replies queued by Python (nd_send); the loop owns the conns.
  std::mutex omu;
  std::vector<Outgoing> outbox;

  // Resource ledger.
  std::mutex lmu;
  ResMap avail;

  // Stats.
  std::mutex smu;
  std::map<std::string, Stat> stats;

  // Python-pushed context for natively-written replies. load_tail is
  // the daemon's load report serialized WITHOUT its "available" entry
  // and without the leading '{' — the loop splices in the ledger's
  // own (always-fresh) availability when it builds a pong/refusal.
  std::mutex cfgmu;
  std::string node_id;
  std::string load_tail = "}";
  std::vector<Peer> peers;

  // Idle-worker registry + native hand-off state. wmu is the
  // OUTERMOST lock in this file: wmu→lmu (ledger release on
  // completion/death), wmu→smu (record_stat), wmu→qmu (push_event)
  // and wmu→omu (driver-bound replies) all occur; never the reverse.
  std::mutex wmu;
  std::condition_variable wcv;  // nd_worker_acquire waiters
  std::map<uint64_t, Worker*> workers;        // wid → worker
  std::unordered_map<int, uint64_t> wfd;      // worker fd → wid
  std::deque<PendingTask> pending;            // waiting for a worker
  size_t pending_cap = 1024;
  std::atomic<size_t> pending_count{0};       // mirrors pending.size()
  std::atomic<uint64_t> handoffs{0};          // frames written natively
  std::atomic<uint64_t> native_done{0};       // results forwarded
  std::atomic<uint64_t> worker_deaths{0};
  std::atomic<uint64_t> handoff_overflow{0};  // pending full → Python

  // Loop-thread-only state.
  std::unordered_map<int, Conn*> conns;
  uint64_t next_conn_id = 1;
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl >= 0) fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void record_stat(NdServer* s, const std::string& handler, double dt_s) {
  std::lock_guard<std::mutex> g(s->smu);
  Stat& st = s->stats[handler];
  st.count++;
  st.total_s += dt_s;
  if (dt_s > st.max_s) st.max_s = dt_s;
  uint32_t us = dt_s >= 4294.0
                    ? 0xFFFFFFFFu
                    : static_cast<uint32_t>(dt_s * 1e6);
  st.ring_us[st.ring_pos] = us;
  st.ring_pos = (st.ring_pos + 1) % 256;
  if (st.ring_n < 256) st.ring_n++;
}

void arm_events(NdServer* s, Conn* c) {
  epoll_event ev{};
  ev.events = (c->paused ? 0u : static_cast<uint32_t>(EPOLLIN)) |
              (c->outq.empty() ? 0u : static_cast<uint32_t>(EPOLLOUT)) |
              EPOLLRDHUP;
  ev.data.fd = c->fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_MOD, c->fd, &ev);
}

void push_event(NdServer* s, Event&& e) {
  {
    std::lock_guard<std::mutex> g(s->qmu);
    s->queue.push_back(std::move(e));
  }
  s->qcv.notify_one();
}

// Backpressure gate: the Python-bound ready queue and the native
// pending queue share one budget, so all-workers-busy churn engages
// the same EPOLLIN pause as a slow drainer.
bool queue_full(NdServer* s) {
  size_t qn;
  {
    std::lock_guard<std::mutex> g(s->qmu);
    qn = s->queue.size();
  }
  return qn + s->pending_count.load() >= s->queue_cap;
}

void close_conn(NdServer* s, Conn* c) {
  epoll_ctl(s->ep_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  s->conns.erase(c->fd);
  if (c->paused) s->paused_count.fetch_sub(1);
  // The close event always lands (never subject to the queue cap):
  // Python cleans up conn-scoped state (actors created over the conn,
  // live stream relays) from it.
  Event e;
  e.conn_id = c->id;
  e.kind = 1;
  push_event(s, std::move(e));
  delete c;
}

// Flush as much of the outq as the socket accepts. Returns false when
// the conn died (already closed + freed).
bool handle_writable(NdServer* s, Conn* c) {
  while (!c->outq.empty()) {
    const std::string& front = c->outq.front();
    ssize_t w = send(c->fd, front.data() + c->out_off,
                     front.size() - c->out_off, MSG_NOSIGNAL);
    if (w > 0) {
      c->out_off += static_cast<size_t>(w);
      if (c->out_off == front.size()) {
        c->outq.pop_front();
        c->out_off = 0;
      }
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    close_conn(s, c);
    return false;
  }
  bool want = !c->outq.empty();
  if (want != c->want_write) {
    c->want_write = want;
    arm_events(s, c);
  }
  return true;
}

// Queue one reply frame (adds the 8-byte big-endian length prefix) and
// try an immediate opportunistic write.
bool queue_frame(NdServer* s, Conn* c, const char* payload, size_t n) {
  std::string buf;
  buf.reserve(8 + n);
  for (int i = 7; i >= 0; i--)
    buf.push_back(static_cast<char>((static_cast<uint64_t>(n) >>
                                     (8 * i)) & 0xFF));
  buf.append(payload, n);
  c->outq.push_back(std::move(buf));
  return handle_writable(s, c);
}

std::string header_str(const JValue* header, const char* key) {
  if (header == nullptr) return std::string();
  const JValue* v = header->get(key);
  return (v != nullptr && v->kind == JValue::STR) ? v->str
                                                  : std::string();
}

// Build the natively-written spillback refusal / pong payloads. The
// load report is spliced from the Python-pushed tail with the ledger's
// live availability, so a refusal always carries an authoritative
// "available" even between heartbeats.
void append_load(NdServer* s, std::string* out) {
  std::string avail_json;
  {
    std::lock_guard<std::mutex> g(s->lmu);
    res_to_json(s->avail, &avail_json);
  }
  out->append("{\"available\":");
  out->append(avail_json);
  std::lock_guard<std::mutex> g(s->cfgmu);
  if (s->load_tail != "}") out->push_back(',');
  out->append(s->load_tail);
}

std::string pick_spill_target(NdServer* s, const ResMap& res,
                              const std::set<std::string>& exclude) {
  std::lock_guard<std::mutex> g(s->cfgmu);
  const Peer* best = nullptr;
  for (const Peer& p : s->peers) {
    if (exclude.count(p.id) != 0) continue;
    if (!res_fits(res, p.avail)) continue;
    if (best == nullptr || p.queued < best->queued ||
        (p.queued == best->queued && p.headroom > best->headroom))
      best = &p;
  }
  return best != nullptr ? best->id : std::string();
}

// ---------------------------------------------------------------------
// Native worker hand-off. Every function below expects wmu held unless
// noted; none touches s->conns (driver-bound bytes go through the
// shared outbox so Python-thread callers can produce replies too).
// ---------------------------------------------------------------------

void nd_wake_fd(NdServer* s) {
  uint64_t one = 1;
  ssize_t rc = write(s->event_fd, &one, 8);
  (void)rc;
}

// Any thread. Queue a driver-bound payload; the loop frames + writes it.
void send_to_driver(NdServer* s, uint64_t conn_id, std::string&& payload) {
  Outgoing o;
  o.conn_id = conn_id;
  o.payload = std::move(payload);
  o.t = Clock::now();
  {
    std::lock_guard<std::mutex> g(s->omu);
    s->outbox.push_back(std::move(o));
  }
  nd_wake_fd(s);
}

void parse_csv(const char* csv, std::set<std::string>* out) {
  if (csv == nullptr) return;
  const char* p = csv;
  while (*p) {
    const char* e = strchr(p, ',');
    size_t n = e != nullptr ? static_cast<size_t>(e - p) : strlen(p);
    if (n > 0) out->insert(std::string(p, n));
    p += n;
    if (*p == ',') p++;
  }
}

// Flush the worker outq. Returns false when the socket failed (caller
// must run worker_died).
bool worker_flush(NdServer* s, Worker* w) {
  (void)s;
  while (!w->outq.empty()) {
    const std::string& front = w->outq.front();
    // MSG_DONTWAIT, not O_NONBLOCK: the fd is dup'd from Python's
    // blocking worker socket and dup() SHARES file-status flags —
    // flipping O_NONBLOCK here would break the cold path's blocking
    // reads on the original fd.
    ssize_t n = send(w->fd, front.data() + w->out_off,
                     front.size() - w->out_off,
                     MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n > 0) {
      w->out_off += static_cast<size_t>(n);
      if (w->out_off == front.size()) {
        w->outq.pop_front();
        w->out_off = 0;
      }
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    return false;
  }
  return true;
}

void worker_arm(NdServer* s, Worker* w) {
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP |
              (w->outq.empty() ? 0u : static_cast<uint32_t>(EPOLLOUT));
  ev.data.fd = w->fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_MOD, w->fd, &ev);
}

// Hand one admitted plain-task body to an idle worker: the wire body
// is forwarded with a fresh length prefix, never re-encoded. Returns
// false when the worker socket failed mid-write (caller runs
// worker_died — the typed error reaches the driver from there).
bool start_native_task(NdServer* s, Worker* w, uint64_t conn_id,
                       const std::string& tid, const std::string& fid,
                       ResMap&& res, const char* body, size_t body_len,
                       Clock::time_point t0, bool want_tm,
                       double recv_wall) {
  w->state = kWBusy;
  w->state_t0 = Clock::now();
  w->task_conn = conn_id;
  w->task_tid = tid;
  w->task_res = std::move(res);
  w->task_t0 = t0;
  w->task_tm = want_tm;
  w->task_recv_wall = recv_wall;
  // Worker-write stamp: the hand-off point where the body leaves the
  // dispatch plane (queueing before this is admission + idle-wait).
  w->task_write_wall = want_tm ? wall_now() : 0.0;
  // The worker caches the fn from the body on first sight of the fid
  // (get_fn in core/worker_main.py), so record it now either way.
  w->fids.insert(fid);
  std::string buf;
  buf.reserve(8 + body_len);
  for (int i = 7; i >= 0; i--)  // cxx-wire: nd-frame-len >Q
    buf.push_back(static_cast<char>(
        (static_cast<uint64_t>(body_len) >> (8 * i)) & 0xFF));
  buf.append(body, body_len);
  w->outq.push_back(std::move(buf));
  s->handoffs.fetch_add(1);
  record_stat(s, "task_native_handoff", seconds_since(t0, Clock::now()));
  if (!worker_flush(s, w)) return false;
  worker_arm(s, w);
  return true;
}

// Pending entries no surviving worker can run fall back to the Python
// cold path: the stored body is the raw pickle the drainer already
// understands, and an existing charge rides kFlagPrecharged.
void requeue_unrunnable_pending(NdServer* s) {
  std::deque<PendingTask> keep;
  for (PendingTask& p : s->pending) {
    bool runnable = p.has_fn && !s->workers.empty();
    if (!runnable)
      for (auto& kv : s->workers)
        if (kv.second->fids.count(p.fid) != 0) {
          runnable = true;
          break;
        }
    if (runnable) {
      keep.push_back(std::move(p));
      continue;
    }
    Event e;
    e.conn_id = p.conn_id;
    e.kind = 0;
    e.flags = p.res.empty() ? 0u : kFlagPrecharged;
    e.data = static_cast<char*>(
        malloc(p.body.size() > 0 ? p.body.size() : 1));
    if (e.data == nullptr) {  // drop, but never leak the charge
      if (!p.res.empty()) {
        std::lock_guard<std::mutex> g(s->lmu);
        for (const auto& kv : p.res) s->avail[kv.first] += kv.second;
      }
      continue;
    }
    memcpy(e.data, p.body.data(), p.body.size());
    e.len = p.body.size();
    push_event(s, std::move(e));
  }
  s->pending.swap(keep);
  s->pending_count.store(s->pending.size());
}

// Tear down a registered worker. An in-flight native task gets the
// typed error the Python path produces for WorkerCrashedError, with
// the ledger released first (same ordering as _run_task's done()).
// notify_python=false for deliberate unregister (retire/discard).
void worker_died(NdServer* s, Worker* w, bool notify_python) {
  s->workers.erase(w->wid);
  s->wfd.erase(w->fd);
  epoll_ctl(s->ep_fd, EPOLL_CTL_DEL, w->fd, nullptr);
  close(w->fd);
  if (notify_python) s->worker_deaths.fetch_add(1);
  if (w->state == kWBusy) {
    if (!w->task_res.empty()) {
      std::lock_guard<std::mutex> g(s->lmu);
      for (const auto& kv : w->task_res) s->avail[kv.first] += kv.second;
    }
    std::string reply = "{\"type\":\"result\",\"task_id\":";
    if (w->task_tid.empty())
      reply.append("null");
    else
      json_escape(w->task_tid, &reply);
    reply.append(",\"crashed\":\"worker died during native hand-off\"}");
    record_stat(s, "task_native", seconds_since(w->task_t0, Clock::now()));
    send_to_driver(s, w->task_conn, std::move(reply));
  }
  if (notify_python) {
    Event e;
    e.conn_id = w->wid;  // worker id, not a conn id, for kind=2
    e.kind = kEvWorkerDead;
    push_event(s, std::move(e));
  }
  delete w;
  requeue_unrunnable_pending(s);
}

// Worker finished (or Python released it): serve the first runnable
// pending task, else park idle and wake an nd_worker_acquire waiter.
// Returns false when the worker died serving (w freed).
bool worker_now_idle(NdServer* s, Worker* w) {
  w->state = kWIdle;
  w->state_t0 = Clock::now();
  w->task_conn = 0;
  w->task_tid.clear();
  w->task_res.clear();
  w->task_tm = false;
  w->task_recv_wall = 0.0;
  w->task_write_wall = 0.0;
  for (auto it = s->pending.begin(); it != s->pending.end(); ++it) {
    if (!(it->has_fn || w->fids.count(it->fid) != 0)) continue;
    PendingTask p = std::move(*it);
    s->pending.erase(it);
    s->pending_count.store(s->pending.size());
    nd_wake_fd(s);  // pending shrank: loop re-checks paused conns
    if (!start_native_task(s, w, p.conn_id, p.tid, p.fid,
                           std::move(p.res), p.body.data(),
                           p.body.size(), p.t0, p.want_tm,
                           p.recv_wall)) {
      worker_died(s, w, true);
      return false;
    }
    return true;
  }
  s->wcv.notify_one();
  return true;
}

// Drain complete frames off a BUSY worker. Exactly one result frame
// per plain task (core/worker_main.py sends gen_item only under
// streaming, which never routes here) — ledger released, frame
// forwarded verbatim, worker recycled. Returns false when w was freed.
bool worker_parse_frames(NdServer* s, Worker* w) {
  for (;;) {
    size_t have = w->inbuf.size() - w->in_off;
    if (have < 8) break;
    const unsigned char* hp = reinterpret_cast<const unsigned char*>(
        w->inbuf.data() + w->in_off);
    uint64_t flen = 0;  // cxx-wire: nd-frame-len >Q
    for (int i = 0; i < 8; i++) flen = (flen << 8) | hp[i];
    if (flen == 0 || flen > s->max_frame || w->state != kWBusy) {
      worker_died(s, w, true);  // protocol violation
      return false;
    }
    if (have < 8 + flen) break;
    std::string payload(w->inbuf.data() + w->in_off + 8,
                        static_cast<size_t>(flen));
    w->in_off += 8 + flen;
    if (!w->task_res.empty()) {
      // Release BEFORE the reply can reach the driver, matching the
      // Python path (done() frees capacity, then replies).
      std::lock_guard<std::mutex> g(s->lmu);
      for (const auto& kv : w->task_res) s->avail[kv.first] += kv.second;
      w->task_res.clear();
    }
    record_stat(s, "task_native", seconds_since(w->task_t0, Clock::now()));
    s->native_done.fetch_add(1);
    if (w->task_tm) {
      // Out-of-band dispatch timestamps, queued ahead of the result on
      // the same conn (the outbox is FIFO per connection): the driver
      // stashes the frame and attaches it to the reply it precedes —
      // warm tasks get daemon dispatch timing with zero Python here.
      char nums[160];
      snprintf(nums, sizeof(nums),
               "\"recv_ts\":%.6f,\"write_ts\":%.6f,\"forward_ts\":%.6f}",
               w->task_recv_wall, w->task_write_wall, wall_now());
      std::string tmf = "{\"type\":\"dispatch_timing\",\"tid\":";
      if (w->task_tid.empty())
        tmf.append("null");
      else
        json_escape(w->task_tid, &tmf);
      tmf.append(",");
      tmf.append(nums);
      send_to_driver(s, w->task_conn, std::move(tmf));
    }
    send_to_driver(s, w->task_conn, std::move(payload));
    if (!worker_now_idle(s, w)) return false;
  }
  if (w->in_off > 0 && w->in_off == w->inbuf.size()) {
    w->inbuf.clear();
    w->in_off = 0;
  }
  return true;
}

// Loop thread, wmu held. Returns false when the worker was freed.
bool worker_readable(NdServer* s, Worker* w) {
  char buf[65536];
  for (;;) {
    // MSG_DONTWAIT for the same dup()-shared-flags reason as
    // worker_flush: the description must stay blocking for Python.
    ssize_t r = recv(w->fd, buf, sizeof(buf), MSG_DONTWAIT);
    if (r > 0) {
      w->inbuf.append(buf, static_cast<size_t>(r));
      if (!worker_parse_frames(s, w)) return false;
      if (static_cast<size_t>(r) < sizeof(buf)) return true;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    worker_died(s, w, true);  // EOF or hard error
    return false;
  }
}

// Loop thread (from handle_frame). Native hand-off of an admitted
// plain task: "plain" is stamped by the driver's hybrid_frame only for
// non-streaming, non-traced, fetch-free, runtime_env-free tasks, so a
// nonempty res here was already precharged (a refusal returned before
// this point). Returns true when the frame was consumed natively —
// forwarded to an idle worker or parked on the bounded pending queue;
// false routes it to the Python cold path.
bool try_native_handoff(NdServer* s, Conn* c, const JValue& header,
                        const char* body, size_t body_len,
                        uint32_t flags, ResMap&& res,
                        Clock::time_point t0) {
  const JValue* pl = header.get("plain");
  if (pl == nullptr || pl->kind != JValue::BOOL || !pl->b) return false;
  if (body_len == 0) return false;
  if (!res.empty() && (flags & kFlagPrecharged) == 0) return false;
  std::string fid = header_str(&header, "fid");
  if (fid.empty()) return false;
  const JValue* hf = header.get("has_fn");
  bool has_fn = hf != nullptr && hf->kind == JValue::BOOL && hf->b;
  std::string tid = header_str(&header, "tid");
  // "tm": the driver wants dispatch wall-clock stamps (traced or
  // timeline-enabled runs); the untraced hot path never pays for the
  // extra clock reads or the timing frame.
  const JValue* tm = header.get("tm");
  bool want_tm = tm != nullptr &&
                 ((tm->kind == JValue::NUM && tm->num != 0) ||
                  (tm->kind == JValue::BOOL && tm->b));
  // Map the steady-clock arrival stamp onto the wall clock so the
  // reported recv_ts is the frame's true arrival, not this call.
  double recv_wall =
      want_tm ? wall_now() - seconds_since(t0, Clock::now()) : 0.0;

  std::lock_guard<std::mutex> g(s->wmu);
  if (s->workers.empty()) return false;
  Worker* pick = nullptr;
  bool idle_seen = false;
  bool fid_known = false;
  for (auto& kv : s->workers) {
    Worker* w = kv.second;
    bool knows = w->fids.count(fid) != 0;
    if (knows) fid_known = true;
    if (w->state != kWIdle) continue;
    idle_seen = true;
    if (knows) {
      pick = w;  // prefer a fid-warm worker
      break;
    }
    if (has_fn && pick == nullptr) pick = w;
  }
  if (pick != nullptr) {
    if (!start_native_task(s, pick, c->id, tid, fid, std::move(res),
                           body, body_len, t0, want_tm, recv_wall))
      worker_died(s, pick, true);  // driver gets the typed error
    return true;
  }
  // An idle worker without the fn: Python's cold path spreads the fn
  // (fn injection); nd_worker_release reports the fid back afterward.
  if (idle_seen) return false;
  if (!has_fn && !fid_known) return false;  // nobody can run it natively
  if (s->pending.size() >= s->pending_cap) {
    s->handoff_overflow.fetch_add(1);
    return false;  // overflow: the Python drainer pool absorbs the burst
  }
  PendingTask p;
  p.conn_id = c->id;
  p.tid = tid;
  p.fid = fid;
  p.has_fn = has_fn;
  p.res = std::move(res);
  p.body.assign(body, body_len);
  p.t0 = t0;
  p.want_tm = want_tm;
  p.recv_wall = recv_wall;
  s->pending.push_back(std::move(p));
  s->pending_count.store(s->pending.size());
  return true;
}

// Classify + handle one complete frame payload. Returns false when the
// conn was closed (malformed frame).
bool handle_frame(NdServer* s, Conn* c, const char* payload, size_t n) {
  Clock::time_point now = Clock::now();
  const char* body = payload;
  size_t body_len = n;
  JValue header;
  bool has_header = false;
  uint32_t flags = 0;

  if (n > 0 && payload[0] == '{') {
    // Cross-language JSON frame: the whole payload is the message.
    JParser p(payload, n);
    if (!p.parse(&header) || header.kind != JValue::OBJ) {
      close_conn(s, c);
      return false;
    }
    has_header = true;
    flags |= kFlagJson;
  } else if (n > 0 && payload[0] == 0x01) {
    // Hybrid frame: 0x01 | u32-LE header len | JSON header | body.
    if (n < 5) {
      close_conn(s, c);
      return false;
    }
    uint32_t hlen = 0;
    memcpy(&hlen, payload + 1, 4);  // cxx-wire: nd-hybrid-hlen <I
    if (5 + static_cast<uint64_t>(hlen) > n) {
      close_conn(s, c);
      return false;
    }
    JParser p(payload + 5, hlen);
    if (!p.parse(&header) || header.kind != JValue::OBJ) {
      close_conn(s, c);
      return false;
    }
    has_header = true;
    body = payload + 5 + hlen;
    body_len = n - 5 - hlen;
  }
  // else: opaque legacy pickle — Python handles everything.

  std::string mtype =
      has_header ? header_str(&header, "type") : std::string("opaque");

  // -- natively-handled fast paths ------------------------------------
  if (has_header && mtype == "ping" && s->ping_native.load()) {
    std::string reply = "{\"type\":\"pong\",\"node_id\":";
    {
      std::lock_guard<std::mutex> g(s->cfgmu);
      json_escape(s->node_id, &reply);
    }
    reply.append(",\"load\":");
    append_load(s, &reply);
    reply.push_back('}');
    record_stat(s, "ping", seconds_since(now, Clock::now()));
    return queue_frame(s, c, reply.data(), reply.size());
  }

  ResMap res;
  if (has_header && mtype == "task") {
    const JValue* sp = header.get("spillable");
    const JValue* resv = header.get("res");
    if (sp != nullptr && sp->kind == JValue::BOOL && sp->b &&
        resv != nullptr && parse_res(*resv, &res) && !res.empty()) {
      // Atomic check-and-charge (the Python daemon's admission block,
      // verbatim semantics): refusal never queues the task here.
      bool ok;
      {
        std::lock_guard<std::mutex> g(s->lmu);
        ok = res_fits(res, s->avail);
        if (ok)
          for (const auto& kv : res) s->avail[kv.first] -= kv.second;
      }
      if (!ok) {
        s->spilled.fetch_add(1);
        std::set<std::string> exclude;
        {
          std::lock_guard<std::mutex> g(s->cfgmu);
          exclude.insert(s->node_id);
        }
        const JValue* ex = header.get("exclude");
        if (ex != nullptr && ex->kind == JValue::ARR)
          for (const JValue& v : ex->arr)
            if (v.kind == JValue::STR) exclude.insert(v.str);
        std::string reply = "{\"type\":\"result\",\"task_id\":";
        std::string tid = header_str(&header, "tid");
        if (tid.empty())
          reply.append("null");
        else
          json_escape(tid, &reply);
        reply.append(",\"spillback\":true,\"retry_at\":");
        std::string target = pick_spill_target(s, res, exclude);
        if (target.empty())
          reply.append("null");
        else
          json_escape(target, &reply);
        reply.append(",\"load\":");
        append_load(s, &reply);
        reply.push_back('}');
        record_stat(s, "spill_refusal",
                    seconds_since(now, Clock::now()));
        return queue_frame(s, c, reply.data(), reply.size());
      }
      flags |= kFlagPrecharged;
    }
  }

  // -- native worker hand-off (warm path: zero Python bytecode) -------
  if (has_header && mtype == "task" &&
      try_native_handoff(s, c, header, body, body_len, flags,
                         std::move(res), now))
    return true;

  // -- hand off to Python ---------------------------------------------
  // Request timing: close on the first reply nd_send queues for this
  // conn. Credit/notification types never get a reply — no timer.
  if (mtype != "gen_ack" && mtype != "pull_complete") {
    c->timing = true;
    c->timing_handler = mtype;
    c->timing_t0 = now;
  }
  Event e;
  e.conn_id = c->id;
  e.kind = 0;
  e.flags = flags;
  e.data = static_cast<char*>(malloc(body_len > 0 ? body_len : 1));
  if (e.data == nullptr) {
    close_conn(s, c);
    return false;
  }
  memcpy(e.data, body, body_len);
  e.len = body_len;
  push_event(s, std::move(e));
  return true;
}

// Extract complete frames from the conn's inbuf. Pauses the conn
// (EPOLLIN off → TCP backpressure on the driver) when the ready queue
// is full. Returns false when the conn died.
bool parse_frames(NdServer* s, Conn* c) {
  for (;;) {
    size_t have = c->inbuf.size() - c->in_off;
    if (have < 8) break;
    const unsigned char* hp = reinterpret_cast<const unsigned char*>(
        c->inbuf.data() + c->in_off);
    uint64_t flen = 0;  // cxx-wire: nd-frame-len >Q
    for (int i = 0; i < 8; i++) flen = (flen << 8) | hp[i];
    if (flen == 0 || flen > s->max_frame) {
      close_conn(s, c);
      return false;
    }
    if (have < 8 + flen) break;
    if (queue_full(s)) {
      if (!c->paused) {
        c->paused = true;
        s->paused_count.fetch_add(1);
        arm_events(s, c);
      }
      return true;  // frame stays buffered until Python catches up
    }
    // Consume the frame before handling: handle_frame may close the
    // conn (and free c) on malformed input.
    size_t off = c->in_off;
    c->in_off += 8 + flen;
    bool alive = handle_frame(s, c, c->inbuf.data() + off + 8,
                              static_cast<size_t>(flen));
    if (!alive) return false;
  }
  if (c->in_off > 0 && c->in_off == c->inbuf.size()) {
    c->inbuf.clear();
    c->in_off = 0;
  } else if (c->in_off > (1u << 20)) {
    c->inbuf.erase(0, c->in_off);
    c->in_off = 0;
  }
  return true;
}

void handle_readable(NdServer* s, Conn* c) {
  char buf[65536];
  for (;;) {
    if (c->paused) return;  // stop pulling bytes while Python is behind
    ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
    if (r > 0) {
      c->inbuf.append(buf, static_cast<size_t>(r));
      if (!parse_frames(s, c)) return;
      if (static_cast<size_t>(r) < sizeof(buf)) return;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    close_conn(s, c);  // EOF or hard error
    return;
  }
}

void process_outbox(NdServer* s) {
  std::vector<Outgoing> batch;
  {
    std::lock_guard<std::mutex> g(s->omu);
    batch.swap(s->outbox);
  }
  for (Outgoing& o : batch) {
    Conn* c = nullptr;
    for (auto& kv : s->conns)
      if (kv.second->id == o.conn_id) {
        c = kv.second;
        break;
      }
    if (c == nullptr) continue;  // conn gone; reply dropped (as today)
    if (c->timing) {
      c->timing = false;
      record_stat(s, c->timing_handler,
                  seconds_since(c->timing_t0, o.t));
    }
    queue_frame(s, c, o.payload.data(), o.payload.size());
  }
}

void resume_paused(NdServer* s) {
  if (s->paused_count.load() == 0 || queue_full(s)) return;
  // Collect first: parse_frames may close (and erase) conns.
  std::vector<Conn*> paused;
  for (auto& kv : s->conns)
    if (kv.second->paused) paused.push_back(kv.second);
  for (Conn* c : paused) {
    if (queue_full(s)) break;
    c->paused = false;
    s->paused_count.fetch_sub(1);
    arm_events(s, c);
    parse_frames(s, c);
  }
}

void accept_ready(NdServer* s) {
  for (;;) {
    int fd = accept4(s->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Conn* c = new Conn();
    c->fd = fd;
    c->id = s->next_conn_id++;
    s->conns[fd] = c;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLRDHUP;
    ev.data.fd = fd;
    epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, fd, &ev);
  }
}

void loop_main(NdServer* s) {
  epoll_event evs[64];
  while (!s->stop.load()) {
    int n = epoll_wait(s->ep_fd, evs, 64, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == s->listen_fd) {
        accept_ready(s);
        continue;
      }
      if (fd == s->event_fd) {
        uint64_t junk;
        while (read(s->event_fd, &junk, 8) == 8) {
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) {
        // Registered worker socket? (PY_OWNED fds were epoll-DELed at
        // acquire; a stale event from this batch is skipped by state.)
        std::lock_guard<std::mutex> g(s->wmu);
        auto wit = s->wfd.find(fd);
        if (wit == s->wfd.end()) continue;
        auto wmi = s->workers.find(wit->second);
        if (wmi == s->workers.end()) continue;
        Worker* w = wmi->second;
        if (w->state == kWPyOwned) continue;
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
          worker_died(s, w, true);
          continue;
        }
        bool alive = true;
        if (evs[i].events & EPOLLOUT) {
          if (!worker_flush(s, w)) {
            worker_died(s, w, true);
            alive = false;
          } else {
            worker_arm(s, w);
          }
        }
        if (alive && (evs[i].events & (EPOLLIN | EPOLLRDHUP)))
          worker_readable(s, w);
        continue;
      }
      Conn* c = it->second;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        if (!handle_writable(s, c)) continue;
      }
      if (evs[i].events & (EPOLLIN | EPOLLRDHUP)) handle_readable(s, c);
    }
    process_outbox(s);
    resume_paused(s);
  }
  // Drain: wake any nd_next waiters so drainers exit.
  s->qcv.notify_all();
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI (ctypes; every call releases the GIL while it runs).
// ---------------------------------------------------------------------

extern "C" {

void* nd_create(int port, int bind_all, unsigned long long max_frame,
                int queue_cap) {
  NdServer* s = new NdServer();
  if (max_frame > 0) s->max_frame = max_frame;
  if (queue_cap > 0) s->queue_cap = static_cast<size_t>(queue_cap);
  s->pending_cap = s->queue_cap;  // one shared backpressure budget
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = bind_all ? htonl(INADDR_ANY)
                                  : htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) < 0 ||
      listen(s->listen_fd, 128) < 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);
  s->ep_fd = epoll_create1(0);
  s->event_fd = eventfd(0, EFD_NONBLOCK);
  if (s->ep_fd < 0 || s->event_fd < 0) {
    if (s->ep_fd >= 0) close(s->ep_fd);
    if (s->event_fd >= 0) close(s->event_fd);
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = s->listen_fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  ev.events = EPOLLIN;
  ev.data.fd = s->event_fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, s->event_fd, &ev);
  return s;
}

int nd_port(void* h) {
  return h != nullptr ? static_cast<NdServer*>(h)->port : -1;
}

int nd_start(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -1;
  s->loop_thread = std::thread(loop_main, s);
  return 0;
}

void nd_wake(NdServer* s) {
  uint64_t one = 1;
  ssize_t rc = write(s->event_fd, &one, 8);
  (void)rc;
}

int nd_next(void* h, int timeout_ms, unsigned long long* conn_id,
            int* kind, unsigned int* flags, char** data,
            unsigned long long* len) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -1;
  Event e;
  {
    std::unique_lock<std::mutex> g(s->qmu);
    // system_clock deadline on purpose: with a steady_clock wait_for,
    // libstdc++ uses pthread_cond_clockwait, which gcc's TSAN runtime
    // does not intercept — every wait would look like a held mutex. A
    // clock jump only stretches one 200ms poll tick.
    if (!s->qcv.wait_until(
            g,
            std::chrono::system_clock::now() +
                std::chrono::milliseconds(timeout_ms),
            [&] { return s->stop.load() || !s->queue.empty(); }))
      return 0;  // timeout
    if (s->queue.empty()) return -1;  // stopped
    e = std::move(s->queue.front());
    s->queue.pop_front();
  }
  if (s->paused_count.load() > 0) nd_wake(s);  // room freed: resume
  *conn_id = e.conn_id;
  *kind = e.kind;
  *flags = e.flags;
  *data = e.data;
  *len = e.len;
  return 1;
}

void nd_free(char* data) { free(data); }

int nd_send(void* h, unsigned long long conn_id, const char* data,
            unsigned long long len) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.load()) return -1;
  Outgoing o;
  o.conn_id = conn_id;
  o.payload.assign(data, static_cast<size_t>(len));
  o.t = Clock::now();
  {
    std::lock_guard<std::mutex> g(s->omu);
    s->outbox.push_back(std::move(o));
  }
  nd_wake(s);
  return 0;
}

void nd_set_node_id(void* h, const char* node_id) {
  NdServer* s = static_cast<NdServer*>(h);
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->node_id = node_id != nullptr ? node_id : "";
}

void nd_set_load_tail(void* h, const char* tail) {
  NdServer* s = static_cast<NdServer*>(h);
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->load_tail = (tail != nullptr && tail[0] != '\0') ? tail : "}";
}

int nd_set_peers_json(void* h, const char* json) {
  NdServer* s = static_cast<NdServer*>(h);
  JValue v;
  JParser p(json, json != nullptr ? strlen(json) : 0);
  if (json == nullptr || !p.parse(&v) || v.kind != JValue::ARR)
    return -1;
  std::vector<Peer> peers;
  for (const JValue& pv : v.arr) {
    if (pv.kind != JValue::OBJ) return -1;
    Peer peer;
    const JValue* id = pv.get("id");
    if (id == nullptr || id->kind != JValue::STR) return -1;
    peer.id = id->str;
    const JValue* q = pv.get("queued");
    if (q != nullptr && q->kind == JValue::NUM)
      peer.queued = static_cast<int64_t>(q->num);
    const JValue* hr = pv.get("headroom");
    if (hr != nullptr && hr->kind == JValue::NUM) peer.headroom = hr->num;
    const JValue* av = pv.get("avail");
    if (av != nullptr && !parse_res(*av, &peer.avail)) return -1;
    peers.push_back(std::move(peer));
  }
  std::lock_guard<std::mutex> g(s->cfgmu);
  s->peers.swap(peers);
  return 0;
}

void nd_set_ping_native(void* h, int enabled) {
  static_cast<NdServer*>(h)->ping_native.store(enabled != 0);
}

// -- idle-worker registry (native hand-off) ----------------------------
// The worker speaks the daemon↔worker framed-pickle protocol on fd:
// 8-byte big-endian length + cloudpickle payload, one result frame per
// plain task (core/worker_proc.py).  // cxx-wire: nd-frame-len >Q

// Register a worker socket. The registry dups fd (Python keeps its
// own), epoll-adds it, and the worker is immediately eligible — it may
// start serving the pending queue before this returns. fids_csv is a
// comma-separated list of hex fn ids the worker has cached.
int nd_worker_register(void* h, unsigned long long wid, int fd, int pid,
                       const char* fids_csv) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.load() || fd < 0) return -1;
  int dupfd = dup(fd);
  if (dupfd < 0) return -1;
  // NO set_nonblock: dup() shares file-status flags with Python's
  // blocking socket object; the loop uses MSG_DONTWAIT per call.
  Worker* w = new Worker();
  w->wid = wid;
  w->fd = dupfd;
  w->pid = pid;
  parse_csv(fids_csv, &w->fids);
  std::lock_guard<std::mutex> g(s->wmu);
  auto old = s->workers.find(wid);
  if (old != s->workers.end()) {  // re-register: drop the stale entry
    Worker* ow = old->second;
    s->wfd.erase(ow->fd);
    epoll_ctl(s->ep_fd, EPOLL_CTL_DEL, ow->fd, nullptr);
    close(ow->fd);
    s->workers.erase(old);
    delete ow;
  }
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP;
  ev.data.fd = w->fd;
  if (epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, w->fd, &ev) != 0) {
    close(w->fd);
    delete w;
    return -1;
  }
  s->workers[wid] = w;
  s->wfd[w->fd] = wid;
  worker_now_idle(s, w);  // may serve pending right away
  return 0;
}

// Deliberate removal (retire/discard): no death event, but an
// in-flight native task still gets its typed error + ledger release.
// Returns 1 removed, 0 unknown wid.
int nd_worker_unregister(void* h, unsigned long long wid) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -1;
  std::lock_guard<std::mutex> g(s->wmu);
  auto it = s->workers.find(wid);
  if (it == s->workers.end()) return 0;
  worker_died(s, it->second, false);
  return 1;
}

// Check an idle worker out for the Python cold path. Its fd leaves the
// epoll set (Python speaks on the socket until release/unregister).
// Returns the wid (>= 0 — ids start at 0, so sentinels are negative):
// -1 on timeout, -2 when stopped.
long long nd_worker_acquire(void* h, int timeout_ms) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return -2;
  std::unique_lock<std::mutex> g(s->wmu);
  Worker* w = nullptr;
  auto find_idle = [&s]() -> Worker* {
    for (auto& kv : s->workers)
      if (kv.second->state == kWIdle) return kv.second;
    return nullptr;
  };
  // system_clock deadline on purpose — same TSAN rationale as nd_next.
  if (!s->wcv.wait_until(
          g,
          std::chrono::system_clock::now() +
              std::chrono::milliseconds(timeout_ms),
          [&] { return s->stop.load() || (w = find_idle()) != nullptr; }))
    return -1;
  if (w == nullptr) return -2;  // stopped
  w->state = kWPyOwned;
  w->state_t0 = Clock::now();
  epoll_ctl(s->ep_fd, EPOLL_CTL_DEL, w->fd, nullptr);
  return static_cast<long long>(w->wid);
}

// Return a PY_OWNED worker to the registry (fids_csv syncs fn ids the
// Python run exported). May serve the pending queue from the calling
// thread. Returns 1 when known, 0 when the wid is not registered (the
// caller falls back to nd_worker_register).
int nd_worker_release(void* h, unsigned long long wid,
                      const char* fids_csv) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.load()) return 0;
  std::lock_guard<std::mutex> g(s->wmu);
  auto it = s->workers.find(wid);
  if (it == s->workers.end()) return 0;
  Worker* w = it->second;
  parse_csv(fids_csv, &w->fids);
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP;
  ev.data.fd = w->fd;
  epoll_ctl(s->ep_fd, EPOLL_CTL_ADD, w->fd, &ev);
  worker_now_idle(s, w);
  return 1;
}

// Per-worker snapshot for shm attribution: BUSY entries carry the hex
// task id so natively-running tasks stay labeled in load reports.
// Every entry carries the seconds since its last state transition
// ("age_s") — the outstanding-resource ledger's acquire-age.
int nd_workers_json(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  Clock::time_point now = Clock::now();
  std::string out = "[";
  {
    std::lock_guard<std::mutex> g(s->wmu);
    bool first = true;
    for (const auto& kv : s->workers) {
      const Worker* w = kv.second;
      if (!first) out.push_back(',');
      first = false;
      char head[96];
      snprintf(head, sizeof(head), "{\"wid\":%llu,\"pid\":%d,\"state\":",
               static_cast<unsigned long long>(w->wid), w->pid);
      out.append(head);
      out.append(w->state == kWBusy
                     ? "\"busy\""
                     : (w->state == kWPyOwned ? "\"py\"" : "\"idle\""));
      char age[40];
      snprintf(age, sizeof(age), ",\"age_s\":%.3f",
               seconds_since(w->state_t0, now));
      out.append(age);
      if (w->state == kWBusy) {
        out.append(",\"tid\":");
        json_escape(w->task_tid, &out);
      }
      out.push_back('}');
    }
  }
  out.push_back(']');
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// Hand-off plane counters (load-report merge + the zero-Python test).
int nd_handoff_json(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  Clock::time_point now = Clock::now();
  size_t idle = 0, busy = 0, py = 0, nworkers = 0, npending = 0;
  double oldest_pending = 0.0;
  {
    std::lock_guard<std::mutex> g(s->wmu);
    nworkers = s->workers.size();
    npending = s->pending.size();
    for (const auto& p : s->pending) {
      double age = seconds_since(p.t0, now);
      if (age > oldest_pending) oldest_pending = age;
    }
    for (const auto& kv : s->workers) {
      if (kv.second->state == kWBusy)
        busy++;
      else if (kv.second->state == kWPyOwned)
        py++;
      else
        idle++;
    }
  }
  char out[384];
  int n = snprintf(
      out, sizeof(out),
      "{\"workers\":%zu,\"idle\":%zu,\"busy\":%zu,\"py_owned\":%zu,"
      "\"pending\":%zu,\"oldest_pending_s\":%.3f,\"handoffs\":%llu,"
      "\"completed\":%llu,\"worker_deaths\":%llu,\"overflow\":%llu}",
      nworkers, idle, busy, py, npending, oldest_pending,
      static_cast<unsigned long long>(s->handoffs.load()),
      static_cast<unsigned long long>(s->native_done.load()),
      static_cast<unsigned long long>(s->worker_deaths.load()),
      static_cast<unsigned long long>(s->handoff_overflow.load()));
  if (n < 0 || n + 1 > cap) return -1;
  memcpy(buf, out, static_cast<size_t>(n) + 1);
  return n;
}

// -- resource ledger ---------------------------------------------------

int nd_ledger_set(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  s->avail.swap(r);
  return 0;
}

int nd_ledger_try_charge(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  if (!res_fits(r, s->avail)) return 0;
  for (const auto& kv : r) s->avail[kv.first] -= kv.second;
  return 1;
}

// Unconditional subtract — except it must not drive availability
// negative silently: ResourceSet.subtract raises, so the Python
// wrapper turns -1 into the same ValueError.
int nd_ledger_charge(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -2;
  std::lock_guard<std::mutex> g(s->lmu);
  for (const auto& kv : r) {
    auto it = s->avail.find(kv.first);
    if ((it == s->avail.end() ? 0 : it->second) < kv.second) return -1;
  }
  for (const auto& kv : r) s->avail[kv.first] -= kv.second;
  return 0;
}

int nd_ledger_release(void* h, const char* json_res) {
  NdServer* s = static_cast<NdServer*>(h);
  ResMap r;
  if (!parse_res_str(json_res, &r)) return -1;
  std::lock_guard<std::mutex> g(s->lmu);
  for (const auto& kv : r) s->avail[kv.first] += kv.second;
  return 0;
}

int nd_ledger_get(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  std::string out;
  {
    std::lock_guard<std::mutex> g(s->lmu);
    res_to_json(s->avail, &out);
  }
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// -- stats -------------------------------------------------------------

unsigned long long nd_spilled(void* h) {
  return static_cast<NdServer*>(h)->spilled.load();
}

int nd_stats_json(void* h, char* buf, int cap) {
  NdServer* s = static_cast<NdServer*>(h);
  std::string out = "{";
  {
    std::lock_guard<std::mutex> g(s->smu);
    bool first = true;
    for (const auto& kv : s->stats) {
      if (!first) out.push_back(',');
      first = false;
      json_escape(kv.first, &out);
      char num[160];
      uint32_t ring[256];
      const Stat& st = kv.second;
      memcpy(ring, st.ring_us,
             sizeof(uint32_t) * static_cast<size_t>(st.ring_n));
      double p95 = 0.0;
      if (st.ring_n > 0) {
        std::sort(ring, ring + st.ring_n);
        int idx = static_cast<int>(0.95 * (st.ring_n - 1) + 0.5);
        p95 = ring[idx] / 1e6;
      }
      snprintf(num, sizeof(num),
               ":{\"count\":%llu,\"total_s\":%.9g,\"max_s\":%.9g,"
               "\"p95_s\":%.9g}",
               static_cast<unsigned long long>(st.count), st.total_s,
               st.max_s, p95);
      out.append(num);
    }
  }
  out.push_back('}');
  if (static_cast<int>(out.size()) + 1 > cap) return -1;
  memcpy(buf, out.data(), out.size());
  buf[out.size()] = '\0';
  return static_cast<int>(out.size());
}

// -- lifecycle ---------------------------------------------------------

void nd_stop(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr || s->stop.exchange(true)) return;
  nd_wake(s);
  s->wcv.notify_all();  // nd_worker_acquire waiters see stop
  if (s->loop_thread.joinable()) s->loop_thread.join();
  for (auto& kv : s->conns) {
    close(kv.second->fd);
    delete kv.second;
  }
  s->conns.clear();
  {
    std::lock_guard<std::mutex> g(s->wmu);
    for (auto& kv : s->workers) {
      close(kv.second->fd);
      delete kv.second;
    }
    s->workers.clear();
    s->wfd.clear();
    s->pending.clear();
    s->pending_count.store(0);
  }
  close(s->listen_fd);
  close(s->ep_fd);
  close(s->event_fd);
  // Free any undrained message bodies.
  std::lock_guard<std::mutex> g(s->qmu);
  for (Event& e : s->queue) free(e.data);
  s->queue.clear();
  s->qcv.notify_all();
}

// Safe only after nd_stop AND after every drainer thread has returned
// from nd_next — the Python side joins its drainers first.
void nd_destroy(void* h) {
  NdServer* s = static_cast<NdServer*>(h);
  if (s == nullptr) return;
#if defined(__SANITIZE_THREAD__)
  // libstdc++'s std::mutex / condition_variable destructors are
  // trivial on Linux, so TSAN never sees them die; a later server
  // allocated at the same address would inherit their sync state and
  // report phantom double-locks. Make the destruction visible.
  pthread_cond_destroy(s->qcv.native_handle());
  pthread_cond_destroy(s->wcv.native_handle());
  pthread_mutex_destroy(s->qmu.native_handle());
  pthread_mutex_destroy(s->omu.native_handle());
  pthread_mutex_destroy(s->lmu.native_handle());
  pthread_mutex_destroy(s->smu.native_handle());
  pthread_mutex_destroy(s->cfgmu.native_handle());
  pthread_mutex_destroy(s->wmu.native_handle());
#endif
  delete s;
}

}  // extern "C"

