// C++ client API for the ray_tpu runtime's native planes.
//
// Capability-reference: the reference ships a C++ language API
// (reference: cpp/include/ray/api/*.h — ray::Init, ray::Put/Get over
// the plasma store, actor/task calls through the C++ core worker).
// Here the C++ surface covers the native planes a C++ process talks to
// directly — the shared-memory object store (zero-copy Put/Get/
// channels) and the control plane (KV, pubsub, node/actor/job tables);
// task/actor *submission* stays in the Python runtime, which is the
// documented scope difference (PARITY.md §2.1 "C++ worker API").
//
// Both clients are wire/ABI-compatible with the Python bindings
// (ray_tpu/_native/shm_store.py, control_client.py): a C++ process and
// a Python process attach the same arena / daemon and exchange data.

#ifndef RAY_TPU_CLIENT_H_
#define RAY_TPU_CLIENT_H_

#include <array>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {

constexpr int kObjectIdLen = 28;  // mirrors shm_store.cc kIdLen

using ObjectID = std::array<uint8_t, kObjectIdLen>;

// Deterministic id from a string name (for cross-language rendezvous
// on well-known ids; cryptographic strength is not required here).
ObjectID IdFromName(const std::string& name);

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

// ---------------------------------------------------------------------------
// Shared-memory object store (reference: cpp plasma client usage)
// ---------------------------------------------------------------------------

class ObjectStoreClient {
 public:
  // Attach (or create) the named arena, e.g. "/ray_tpu_<session>".
  ObjectStoreClient(const std::string& name, uint64_t capacity = 0,
                    bool create = false);
  ~ObjectStoreClient();
  ObjectStoreClient(const ObjectStoreClient&) = delete;
  ObjectStoreClient& operator=(const ObjectStoreClient&) = delete;

  // Copy `data` into a new sealed object. Throws on duplicate/full.
  void Put(const ObjectID& id, const void* data, uint64_t size);

  // Zero-copy view of a sealed object (valid while pinned; callers
  // that need the data past Release must copy). pin=true increments
  // the pin count — call Release(id) when done.
  struct Buffer {
    const uint8_t* data;
    uint64_t size;
  };
  Buffer Get(const ObjectID& id, bool pin = true);
  void Release(const ObjectID& id);
  bool Contains(const ObjectID& id);
  void Delete(const ObjectID& id);

  // Mutable channel objects (seqlock; compiled-DAG channels).
  void ChannelCreate(const ObjectID& id, uint64_t max_size);
  void ChannelWrite(const ObjectID& id, const void* data, uint64_t size);
  // Returns false if no stable version is available yet.
  bool ChannelRead(const ObjectID& id, std::vector<uint8_t>* out,
                   uint64_t* version);

  uint64_t Used();
  uint64_t Capacity();
  uint64_t NumObjects();

 private:
  void* handle_;
  uint8_t* base_;
};

// ---------------------------------------------------------------------------
// Control plane client (reference: cpp GcsClient usage)
// ---------------------------------------------------------------------------

class ControlClient {
 public:
  ControlClient(const std::string& host, int port,
                double timeout_s = 30.0);
  ~ControlClient();
  ControlClient(const ControlClient&) = delete;
  ControlClient& operator=(const ControlClient&) = delete;

  void Ping();

  // KV (reference: InternalKVAccessor).
  void KvPut(const std::string& key, const std::string& value,
             bool overwrite = true);
  // Returns false when the key is absent.
  bool KvGet(const std::string& key, std::string* value);
  bool KvDel(const std::string& key);
  bool KvExists(const std::string& key);
  std::vector<std::string> KvKeys(const std::string& prefix);

  // Pubsub: publish now; subscription drains pushes received so far
  // (poll-style — the Python client owns the callback thread model).
  void Publish(const std::string& channel, const std::string& payload);
  void Subscribe(const std::string& channel);
  // Non-blocking-ish: reads frames already buffered on the socket for
  // up to timeout_s, appending (channel, payload) pairs.
  std::vector<std::pair<std::string, std::string>> PollPushes(
      double timeout_s);

  // Tables.
  std::vector<std::string> ListNodes();         // node ids
  std::map<std::string, uint64_t> Stats();      // op -> count

 private:
  std::vector<uint8_t> Request(uint8_t op,
                               const std::vector<uint8_t>& body);
  void SendFrame(const std::vector<uint8_t>& frame_body);
  bool ReadFrame(std::vector<uint8_t>* body, double timeout_s);

  int fd_;
  uint64_t req_id_ = 0;
  double timeout_s_;
  std::vector<uint8_t> rxbuf_;  // partial-frame carryover
  std::vector<std::pair<std::string, std::string>> pushes_;
};

// Task/actor submission from C++ — the cross-language worker surface
// (reference capability: cpp/ worker submitting tasks by
// FunctionDescriptor + msgpack args, python/ray/cross_language.py).
// Speaks the node daemon's dispatch protocol with JSON frames: a task
// is a qualified Python name + JSON-encoded args; results come back as
// JSON. Actors created here live on the daemon and die with this
// client's connection (or on the daemon's actor_kill).
class TaskClient {
 public:
  TaskClient(const std::string& host, int port);
  ~TaskClient();
  TaskClient(const TaskClient&) = delete;
  TaskClient& operator=(const TaskClient&) = delete;

  // "math.hypot" with args_json "[3, 4]" → "5.0" (JSON result).
  // args_json may be a JSON array (positional) or object (kwargs).
  std::string SubmitPyTask(const std::string& qualname,
                           const std::string& args_json);

  // Create a Python actor by class qualname; returns its actor id.
  std::string CreatePyActor(const std::string& qualname,
                            const std::string& args_json);
  // Call a method on it; returns the JSON result.
  std::string CallPyActor(const std::string& actor_id,
                          const std::string& method,
                          const std::string& args_json);

  // -- pipelined (async) submission ---------------------------------
  // Reference capability: the C++ API's asynchronous task callers
  // (cpp/include/ray/api/task_caller.h) — K submissions in flight
  // before the first reply. The daemon processes one connection's
  // frames strictly in order and replies in order, so the pipeline IS
  // the per-actor sequence (the actor_submit_queue.h sequence-number
  // idea realized by the transport): ordering holds with any mix of
  // async and sync calls on one client. Wait(ticket) returns the JSON
  // result or throws Error with the remote failure.
  uint64_t SubmitPyTaskAsync(const std::string& qualname,
                             const std::string& args_json);
  uint64_t CallPyActorAsync(const std::string& actor_id,
                            const std::string& method,
                            const std::string& args_json);
  std::string Wait(uint64_t ticket);

 private:
  std::string Roundtrip(const std::string& json_msg);
  uint64_t SendAsync(const std::string& json_msg);
  // Reads one length-prefixed reply frame off the socket. Called with
  // mu_ RELEASED — rx_busy_ makes the caller the sole reader, so two
  // threads never interleave partial frames.
  std::string ReadFrame();

  int fd_;
  std::mutex mu_;
  // Designated-reader handoff: exactly one waiter reads the socket
  // with mu_ dropped (rx_busy_ set); the rest sleep on cv_ and
  // re-check done_ whenever a reply is published.
  std::condition_variable cv_;
  bool rx_busy_ = false;
  uint64_t next_ticket_ = 1;
  std::deque<uint64_t> inflight_;               // send order = reply order
  std::map<uint64_t, std::pair<bool, std::string>> done_;  // ok, payload
};

}  // namespace ray_tpu

#endif  // RAY_TPU_CLIENT_H_
