// Cross-language smoke test driven by tests/test_cpp_api.py.
//
// argv: <mode> <arena_name> <host> <port>
//   mode "produce": put an object + channel write + KV puts, then exit
//   mode "consume": read the object Python wrote, echo KV, publish
//
// Prints "OK <detail>" lines; any failure throws and exits nonzero.

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

#include "ray_tpu/client.h"

using ray_tpu::ControlClient;
using ray_tpu::IdFromName;
using ray_tpu::ObjectStoreClient;

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: smoke_test <mode> <arena> <host> <port>\n");
    return 2;
  }
  std::string mode = argv[1];
  std::string arena = argv[2];
  std::string host = argv[3];
  int port = std::atoi(argv[4]);

  if (mode == "tasks") {
    // Task/actor submission from C++ (host/port = a NODE DAEMON's
    // dispatch endpoint; the arena argument is unused: "-").
    ray_tpu::TaskClient tasks(host, port);
    std::string r = tasks.SubmitPyTask("math.hypot", "[3, 4]");
    std::printf("OK task=%s\n", r.c_str());
    std::string aid = tasks.CreatePyActor("builtins.list",
                                          "[[\"a\"]]");
    std::printf("OK actor=%zu\n", aid.size());
    tasks.CallPyActor(aid, "append", "[\"b\"]");
    std::string copy = tasks.CallPyActor(aid, "copy", "[]");
    std::printf("OK actor_state=%s\n", copy.c_str());

    // Pipelined: K submissions in flight BEFORE the first Wait, mixed
    // tasks + ordered actor calls, results claimed out of order.
    std::vector<uint64_t> tickets;
    for (int i = 0; i < 8; i++) {
      char args[32];
      std::snprintf(args, sizeof(args), "[%d, %d]", 3 * i, 4 * i);
      tickets.push_back(tasks.SubmitPyTaskAsync("math.hypot", args));
    }
    for (int i = 0; i < 4; i++)
      tickets.push_back(tasks.CallPyActorAsync(aid, "append", "[1]"));
    tickets.push_back(tasks.CallPyActorAsync(aid, "__len__", "[]"));
    // Claim the LAST first (out-of-order wait over the pipeline).
    std::string len = tasks.Wait(tickets.back());
    if (len != "6") {  // ["a","b"] + 4 appends → 6
      std::fprintf(stderr, "pipelined actor order broken: len=%s\n",
                   len.c_str());
      return 1;
    }
    for (int i = 0; i < 8; i++) {
      std::string got = tasks.Wait(tickets[i]);
      char expect[32];
      std::snprintf(expect, sizeof(expect), "%.1f", 5.0 * i);
      if (got != expect) {
        std::fprintf(stderr, "pipelined task %d: %s != %s\n", i,
                     got.c_str(), expect);
        return 1;
      }
    }
    std::printf("OK pipelined=13\n");
    return 0;
  }

  if (mode == "tasks-threaded") {
    // One TaskClient shared by several threads, each pipelining its
    // own submissions and claiming its own tickets. Exercises the
    // designated-reader Wait: whichever thread holds the socket
    // publishes replies for everyone; the others sleep on the cv
    // until their ticket lands in done_.
    ray_tpu::TaskClient tasks(host, port);
    const int kThreads = 4;
    const int kPerThread = 8;
    std::atomic<int> failures{0};
    std::vector<std::thread> threads;
    for (int t = 0; t < kThreads; t++) {
      threads.emplace_back([&tasks, &failures, t]() {
        try {
          std::vector<uint64_t> tickets;
          for (int i = 0; i < kPerThread; i++) {
            int k = t * kPerThread + i;
            char args[32];
            std::snprintf(args, sizeof(args), "[%d, %d]", 3 * k,
                          4 * k);
            tickets.push_back(
                tasks.SubmitPyTaskAsync("math.hypot", args));
          }
          // Claim newest-first so most waits target a ticket BEHIND
          // the socket's reply cursor — the waiter must drain other
          // threads' replies (or sleep while another thread does).
          for (int i = kPerThread - 1; i >= 0; i--) {
            int k = t * kPerThread + i;
            std::string got = tasks.Wait(tickets[i]);
            char expect[32];
            std::snprintf(expect, sizeof(expect), "%.1f", 5.0 * k);
            if (got != expect) {
              std::fprintf(stderr, "thread %d ticket %d: %s != %s\n",
                           t, i, got.c_str(), expect);
              failures++;
              return;
            }
          }
        } catch (const std::exception& e) {
          std::fprintf(stderr, "thread %d: %s\n", t, e.what());
          failures++;
        }
      });
    }
    for (auto& th : threads) th.join();
    if (failures.load() != 0) return 1;
    std::printf("OK threaded=%d\n", kThreads * kPerThread);
    return 0;
  }

  ObjectStoreClient store(arena);
  ControlClient ctl(host, port);
  ctl.Ping();
  std::printf("OK connected used=%llu cap=%llu\n",
              (unsigned long long)store.Used(),
              (unsigned long long)store.Capacity());

  if (mode == "produce") {
    const char* payload = "hello from c++";
    store.Put(IdFromName("cpp-object"), payload, std::strlen(payload));
    store.ChannelCreate(IdFromName("cpp-channel"), 128);
    store.ChannelWrite(IdFromName("cpp-channel"), "tick-1", 6);
    ctl.KvPut("cpp/greeting", "bonjour");
    std::printf("OK produced objects=%llu\n",
                (unsigned long long)store.NumObjects());
  } else if (mode == "consume") {
    auto buf = store.Get(IdFromName("py-object"));
    std::string text(reinterpret_cast<const char*>(buf.data), buf.size);
    store.Release(IdFromName("py-object"));
    std::printf("OK object=%s\n", text.c_str());

    std::vector<uint8_t> ch;
    uint64_t version = 0;
    if (!store.ChannelRead(IdFromName("py-channel"), &ch, &version)) {
      std::fprintf(stderr, "channel read failed\n");
      return 1;
    }
    std::printf("OK channel=%s v=%llu\n",
                std::string(ch.begin(), ch.end()).c_str(),
                (unsigned long long)version);

    std::string v;
    if (!ctl.KvGet("py/greeting", &v)) {
      std::fprintf(stderr, "kv missing\n");
      return 1;
    }
    std::printf("OK kv=%s keys=%zu\n", v.c_str(),
                ctl.KvKeys("py/").size());
    ctl.KvPut("cpp/echo", v + "+cpp");
    ctl.Publish("cpp-events", "done");
    std::printf("OK stats_ops=%zu nodes=%zu\n", ctl.Stats().size(),
                ctl.ListNodes().size());
  } else {
    std::fprintf(stderr, "unknown mode %s\n", mode.c_str());
    return 2;
  }
  return 0;
}
