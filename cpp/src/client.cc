// C++ client implementation. See include/ray_tpu/client.h.
//
// Links against the same libshm_store.so the Python bindings load (the
// arena protocol lives in shared memory; both languages are peers) and
// speaks the control-plane wire protocol documented at the top of
// src/control_plane.cc ([u32 len][u8 type][body]; request body =
// [u64 req_id][u8 op][args]).

#include "ray_tpu/client.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>

// ---------------------------------------------------------------------------
// extern "C" surface of libshm_store.so
// ---------------------------------------------------------------------------
extern "C" {
void* rts_connect(const char* name, uint64_t capacity, int create);
void rts_disconnect(void* handle);
int rts_create(void* handle, const uint8_t* id, uint64_t size,
               uint64_t* offset_out);
int rts_seal(void* handle, const uint8_t* id);
int rts_get(void* handle, const uint8_t* id, uint64_t* offset_out,
            uint64_t* size_out, int pin);
int rts_release(void* handle, const uint8_t* id);
int rts_contains(void* handle, const uint8_t* id);
int rts_delete(void* handle, const uint8_t* id);
uint64_t rts_used(void* handle);
uint64_t rts_capacity(void* handle);
uint64_t rts_num_objects(void* handle);
void* rts_base(void* handle);
int rts_ch_create(void* handle, const uint8_t* id, uint64_t max_size,
                  uint64_t* offset_out);
int rts_ch_write_acquire(void* handle, const uint8_t* id, uint64_t size,
                         uint64_t* offset_out);
int rts_ch_write_release(void* handle, const uint8_t* id);
int64_t rts_ch_read(void* handle, const uint8_t* id,
                    uint64_t* offset_out, uint64_t* size_out);
}

namespace ray_tpu {

ObjectID IdFromName(const std::string& name) {
  // FNV-1a stretched over the id width — matches no Python helper by
  // necessity (ids are opaque bytes on both sides); deterministic so
  // two processes can derive the same id from a shared name.
  ObjectID id{};
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : name) {
    h ^= c;
    h *= 1099511628211ull;
  }
  for (int i = 0; i < kObjectIdLen; i++) {
    id[i] = static_cast<uint8_t>(h >> ((i % 8) * 8));
    if (i % 8 == 7) {
      h ^= h >> 33;
      h *= 0xff51afd7ed558ccdull;
    }
  }
  return id;
}

// ---------------------------------------------------------------------------
// ObjectStoreClient
// ---------------------------------------------------------------------------

ObjectStoreClient::ObjectStoreClient(const std::string& name,
                                     uint64_t capacity, bool create) {
  handle_ = rts_connect(name.c_str(), capacity, create ? 1 : 0);
  if (handle_ == nullptr) {
    throw Error("cannot attach shm arena " + name);
  }
  base_ = static_cast<uint8_t*>(rts_base(handle_));
}

ObjectStoreClient::~ObjectStoreClient() {
  if (handle_ != nullptr) rts_disconnect(handle_);
}

void ObjectStoreClient::Put(const ObjectID& id, const void* data,
                            uint64_t size) {
  uint64_t off = 0;
  int rc = rts_create(handle_, id.data(), size, &off);
  if (rc == -1) throw Error("object already exists");
  if (rc == -2) throw Error("object store full");
  if (rc != 0) throw Error("object table full");
  std::memcpy(base_ + off, data, size);
  if (rts_seal(handle_, id.data()) != 0) throw Error("seal failed");
}

ObjectStoreClient::Buffer ObjectStoreClient::Get(const ObjectID& id,
                                                 bool pin) {
  uint64_t off = 0, size = 0;
  if (rts_get(handle_, id.data(), &off, &size, pin ? 1 : 0) != 0) {
    throw Error("object not found (or unsealed)");
  }
  return Buffer{base_ + off, size};
}

void ObjectStoreClient::Release(const ObjectID& id) {
  rts_release(handle_, id.data());
}

bool ObjectStoreClient::Contains(const ObjectID& id) {
  return rts_contains(handle_, id.data()) == 1;
}

void ObjectStoreClient::Delete(const ObjectID& id) {
  int rc = rts_delete(handle_, id.data());
  if (rc == -2) throw Error("object is pinned");
  if (rc != 0) throw Error("object not found");
}

void ObjectStoreClient::ChannelCreate(const ObjectID& id,
                                      uint64_t max_size) {
  uint64_t off = 0;
  int rc = rts_ch_create(handle_, id.data(), max_size, &off);
  if (rc == -1) throw Error("channel already exists");
  if (rc == -2) throw Error("object store full");
  if (rc != 0) throw Error("object table full");
}

void ObjectStoreClient::ChannelWrite(const ObjectID& id, const void* data,
                                     uint64_t size) {
  uint64_t off = 0;
  if (rts_ch_write_acquire(handle_, id.data(), size, &off) != 0) {
    throw Error("channel write acquire failed (missing or too large)");
  }
  std::memcpy(base_ + off, data, size);
  if (rts_ch_write_release(handle_, id.data()) != 0) {
    throw Error("channel write release failed");
  }
}

bool ObjectStoreClient::ChannelRead(const ObjectID& id,
                                    std::vector<uint8_t>* out,
                                    uint64_t* version) {
  for (int attempt = 0; attempt < 1000; attempt++) {
    uint64_t off = 0, size = 0;
    int64_t v = rts_ch_read(handle_, id.data(), &off, &size);
    if (v == -1) throw Error("channel not found");
    if (v == -2) {  // writer in progress — retry
      usleep(100);
      continue;
    }
    if (v == 0) return false;  // created but never written (matches
                               // the Python binding's size>0 gate)
    out->assign(base_ + off, base_ + off + size);
    // Seqlock validation: the version must be unchanged after the copy.
    uint64_t off2 = 0, size2 = 0;
    int64_t v2 = rts_ch_read(handle_, id.data(), &off2, &size2);
    if (v2 == v) {
      if (version != nullptr) *version = static_cast<uint64_t>(v);
      return true;
    }
  }
  return false;
}

uint64_t ObjectStoreClient::Used() { return rts_used(handle_); }
uint64_t ObjectStoreClient::Capacity() { return rts_capacity(handle_); }
uint64_t ObjectStoreClient::NumObjects() {
  return rts_num_objects(handle_);
}

// ---------------------------------------------------------------------------
// ControlClient
// ---------------------------------------------------------------------------

namespace {

enum Op : uint8_t {
  OP_PING = 0,
  OP_KV_PUT = 1,
  OP_KV_GET = 2,
  OP_KV_DEL = 3,
  OP_KV_KEYS = 4,
  OP_KV_EXISTS = 5,
  OP_SUBSCRIBE = 10,
  OP_UNSUBSCRIBE = 11,
  OP_PUBLISH = 12,
  OP_LIST_NODES = 22,
  OP_STATS = 50,
};

enum Status : uint8_t {
  ST_OK = 0,
  ST_NOT_FOUND = 1,
  ST_EXISTS = 2,
};

void put_u32(std::vector<uint8_t>* b, uint32_t v) {
  size_t n = b->size();
  b->resize(n + 4);
  std::memcpy(b->data() + n, &v, 4);
}

void put_u64(std::vector<uint8_t>* b, uint64_t v) {
  size_t n = b->size();
  b->resize(n + 8);
  std::memcpy(b->data() + n, &v, 8);
}

void put_str(std::vector<uint8_t>* b, const std::string& s) {
  put_u32(b, static_cast<uint32_t>(s.size()));
  b->insert(b->end(), s.begin(), s.end());
}

struct Cursor {
  const uint8_t* p;
  size_t left;

  uint8_t u8() {
    if (left < 1) throw Error("short response");
    uint8_t v = *p;
    p++;
    left--;
    return v;
  }
  uint32_t u32() {
    if (left < 4) throw Error("short response");
    uint32_t v;
    std::memcpy(&v, p, 4);
    p += 4;
    left -= 4;
    return v;
  }
  uint64_t u64() {
    if (left < 8) throw Error("short response");
    uint64_t v;
    std::memcpy(&v, p, 8);
    p += 8;
    left -= 8;
    return v;
  }
  std::string str() {
    uint32_t n = u32();
    if (left < n) throw Error("short response");
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    left -= n;
    return s;
  }
};

}  // namespace

ControlClient::ControlClient(const std::string& host, int port,
                             double timeout_s)
    : timeout_s_(timeout_s) {
  fd_ = socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw Error("socket() failed");
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    // Not a numeric address — resolve the hostname (the Python client
    // accepts "localhost" etc.; so must we).
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
        res == nullptr) {
      close(fd_);
      throw Error("cannot resolve host " + host);
    }
    addr.sin_addr =
        reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (connect(fd_, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd_);
    throw Error("cannot connect to control plane");
  }
}

ControlClient::~ControlClient() {
  if (fd_ >= 0) close(fd_);
}

void ControlClient::SendFrame(const std::vector<uint8_t>& frame_body) {
  std::vector<uint8_t> frame;
  put_u32(&frame, static_cast<uint32_t>(frame_body.size()));
  frame.insert(frame.end(), frame_body.begin(), frame_body.end());
  size_t sent = 0;
  while (sent < frame.size()) {
    ssize_t n = send(fd_, frame.data() + sent, frame.size() - sent, 0);
    if (n <= 0) throw Error("control plane send failed");
    sent += static_cast<size_t>(n);
  }
}

bool ControlClient::ReadFrame(std::vector<uint8_t>* body,
                              double timeout_s) {
  // All-or-nothing framing over a persistent receive buffer: a timeout
  // mid-frame leaves the partial bytes in rxbuf_ for the next call —
  // never desynchronizing the stream.
  while (true) {
    if (rxbuf_.size() >= 4) {
      uint32_t len;
      std::memcpy(&len, rxbuf_.data(), 4);
      if (rxbuf_.size() >= 4 + static_cast<size_t>(len)) {
        body->assign(rxbuf_.begin() + 4, rxbuf_.begin() + 4 + len);
        rxbuf_.erase(rxbuf_.begin(), rxbuf_.begin() + 4 + len);
        return true;
      }
    }
    pollfd pfd{fd_, POLLIN, 0};
    int pr = poll(&pfd, 1, static_cast<int>(timeout_s * 1000));
    if (pr <= 0) return false;
    uint8_t chunk[65536];
    ssize_t r = recv(fd_, chunk, sizeof(chunk), 0);
    if (r <= 0) throw Error("control plane connection closed");
    rxbuf_.insert(rxbuf_.end(), chunk, chunk + r);
  }
}

std::vector<uint8_t> ControlClient::Request(
    uint8_t op, const std::vector<uint8_t>& body) {
  req_id_++;
  std::vector<uint8_t> frame_body;
  frame_body.push_back(0);  // type: request
  put_u64(&frame_body, req_id_);
  frame_body.push_back(op);
  frame_body.insert(frame_body.end(), body.begin(), body.end());
  SendFrame(frame_body);

  // Read until OUR response; pushes received meanwhile are queued.
  std::vector<uint8_t> resp;
  while (true) {
    if (!ReadFrame(&resp, timeout_s_)) {
      throw Error("control plane request timed out");
    }
    if (resp.empty()) throw Error("empty frame");
    if (resp[0] == 0) {  // response
      if (resp.size() < 9) throw Error("short response frame");
      uint64_t rid;
      std::memcpy(&rid, resp.data() + 1, 8);
      if (rid != req_id_) continue;  // stale (shouldn't happen: sync)
      return std::vector<uint8_t>(resp.begin() + 9, resp.end());
    }
    Cursor c{resp.data() + 1, resp.size() - 1};
    std::string channel = c.str();
    std::string payload = c.str();
    pushes_.emplace_back(channel, payload);
  }
}

void ControlClient::Ping() { Request(OP_PING, {}); }

void ControlClient::KvPut(const std::string& key, const std::string& value,
                          bool overwrite) {
  std::vector<uint8_t> b;
  put_str(&b, key);
  put_str(&b, value);
  b.push_back(overwrite ? 1 : 0);
  auto r = Request(OP_KV_PUT, b);
  if (r.empty()) throw Error("kv put: empty response");
  if (r[0] == ST_EXISTS) throw Error("key exists (overwrite=false)");
  if (r[0] != ST_OK) {
    throw Error("kv put failed (status " + std::to_string(r[0]) + ")");
  }
}

bool ControlClient::KvGet(const std::string& key, std::string* value) {
  std::vector<uint8_t> b;
  put_str(&b, key);
  auto r = Request(OP_KV_GET, b);
  Cursor c{r.data(), r.size()};
  uint8_t st = c.u8();
  if (st == ST_NOT_FOUND) return false;
  if (st != ST_OK) throw Error("kv get failed");
  *value = c.str();
  return true;
}

bool ControlClient::KvDel(const std::string& key) {
  std::vector<uint8_t> b;
  put_str(&b, key);
  auto r = Request(OP_KV_DEL, b);
  return !r.empty() && r[0] == ST_OK;
}

bool ControlClient::KvExists(const std::string& key) {
  std::vector<uint8_t> b;
  put_str(&b, key);
  auto r = Request(OP_KV_EXISTS, b);
  Cursor c{r.data(), r.size()};
  if (c.u8() != ST_OK) throw Error("kv exists failed");
  return c.u8() == 1;
}

std::vector<std::string> ControlClient::KvKeys(const std::string& prefix) {
  std::vector<uint8_t> b;
  put_str(&b, prefix);
  auto r = Request(OP_KV_KEYS, b);
  Cursor c{r.data(), r.size()};
  if (c.u8() != ST_OK) throw Error("kv keys failed");
  uint32_t n = c.u32();
  std::vector<std::string> out;
  out.reserve(n);
  for (uint32_t i = 0; i < n; i++) out.push_back(c.str());
  return out;
}

void ControlClient::Publish(const std::string& channel,
                            const std::string& payload) {
  std::vector<uint8_t> b;
  put_str(&b, channel);
  put_str(&b, payload);
  Request(OP_PUBLISH, b);
}

void ControlClient::Subscribe(const std::string& channel) {
  std::vector<uint8_t> b;
  put_str(&b, channel);
  Request(OP_SUBSCRIBE, b);
}

std::vector<std::pair<std::string, std::string>> ControlClient::PollPushes(
    double timeout_s) {
  std::vector<uint8_t> frame;
  while (ReadFrame(&frame, timeout_s)) {
    if (frame.empty()) break;
    if (frame[0] != 0) {
      Cursor c{frame.data() + 1, frame.size() - 1};
      std::string channel = c.str();
      std::string payload = c.str();
      pushes_.emplace_back(channel, payload);
      timeout_s = 0.01;  // drain whatever else is buffered
    }
  }
  auto out = std::move(pushes_);
  pushes_.clear();
  return out;
}

std::vector<std::string> ControlClient::ListNodes() {
  auto r = Request(OP_LIST_NODES, {});
  Cursor c{r.data(), r.size()};
  if (c.u8() != ST_OK) throw Error("list nodes failed");
  uint32_t n = c.u32();
  std::vector<std::string> out;
  for (uint32_t i = 0; i < n; i++) {
    out.push_back(c.str());      // node_id
    c.str();                     // meta (opaque here)
    c.u8();                      // alive
    c.u8();                      // draining
    c.u64();                     // ms since last heartbeat
    c.str();                     // load report (opaque here)
  }
  return out;
}

std::map<std::string, uint64_t> ControlClient::Stats() {
  auto r = Request(OP_STATS, {});
  Cursor c{r.data(), r.size()};
  if (c.u8() != ST_OK) throw Error("stats failed");
  uint32_t n = c.u32();
  std::map<std::string, uint64_t> out;  // "op_<n>" -> call count
  for (uint32_t i = 0; i < n; i++) {
    uint8_t op = c.u8();
    uint64_t count = c.u64();
    c.u64();  // total_us
    out["op_" + std::to_string(op)] = count;
  }
  return out;
}

// ---------------------------------------------------------------------------
// TaskClient — dispatch-protocol JSON frames ([u64 big-endian len][json])
// ---------------------------------------------------------------------------

namespace {

// Minimal JSON string escaping for the fields this client sends.
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char ch : s) {
    switch (ch) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(ch) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", ch);
          out += buf;
        } else {
          out += ch;
        }
    }
  }
  return out;
}

// Extract a top-level string/raw value from the daemon's flat reply
// ({"type": "result", "result": ..., "error": ...}). The result is a
// JSON value returned VERBATIM as text; "__none__" when absent.
std::string JsonField(const std::string& doc, const std::string& key) {
  std::string pat = "\"" + key + "\":";
  size_t p = doc.find(pat);
  if (p == std::string::npos) return "__none__";
  p += pat.size();
  while (p < doc.size() && (doc[p] == ' ')) p++;
  if (p >= doc.size()) return "__none__";
  if (doc[p] == '"') {
    std::string out;
    for (size_t i = p + 1; i < doc.size(); i++) {
      if (doc[i] == '\\' && i + 1 < doc.size()) {
        char n = doc[++i];
        out += (n == 'n') ? '\n' : (n == 't') ? '\t' : n;
      } else if (doc[i] == '"') {
        return out;
      } else {
        out += doc[i];
      }
    }
    return out;
  }
  // Raw value (number/bool/null/array/object): scan to the matching
  // end at depth 0, skipping string contents (']' '}' ',' inside a
  // quoted string are data, not structure).
  int depth = 0;
  bool in_string = false;
  size_t i = p;
  for (; i < doc.size(); i++) {
    char ch = doc[i];
    if (in_string) {
      if (ch == '\\') i++;  // skip the escaped char
      else if (ch == '"') in_string = false;
      continue;
    }
    if (ch == '"') { in_string = true; continue; }
    if (ch == '[' || ch == '{') depth++;
    if (ch == ']' || ch == '}') {
      if (depth == 0) break;
      depth--;
    }
    if ((ch == ',') && depth == 0) break;
  }
  return doc.substr(p, i - p);
}

}  // namespace

TaskClient::TaskClient(const std::string& host, int port) {
  fd_ = socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw Error("socket failed");
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
        res == nullptr) {
      close(fd_);
      throw Error("cannot resolve host " + host);
    }
    addr.sin_addr =
        reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (connect(fd_, reinterpret_cast<sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(fd_);
    throw Error("cannot connect to node daemon");
  }
}

TaskClient::~TaskClient() {
  if (fd_ >= 0) close(fd_);
}

uint64_t TaskClient::SendAsync(const std::string& json_msg) {
  // [u64 BIG-ENDIAN length][payload] — the dispatch protocol's framing
  // (node/daemon.py; struct "!Q").
  uint64_t n = json_msg.size();
  uint8_t header[8];
  for (int i = 0; i < 8; i++)
    header[i] = static_cast<uint8_t>((n >> (8 * (7 - i))) & 0xff);
  std::string frame(reinterpret_cast<char*>(header), 8);
  frame += json_msg;
  std::lock_guard<std::mutex> lk(mu_);
  size_t sent = 0;
  while (sent < frame.size()) {
    ssize_t w = send(fd_, frame.data() + sent, frame.size() - sent, 0);
    if (w <= 0) throw Error("daemon send failed");
    sent += static_cast<size_t>(w);
  }
  uint64_t t = next_ticket_++;
  inflight_.push_back(t);
  return t;
}

std::string TaskClient::ReadFrame() {
  // Called with mu_ RELEASED; rx_busy_ guarantees a single reader, so
  // the two recv loops below never interleave with another thread's.
  // Dropping the mutex here is what lets other threads keep
  // pipelining SendAsync() while one waiter blocks in recv.
  uint8_t rh[8];
  size_t got = 0;
  while (got < 8) {
    ssize_t r = recv(fd_, rh + got, 8 - got, 0);
    if (r <= 0) throw Error("daemon connection closed");
    got += static_cast<size_t>(r);
  }
  uint64_t rlen = 0;
  for (int i = 0; i < 8; i++) rlen = (rlen << 8) | rh[i];
  if (rlen > (1ull << 30)) throw Error("oversized daemon reply");
  std::string resp(rlen, '\0');
  got = 0;
  while (got < rlen) {
    ssize_t r = recv(fd_, resp.data() + got, rlen - got, 0);
    if (r <= 0) throw Error("daemon connection closed");
    got += static_cast<size_t>(r);
  }
  return resp;
}

std::string TaskClient::Wait(uint64_t ticket) {
  std::unique_lock<std::mutex> lk(mu_);
  for (;;) {
    auto it = done_.find(ticket);
    if (it != done_.end()) {
      std::pair<bool, std::string> r = it->second;
      done_.erase(it);
      if (!r.first) throw Error(r.second);
      return r.second;
    }
    // A ticket that is neither done nor in flight (double-claimed or
    // never issued) can never resolve — waiting would never return.
    if (ticket >= next_ticket_ ||
        std::find(inflight_.begin(), inflight_.end(), ticket) ==
            inflight_.end())
      throw Error("unknown or already-claimed ticket");
    if (rx_busy_) {
      // Another waiter owns the socket; it publishes into done_ and
      // notifies after every frame (including on error, where it
      // clears rx_busy_ so a survivor can take over the read side).
      cv_.wait(lk);
      continue;
    }
    rx_busy_ = true;
    lk.unlock();
    std::string resp;
    try {
      resp = ReadFrame();
    } catch (...) {
      lk.lock();
      rx_busy_ = false;
      cv_.notify_all();
      throw;
    }
    lk.lock();
    rx_busy_ = false;
    // Responses arrive in submission order; this frame belongs to the
    // oldest in-flight ticket.
    if (inflight_.empty()) {
      cv_.notify_all();
      throw Error("daemon reply with no in-flight request");
    }
    uint64_t t = inflight_.front();
    inflight_.pop_front();
    std::string err = JsonField(resp, "error");
    if (err != "__none__" && err != "null")
      done_[t] = {false, "remote task failed: " + err};
    else
      done_[t] = {true, JsonField(resp, "result")};
    cv_.notify_all();
  }
}

std::string TaskClient::Roundtrip(const std::string& json_msg) {
  return Wait(SendAsync(json_msg));
}

uint64_t TaskClient::SubmitPyTaskAsync(const std::string& qualname,
                                       const std::string& args_json) {
  std::string msg = "{\"type\": \"task_xlang\", \"qualname\": \"" +
                    JsonEscape(qualname) + "\", \"args_json\": \"" +
                    JsonEscape(args_json) + "\"}";
  return SendAsync(msg);
}

uint64_t TaskClient::CallPyActorAsync(const std::string& actor_id,
                                      const std::string& method,
                                      const std::string& args_json) {
  std::string msg = "{\"type\": \"actor_call_xlang\", \"actor_id\": \"" +
                    JsonEscape(actor_id) + "\", \"method\": \"" +
                    JsonEscape(method) + "\", \"args_json\": \"" +
                    JsonEscape(args_json) + "\"}";
  return SendAsync(msg);
}

std::string TaskClient::SubmitPyTask(const std::string& qualname,
                                     const std::string& args_json) {
  std::string msg = "{\"type\": \"task_xlang\", \"qualname\": \"" +
                    JsonEscape(qualname) + "\", \"args_json\": \"" +
                    JsonEscape(args_json) + "\"}";
  return Roundtrip(msg);
}

std::string TaskClient::CreatePyActor(const std::string& qualname,
                                      const std::string& args_json) {
  std::string msg =
      "{\"type\": \"actor_create_xlang\", \"qualname\": \"" +
      JsonEscape(qualname) + "\", \"args_json\": \"" +
      JsonEscape(args_json) + "\"}";
  return Roundtrip(msg);
}

std::string TaskClient::CallPyActor(const std::string& actor_id,
                                    const std::string& method,
                                    const std::string& args_json) {
  std::string msg = "{\"type\": \"actor_call_xlang\", \"actor_id\": \"" +
                    JsonEscape(actor_id) + "\", \"method\": \"" +
                    JsonEscape(method) + "\", \"args_json\": \"" +
                    JsonEscape(args_json) + "\"}";
  return Roundtrip(msg);
}

}  // namespace ray_tpu
