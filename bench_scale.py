"""Scalability envelope — the reference's release benchmarks at this
box's scale (reference: release/benchmarks/README.md:5-31 — many_tasks,
many_actors, 1M queued tasks, 10k-ref get, 100GiB get, object
broadcast; single-node numbers in
release/release_logs/2.9.0/scalability/single_node.json).

Prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ...}

Run:  python bench_scale.py [--quick]
Numbers are recorded in PARITY.md §perf beside the reference's.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def emit(metric: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, **extra}), flush=True)
    # Scale-envelope evidence (VERDICT r4 #6): every run lands in
    # BENCH_HISTORY.json beside the train/serve metrics so the envelope
    # is recorded numbers, not just code.
    try:
        import bench

        bench.push_history("scale_" + metric, value, unit,
                           match={}, extra=extra)
    except Exception:  # noqa: BLE001 - recording must not fail the run
        pass


def bench_many_tasks(ray, n: int) -> None:
    """Reference: many_tasks — 10k+ concurrent trivial tasks
    (586 tasks/s at 2.5k CPUs)."""

    @ray.remote
    def noop():
        return None

    t0 = time.perf_counter()
    ray.get([noop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    emit("many_tasks_throughput", n / dt, "tasks/s", n=n,
         total_s=round(dt, 2))


def bench_many_actors(ray, n: int) -> None:
    """Reference: many_actors — 10k actors, 590 actors/s launch."""

    @ray.remote
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray.get([a.ping.remote() for a in actors])
    dt = time.perf_counter() - t0
    emit("many_actors_launch_and_ping", n / dt, "actors/s", n=n,
         total_s=round(dt, 2))
    for a in actors:
        ray.kill(a)


def bench_queued_tasks(ray, n: int) -> None:
    """Reference: 1M queued tasks in 192.3s (single node). Queue depth
    is bounded here by submission rate: tasks depend on a gate object
    so none can start until all are queued."""

    @ray.remote
    def gated(_gate):
        return None

    @ray.remote
    def gate_task():
        return None

    gate = gate_task.remote()
    # All n tasks queue behind the (already-resolved) gate — the point
    # is submission + scheduling throughput with a deep queue.
    t0 = time.perf_counter()
    refs = [gated.remote(gate) for _ in range(n)]
    submit_dt = time.perf_counter() - t0
    ray.get(refs)
    total_dt = time.perf_counter() - t0
    emit("queued_tasks", n, "tasks", submit_s=round(submit_dt, 2),
         drain_s=round(total_dt, 2),
         submit_rate=round(n / submit_dt, 1))


def bench_many_refs_get(ray, n: int) -> None:
    """Reference: ray.get on 10k refs in 24.5s."""

    refs = [ray.put(i) for i in range(n)]
    t0 = time.perf_counter()
    out = ray.get(refs)
    dt = time.perf_counter() - t0
    assert out[-1] == n - 1
    emit("get_10k_refs", dt, "s", n=n)


def bench_large_object(ray, gib: float) -> None:
    """Reference: 100GiB+ ray.get in 30.5s (m4.16xlarge). Scaled to
    this box: one multi-GiB numpy object through the shm plane."""
    import numpy as np

    nbytes = int(gib * 1024**3)
    arr = np.ones(nbytes // 8, dtype=np.float64)
    t0 = time.perf_counter()
    ref = ray.put(arr)
    put_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = ray.get(ref)
    get_dt = time.perf_counter() - t0
    assert out.nbytes == arr.nbytes
    emit("large_object_roundtrip", gib, "GiB",
         put_s=round(put_dt, 2), get_s=round(get_dt, 2),
         put_gbps=round(arr.nbytes / put_dt / 1024**3, 2),
         get_gbps=round(arr.nbytes / get_dt / 1024**3, 2))
    del ref, out, arr


def bench_broadcast(n_nodes: int, mib: int) -> None:
    """Reference: 1GiB broadcast to 50 nodes in 95.8s. Here: one
    object consumed by a task on every REAL node daemon (arena-to-arena
    transfer plane)."""
    import ray_tpu
    from ray_tpu.cluster_utils import RealCluster

    ray_tpu.shutdown()
    # Generous health timeout: n_nodes concurrent GiB-scale memcpys on
    # a small box starve daemon heartbeat threads for seconds at a
    # time, and a spurious death mid-broadcast scrubs that node's
    # locations and forces re-pulls. This measures the transfer plane;
    # failure detection has its own tests (tests/test_chaos.py).
    cluster = RealCluster(health_timeout_ms=60_000)
    # Each daemon's arena must hold the broadcast object (+ headroom).
    # The DRIVER arena is sized from the driver's own environment, not
    # the add_node env dict — set it too, or the driver-side get() of
    # the produced object cannot admit it.
    arena = str(int(mib * 1024**2 * 1.5) + (64 << 20))
    env = {"RAY_TPU_OBJECT_STORE_MEMORY_BYTES": arena}
    os.environ["RAY_TPU_OBJECT_STORE_MEMORY_BYTES"] = arena
    try:
        for _ in range(n_nodes):
            cluster.add_node(num_cpus=1, env=env)
        ray = cluster.connect()
        import numpy as np

        @ray.remote
        def make(nbytes):
            return np.ones(nbytes // 8, dtype=np.float64)

        @ray.remote(num_cpus=1)
        def consume(a):
            return float(a[0])

        ref = make.remote(mib * 1024**2)
        ray.get(ref)
        t0 = time.perf_counter()
        out = ray.get([consume.remote(ref) for _ in range(n_nodes)])
        dt = time.perf_counter() - t0
        assert out == [1.0] * n_nodes
        # Per-source pull counts from the object directory's
        # pull_complete reports: a relay-tree broadcast spreads the
        # counts across many sources; a star would put everything on
        # the producer's endpoint.
        sources = {}
        with contextlib.suppress(Exception):
            from ray_tpu.core.runtime import global_runtime_or_none

            rt = global_runtime_or_none()
            if rt is not None and rt.remote_plane is not None:
                sources = rt.remote_plane.pull_source_counts()
        emit("broadcast", dt, "s", nodes=n_nodes, mib=mib,
             agg_gbps=round(mib * n_nodes / 1024 / dt, 2),
             pull_sources=sources,
             distinct_sources=len(sources))
    finally:
        cluster.shutdown()


def bench_transfer_contention(n_pullers: int, n_objects: int,
                              mib: int) -> None:
    """Transfer-plane throughput under contention (VERDICT r4 #4):
    N requesters pulling N_objects x mib MiB concurrently through one
    PullManager whose in-flight budget is far smaller than the working
    set — aggregate MiB/s with fair queueing + byte-budget admission
    active. Reference coverage: object-manager contention tests
    (src/ray/object_manager/test/)."""
    import threading

    import numpy as np

    from ray_tpu._native import object_transfer as ot
    from ray_tpu._native.shm_store import ShmStore

    if not (ot.available()):
        emit("transfer_contention_skipped", 0, "n/a")
        return
    pid = os.getpid()
    src_name, dst_name = f"/rt_bs_src_{pid}", f"/rt_bs_dst_{pid}"
    total_mib = n_objects * mib
    src = ShmStore(src_name, capacity=(total_mib + 64) << 20)
    dst = ShmStore(dst_name, capacity=(total_mib + 64) << 20)
    server = ot.TransferServer(src_name)
    budget = max(8, total_mib // 8) << 20  # budget << working set
    mgr = ot.PullManager(dst_name, budget_bytes=budget, workers=4)
    try:
        payload = np.random.default_rng(0).bytes(mib << 20)
        ids = []
        for i in range(n_objects):
            oid = i.to_bytes(4, "little") + b"\x00" * 24
            src.put(oid, payload)
            ids.append(oid)

        errs = []

        def puller(req_id, chunk):
            try:
                ts = [mgr.submit_pull(req_id, "127.0.0.1", server.port,
                                      oid) for oid in chunk]
                for t in ts:
                    mgr.wait(t, timeout_ms=120000)
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        per = max(1, n_objects // n_pullers)
        chunks = [ids[i * per:(i + 1) * per] for i in range(n_pullers)]
        threads = [threading.Thread(target=puller, args=(i, c))
                   for i, c in enumerate(chunks) if c]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert not errs, errs[:3]
        moved = sum(len(c) for c in chunks) * mib
        emit("transfer_contention_mib_s", moved / dt, "MiB/s",
             pullers=n_pullers, objects=n_objects, mib_each=mib,
             budget_mib=budget >> 20, wall_s=round(dt, 2))
    finally:
        mgr.stop()
        server.stop()
        src.close()
        dst.close()
        ShmStore.unlink(src_name)
        ShmStore.unlink(dst_name)


def bench_heartbeat_soak(n_nodes: int, soak_s: float) -> None:
    """Control-plane health plane at N nodes (reference bar: 50+ node
    clusters under GCS health checks): N registered heartbeaters soak;
    all must stay ALIVE the whole window; then a subset stops
    heartbeating and EXACTLY those expire DEAD."""
    import threading

    from ray_tpu._native import control_client as cc

    proc, port = cc.launch_control_plane(health_timeout_ms=3000)
    stopped: set = set()
    stop_all = threading.Event()

    def hb_loop(cli, nid):
        while not stop_all.wait(0.2):
            if nid in stopped:
                continue
            try:
                cli.heartbeat(nid)
            except Exception:  # noqa: BLE001
                pass

    clients = []
    threads = []
    try:
        for i in range(n_nodes):
            cli = cc.ControlClient(port)
            cli.register_node(f"soak-{i}", meta="{}")
            clients.append(cli)
            t = threading.Thread(target=hb_loop, args=(cli, f"soak-{i}"),
                                 daemon=True)
            t.start()
            threads.append(t)
        obs = cc.ControlClient(port)
        t0 = time.perf_counter()
        flaps = 0
        while time.perf_counter() - t0 < soak_s:
            alive = sum(1 for n in obs.list_nodes() if n["alive"])
            if alive != n_nodes:
                flaps += 1
            time.sleep(0.5)
        # Kill a subset's heartbeats: exactly those must expire.
        victims = {f"soak-{i}" for i in range(0, n_nodes, 10)}
        stopped.update(victims)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            nodes = obs.list_nodes()
            dead = {n["node_id"] for n in nodes if not n["alive"]}
            if dead == victims:
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                f"health expiry wrong: dead={dead} victims={victims}")
        emit("heartbeat_soak", n_nodes, "nodes",
             soak_s=soak_s, flaps=flaps,
             expired_exactly=sorted(victims) == sorted(dead))
        obs.close()
    finally:
        stop_all.set()
        for cli in clients:
            try:
                cli.close()
            except Exception:  # noqa: BLE001
                pass
        proc.terminate()
        proc.wait(timeout=5)


def bench_scheduler_view_soak(n_nodes: int, n_tasks: int) -> None:
    """Driver scheduler view at N nodes: N in-process nodes, tasks
    spread across them, placements span the fleet (reference: every
    raylet schedules 'anywhere' off the synced view)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    try:
        for _ in range(n_nodes):
            cluster.add_node(num_cpus=1)
        import ray_tpu as ray

        @ray.remote(num_cpus=1)
        def where():
            # Hold the slot briefly: instantly-returning tasks are
            # (correctly) placed local-first and never pressure the
            # fleet — the soak must exercise the WIDE view.
            time.sleep(0.05)
            return ray.get_runtime_context().get_node_id()

        t0 = time.perf_counter()
        out = ray.get([where.remote() for _ in range(n_tasks)])
        dt = time.perf_counter() - t0
        distinct = len(set(out))
        emit("scheduler_view_soak", n_nodes, "nodes",
             tasks=n_tasks, total_s=round(dt, 2),
             distinct_nodes_used=distinct,
             rate=round(n_tasks / dt, 1))
        assert distinct >= max(2, n_nodes // 2), (
            f"placements collapsed onto {distinct} nodes")
    finally:
        cluster.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    q = args.quick

    import ray_tpu as ray

    ray.shutdown()
    # Arena sized for the multi-GiB object phase (the default 1 GiB
    # store would silently route it through the in-process fallback).
    ray.init(num_cpus=4, num_tpus=0, _system_config={
        "object_store_memory_bytes": (1 if q else 6) * 1024**3})
    bench_many_tasks(ray, 1_000 if q else 10_000)
    # Reference scale points (release/benchmarks/README.md:5-31):
    # 10k actors (590/s), 1M queued tasks (192.3s) — completing on this
    # 1-core box is the bar; times are recorded beside the reference's.
    bench_many_actors(ray, 100 if q else 10_000)
    bench_queued_tasks(ray, 10_000 if q else 1_000_000)
    bench_many_refs_get(ray, 1_000 if q else 10_000)
    bench_large_object(ray, 0.25 if q else 2.0)
    ray.shutdown()
    # 1 GiB broadcast to 16 real daemon processes (ref: 1 GiB x 50).
    bench_broadcast(2 if q else 16, 32 if q else 1024)
    bench_transfer_contention(4 if q else 8, 8 if q else 32,
                              4 if q else 16)
    bench_heartbeat_soak(10 if q else 50, 5.0 if q else 30.0)
    bench_scheduler_view_soak(8 if q else 50, 200 if q else 1_000)


if __name__ == "__main__":
    main()
