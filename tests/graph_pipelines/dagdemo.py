"""Demo pipelines for static<->dynamic graph verification.

tests/test_graph_capture.py captures these statically (raylint's
graphcap pass over this directory) AND runs them dynamically,
then asserts the two task graphs are isomorphic. Keep submissions
here structural — every `.remote()`/`.bind()` below is part of the
verified graph shape.
"""

import ray_tpu
from ray_tpu.dag import InputNode, compile_dag


@ray_tpu.remote
def preprocess(x):
    return x + 1


@ray_tpu.remote
def combine(a, b):
    return a + b


@ray_tpu.remote
class Stage:
    def __init__(self, scale=2):
        self.scale = scale

    def work(self, x):
        return self.scale * x


@ray_tpu.graphable
def fanin_pipeline(x):
    """Dynamic-dispatch pipeline: two preprocess tasks fan into
    combine, whose result feeds an actor stage — every edge is ref
    dataflow visible to both static capture and the task-event
    dep/return stamps."""
    a = preprocess.remote(x)
    b = preprocess.remote(x + 1)
    c = combine.remote(a, b)
    s = Stage.remote()
    out = s.work.remote(c)
    return ray_tpu.get(out)


@ray_tpu.graphable
def compiled_pipeline(values):
    """Compiled-dag pipeline: the two-stage shape of the compiled-dag
    tests declared with `.bind()`; returns the results and the DAG
    object so the verifier can walk the dynamically built node graph."""
    s1, s2 = Stage.remote(), Stage.remote()
    with InputNode() as inp:
        dag = s2.work.bind(s1.work.bind(inp))
    cdag = compile_dag(dag)
    try:
        return [cdag.execute(v) for v in values], dag
    finally:
        cdag.teardown()
