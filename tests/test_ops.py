"""Tests for ray_tpu.ops pallas kernels (interpret mode on CPU).

Mirrors the reference's kernel-test style (value + gradient checks
against a dense reference implementation)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ray_tpu.ops import flash_attention, ring_attention, ulysses_attention


def dense_ref(q, k, v, causal=True):
    """(B, S, H, D) layout reference."""
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(D))
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def rand_qkv(key, B=2, S=256, H=4, KVH=None, D=64, dtype=jnp.float32):
    KVH = KVH or H
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KVH, D), dtype)
    v = jax.random.normal(kv, (B, S, KVH, D), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = rand_qkv(jax.random.key(0))
        out = flash_attention(q, k, v, causal=causal)
        ref = dense_ref(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        q, k, v = rand_qkv(jax.random.key(1), H=8, KVH=2)
        out = flash_attention(q, k, v)
        kr = jnp.repeat(k, 4, axis=2)
        vr = jnp.repeat(v, 4, axis=2)
        ref = dense_ref(q, kr, vr)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_grads_match_reference(self):
        q, k, v = rand_qkv(jax.random.key(2), B=1, S=128, H=2)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(dense_ref(q, k, v) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_gqa_grads(self):
        q, k, v = rand_qkv(jax.random.key(3), B=1, S=128, H=4, KVH=2)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v) ** 2)

        def f_ref(q, k, v):
            kr = jnp.repeat(k, 2, axis=2)
            vr = jnp.repeat(v, 2, axis=2)
            return jnp.sum(dense_ref(q, kr, vr) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)

    def test_offsets_decode_step(self):
        # One query token at position 255 attending to a 256-token kv —
        # the paged/decode masking path.
        key = jax.random.key(4)
        q, k, v = rand_qkv(key, B=1, S=256, H=2)
        qlast = q[:, 255:256]
        out = flash_attention(qlast, k, v, causal=True, q_offset=255)
        ref = dense_ref(q, k, v, causal=True)[:, 255:256]
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_ragged_falls_back(self):
        q, k, v = rand_qkv(jax.random.key(5), S=100, D=60)
        out = flash_attention(q, k, v)
        ref = dense_ref(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def _sp_mesh(devices, n=4):
    return Mesh(np.array(devices[:n]), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, cpu_mesh8, causal):
        mesh = _sp_mesh(cpu_mesh8, 4)
        q, k, v = rand_qkv(jax.random.key(6), B=2, S=256, H=2, D=32)

        ring = shard_map(
            functools.partial(ring_attention, axis_name="sp",
                              causal=causal),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))
        out = ring(q, k, v)
        ref = dense_ref(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_grads_match_dense(self, cpu_mesh8):
        mesh = _sp_mesh(cpu_mesh8, 4)
        q, k, v = rand_qkv(jax.random.key(7), B=1, S=128, H=2, D=32)

        ring = shard_map(
            functools.partial(ring_attention, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))

        def f_ring(q, k, v):
            return jnp.sum(ring(q, k, v) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(dense_ref(q, k, v) ** 2)

        g1 = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)

    def test_gqa(self, cpu_mesh8):
        mesh = _sp_mesh(cpu_mesh8, 4)
        q, k, v = rand_qkv(jax.random.key(8), B=1, S=128, H=4, KVH=2,
                           D=32)
        ring = shard_map(
            functools.partial(ring_attention, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))
        out = ring(q, k, v)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        ref = dense_ref(q, kr, vr)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, cpu_mesh8, causal):
        mesh = _sp_mesh(cpu_mesh8, 4)
        q, k, v = rand_qkv(jax.random.key(9), B=2, S=256, H=4, D=32)
        ul = shard_map(
            functools.partial(ulysses_attention, axis_name="sp",
                              causal=causal),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))
        out = ul(q, k, v)
        ref = dense_ref(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_grads(self, cpu_mesh8):
        mesh = _sp_mesh(cpu_mesh8, 4)
        q, k, v = rand_qkv(jax.random.key(10), B=1, S=128, H=4, D=32)
        ul = shard_map(
            functools.partial(ulysses_attention, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))

        g1 = jax.grad(lambda *a: jnp.sum(ul(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(dense_ref(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)
