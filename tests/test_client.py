"""Client-mode tests: a subprocess hosts the runtime via ClientServer;
this process connects as a remote driver (reference coverage model:
python/ray/tests/test_client.py, test_client_builder.py)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

SERVER_SCRIPT = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from ray_tpu.client import ClientServer

srv = ClientServer(port=0, num_cpus=4, num_tpus=0)
srv.start()
print(f"PORT={srv.port}", flush=True)
import time
while True:
    time.sleep(0.5)
"""


@pytest.fixture(scope="module")
def client_server():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", SERVER_SCRIPT],
                            stdout=subprocess.PIPE, env=env, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT="), f"server failed: {line}"
        port = int(line.strip().split("=", 1)[1])
        yield port
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.fixture
def client(client_server):
    import ray_tpu
    from ray_tpu import client as client_mod

    client_mod.disconnect()
    ray_tpu.init(address=f"tpu://127.0.0.1:{client_server}")
    yield ray_tpu
    client_mod.disconnect()


def test_put_get_roundtrip(client):
    ref = client.put({"a": np.arange(5)})
    out = client.get(ref)
    np.testing.assert_array_equal(out["a"], np.arange(5))


def test_remote_function(client):
    @client.remote
    def add(a, b):
        return a + b

    assert client.get(add.remote(2, 3)) == 5
    # Refs as args resolve server-side.
    r1 = add.remote(1, 1)
    assert client.get(add.remote(r1, 10)) == 12


def test_remote_with_options(client):
    @client.remote(num_returns=2)
    def pair():
        return 1, 2

    a, b = pair.remote()
    assert client.get([a, b]) == [1, 2]


def test_task_error_propagates(client):
    @client.remote
    def boom():
        raise ValueError("kapow")

    ref = boom.remote()
    with pytest.raises(Exception, match="kapow"):
        client.get(ref)


def test_actor_lifecycle(client):
    @client.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert client.get(c.incr.remote()) == 11
    assert client.get(c.incr.remote(5)) == 16
    client.kill(c)


def test_wait(client):
    import time as _t

    @client.remote
    def fast():
        return "fast"

    @client.remote
    def slow():
        _t.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, pending = client.wait([f, s], num_returns=1, timeout=3)
    assert len(ready) == 1 and len(pending) == 1
    assert client.get(ready[0]) == "fast"


def test_cluster_resources(client):
    res = client.cluster_resources()
    assert res.get("CPU", 0) >= 4


def test_is_initialized_in_client_mode(client):
    assert client.is_initialized()


def test_named_actor_via_client(client):
    @client.remote
    class Registry:
        def whoami(self):
            return "registry"

    Registry.options(name="client_reg").remote()
    h = client.get_actor("client_reg")
    assert client.get(h.whoami.remote()) == "registry"


def test_client_refs_released_on_gc(client):
    """Review finding: dropping the last local handle must release the
    server-side pinned ref (batched on the next call)."""
    import gc
    from ray_tpu import client as client_mod

    ctx = client_mod.get_client()
    ref = client.put(np.zeros(16))
    rid = ref.ref_id
    assert rid in ctx._ref_counts
    del ref
    gc.collect()
    assert rid not in ctx._ref_counts
    # Flushed lazily with the next request.
    client.put(1)
    with ctx._ref_lock:
        assert rid not in ctx._pending_release


def test_looked_up_named_actor_survives_disconnect(client_server):
    """Review finding: a session that only looked up a named actor must
    not kill it on disconnect."""
    import ray_tpu
    from ray_tpu import client as client_mod

    client_mod.disconnect()
    ray_tpu.init(address=f"tpu://127.0.0.1:{client_server}")

    @ray_tpu.remote
    class KV:
        def ping(self):
            return "pong"

    KV.options(name="survivor", lifetime="detached").remote()
    client_mod.disconnect()

    # Second session: look it up, use it, disconnect.
    ray_tpu.init(address=f"tpu://127.0.0.1:{client_server}")
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    client_mod.disconnect()

    # Third session: still alive.
    ray_tpu.init(address=f"tpu://127.0.0.1:{client_server}")
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    client_mod.disconnect()


def test_named_actor_namespaces_via_client(client):
    """Namespaced names resolve through the client protocol
    (reference: namespaces work through Ray Client)."""
    @client.remote
    class Svc:
        def tag(self):
            return "x"

    Svc.options(name="nsvc", namespace="team-a").remote()
    h = client.get_actor("nsvc", namespace="team-a")
    assert client.get(h.tag.remote()) == "x"
    import pytest as _p

    with _p.raises(Exception):
        client.get_actor("nsvc", namespace="team-b")
