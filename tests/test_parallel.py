"""Mesh/plan/sharding tests on the virtual 8-device CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (
    DEFAULT_RULES,
    ParallelPlan,
    logical_to_mesh_axes,
    make_mesh,
)
from ray_tpu.parallel.sharding import logical_to_sharding, tree_shardings


def test_plan_validation():
    plan = ParallelPlan(dp=2, tp=4)
    assert plan.num_devices == 8
    with pytest.raises(ValueError):
        ParallelPlan(dp=0)


def test_plan_auto():
    assert ParallelPlan.auto(8).fsdp == 8
    assert ParallelPlan.auto(8, prefer="tp").tp == 8


def test_make_mesh_shapes(cpu_mesh8):
    mesh = make_mesh(ParallelPlan(dp=2, tp=4), devices=cpu_mesh8)
    assert mesh.axis_names == ("dcn", "pp", "dp", "fsdp", "ep", "sp", "tp")
    assert mesh.devices.shape == (1, 1, 2, 1, 1, 1, 4)


def test_make_mesh_too_few_devices(cpu_mesh8):
    with pytest.raises(ValueError):
        make_mesh(ParallelPlan(dp=16), devices=cpu_mesh8)


def test_logical_to_mesh_axes():
    spec = logical_to_mesh_axes(("batch", "seq", "embed"))
    assert spec == P(("dcn", "dp", "fsdp", "ep"), "sp", "fsdp")
    assert logical_to_mesh_axes(None) == P()
    assert logical_to_mesh_axes(("unknown_axis",)) == P(None)


def test_mesh_trims_size1_axes(cpu_mesh8):
    mesh = make_mesh(ParallelPlan(fsdp=8), devices=cpu_mesh8)
    # dp/tp/sp are size 1 → dropped from specs; batch maps to fsdp only.
    spec = logical_to_mesh_axes(("batch", "seq"), DEFAULT_RULES, mesh)
    assert spec == P(("fsdp",), None)


def test_sharded_matmul_correctness(cpu_mesh8):
    """A tp-sharded matmul must equal the single-device result."""
    mesh = make_mesh(ParallelPlan(tp=8), devices=cpu_mesh8)
    x = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    w = np.random.RandomState(1).randn(32, 64).astype(np.float32)
    expected = x @ w

    xs = jax.device_put(x, logical_to_sharding(("batch", "embed"), mesh))
    ws = jax.device_put(w, logical_to_sharding(("embed", "mlp"), mesh))
    with jax.sharding.set_mesh(mesh):
        out = jax.jit(jnp.dot)(xs, ws)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4)
    # Output columns sharded over tp.
    assert out.sharding.spec == P(None, "tp")


def test_fsdp_param_sharding(cpu_mesh8):
    """FSDP plan shards the embed dim across all 8 devices."""
    mesh = make_mesh(ParallelPlan(fsdp=8), devices=cpu_mesh8)
    w = jnp.zeros((64, 128))
    ws = jax.device_put(w, logical_to_sharding(("embed", "mlp"), mesh))
    shard_shapes = {s.data.shape for s in ws.addressable_shards}
    assert shard_shapes == {(8, 128)}


def test_tree_shardings_structure(cpu_mesh8):
    mesh = make_mesh(ParallelPlan(tp=2, fsdp=4), devices=cpu_mesh8)
    logical = {"a": ("embed", "mlp"), "b": {"c": (None,), "d": None}}
    sh = tree_shardings(logical, mesh)
    assert sh["a"].spec == P("fsdp", "tp")
    assert sh["b"]["c"].spec == P(None)
    assert sh["b"]["d"].spec == P()


def test_psum_over_mesh_axis(cpu_mesh8):
    """shard_map + psum over dp — the collective substrate trains ride."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh

    mesh = make_mesh(ParallelPlan(dp=8), devices=cpu_mesh8)

    def f(x):
        return jax.lax.psum(x, axis_name="dp")

    xs = jnp.arange(8.0)
    out = shard_map(
        f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(xs)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


class TestPlanProperties:
    """Property tests over ParallelPlan axis combinations (VERDICT r3
    weak #7): every 8-device plan must build a mesh, shard params and
    batch CONSISTENTLY (global shapes preserved, every shard axis a
    real mesh axis), and run one finite train step."""

    ALL_PLANS_8 = [
        ParallelPlan(dp=8),
        ParallelPlan(fsdp=8),
        ParallelPlan(tp=8),
        ParallelPlan(dp=2, fsdp=2, tp=2),
        ParallelPlan(dp=2, fsdp=4),
        ParallelPlan(fsdp=2, tp=2, sp=2),
        ParallelPlan(ep=2, tp=2, dp=2),
        ParallelPlan(dcn=2, dp=2, fsdp=2),
        ParallelPlan(dcn=2, fsdp=2, tp=2),
        ParallelPlan(dp=2, sp=2, tp=2),
        ParallelPlan(ep=2, fsdp=2, dp=2),
        ParallelPlan(pp=2, dp=4),
        ParallelPlan(pp=2, dp=2, fsdp=2),
        ParallelPlan(pp=4, dp=2),
    ]

    @pytest.mark.parametrize(
        "plan", ALL_PLANS_8,
        ids=[p.describe() for p in ALL_PLANS_8])
    def test_mesh_and_shardings_consistent(self, plan, cpu_mesh8):
        from ray_tpu.models import configs
        from ray_tpu.models.transformer import param_logical_axes

        mesh = make_mesh(plan, devices=cpu_mesh8)
        assert dict(mesh.shape) == {
            k: v for k, v in plan.axis_sizes().items()}
        cfg = configs.tiny_test()
        shardings = tree_shardings(param_logical_axes(cfg), mesh)
        mesh_axes = set(mesh.shape)
        for sh in jax.tree_util.tree_leaves(
                shardings, is_leaf=lambda x: hasattr(x, "spec")):
            for part in sh.spec:
                if part is None:
                    continue
                parts = part if isinstance(part, tuple) else (part,)
                assert set(parts) <= mesh_axes, (sh.spec, mesh_axes)
        # Batch sharding spans exactly the data axes.
        bsh = logical_to_sharding(("batch", "seq"), mesh)
        flat = [a for p in bsh.spec if p is not None
                for a in (p if isinstance(p, tuple) else (p,))]
        assert set(flat) <= mesh_axes

    @pytest.mark.parametrize(
        "plan", [ParallelPlan(dp=2, fsdp=2, tp=2),
                 ParallelPlan(ep=2, tp=2, dp=2),
                 ParallelPlan(dcn=2, dp=2, fsdp=2),
                 ParallelPlan(fsdp=2, tp=2, sp=2)],
        ids=["dp2-fsdp2-tp2", "ep2-tp2-dp2", "dcn2-dp2-fsdp2",
             "fsdp2-tp2-sp2"])
    def test_plan_executes_one_step(self, plan, cpu_mesh8):
        """Params + batch sharded by the plan run one finite step with
        GLOBAL shapes preserved (the consistency that matters: no axis
        combination silently reshapes or double-shards a tensor)."""
        from dataclasses import replace

        from ray_tpu.models import configs
        from ray_tpu.train.step import (
            init_state,
            make_optimizer,
            make_train_step,
            shard_batch,
        )

        cfg = configs.tiny_test()
        if plan.ep > 1:
            cfg = replace(cfg, moe_experts=4, moe_top_k=2)
        mesh = make_mesh(plan, devices=cpu_mesh8)
        opt = make_optimizer(lr=1e-3, warmup_steps=1, total_steps=10)
        batch = max(4, plan.global_batch_divisor())
        with jax.sharding.set_mesh(mesh):
            st = init_state(cfg, mesh, opt, seed=0)
            shapes0 = jax.tree.map(lambda x: x.shape, st.params)
            tok = jax.random.randint(
                jax.random.key(2), (batch, 32), 0, cfg.vocab_size)
            b = shard_batch(
                {"t": tok, "y": jnp.roll(tok, -1, 1),
                 "m": jnp.ones_like(tok, jnp.float32)}, mesh)
            assert b["t"].shape == (batch, 32)  # global shape intact
            st, m = make_train_step(cfg, opt)(st, b["t"], b["y"],
                                              b["m"])
            assert jnp.isfinite(float(m["loss"]))
            shapes1 = jax.tree.map(lambda x: x.shape, st.params)
        assert shapes0 == shapes1  # update preserved global shapes
