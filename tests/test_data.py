"""Data library tests (reference coverage model:
python/ray/data/tests/test_map.py, test_consumption.py,
test_streaming_integration.py)."""

import numpy as np
import pytest


@pytest.fixture
def data(ray_start):
    import ray_tpu.data as data
    return data


def test_from_items_take(data):
    ds = data.from_items([{"x": i} for i in range(10)])
    rows = ds.take(5)
    assert [r["x"] for r in rows] == [0, 1, 2, 3, 4]


def test_range_count_schema(data):
    ds = data.range(100, parallelism=4)
    assert ds.count() == 100
    assert "id" in ds.schema().names


def test_map_batches(data):
    ds = data.range(32, parallelism=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_map_and_filter_and_flat_map(data):
    ds = (data.range(20, parallelism=2)
          .map(lambda r: {"v": r["id"] * 2})
          .filter(lambda r: r["v"] % 4 == 0)
          .flat_map(lambda r: [{"v": r["v"]}, {"v": -r["v"]}]))
    vals = [r["v"] for r in ds.take_all()]
    assert len(vals) == 20
    assert set(map(abs, vals)) == {0, 4, 8, 12, 16, 20, 24, 28, 32, 36}


def test_operator_fusion(data):
    from ray_tpu.data.plan import optimize, MapLike

    ds = (data.range(10)
          .map(lambda r: r)
          .filter(lambda r: True)
          .map(lambda r: r))
    optimized = optimize(ds._op)
    maps = [op for op in optimized.chain() if isinstance(op, MapLike)]
    assert len(maps) == 1  # all three fused
    assert len(maps[0].specs) == 3
    assert ds.count() == 10


def test_limit_short_circuits(data):
    ds = data.range(1000, parallelism=10).limit(25)
    assert ds.count() == 25


def test_repartition(data):
    ds = data.range(100, parallelism=2).repartition(5)
    blocks = ds.iterator().materialize_blocks()
    assert len(blocks) == 5
    assert sum(b.num_rows for b in blocks) == 100


def test_random_shuffle_preserves_rows(data):
    ds = data.range(50, parallelism=5).random_shuffle(seed=7)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(50))
    first = [r["id"] for r in
             data.range(50, parallelism=5).random_shuffle(seed=7).take(10)]
    assert first != list(range(10))


def test_sort(data):
    ds = data.from_items([{"k": v} for v in [3, 1, 2]]).sort("k")
    assert [r["k"] for r in ds.take_all()] == [1, 2, 3]
    ds = data.from_items([{"k": v} for v in [3, 1, 2]]).sort(
        "k", descending=True)
    assert [r["k"] for r in ds.take_all()] == [3, 2, 1]


def test_union_and_zip(data):
    a = data.from_items([{"x": 1}, {"x": 2}])
    b = data.from_items([{"x": 3}])
    assert a.union(b).count() == 3
    z = a.zip(data.from_items([{"y": 10}, {"y": 20}]))
    rows = z.take_all()
    assert rows == [{"x": 1, "y": 10}, {"x": 2, "y": 20}]


def test_iter_batches_rebatching(data):
    ds = data.range(100, parallelism=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sum(sizes) == 100
    assert sizes[:-1] == [32, 32, 32]


def test_tensor_columns_roundtrip(data):
    arr = np.arange(24, dtype=np.float32).reshape(6, 4)
    ds = data.from_numpy(arr)
    batches = list(ds.iter_batches(batch_size=None))
    got = np.concatenate([b["data"] for b in batches])
    np.testing.assert_array_equal(got, arr)


def test_class_udf_on_actor_pool(data):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = data.range(20, parallelism=4).map_batches(
        AddConst, fn_constructor_args=(100,), compute="actors",
        concurrency=2)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(100, 120))


def test_streaming_split_disjoint_and_complete(data):
    ds = data.range(64, parallelism=8)
    splits = ds.streaming_split(2)

    import threading

    results = [[], []]

    def consume(i):
        for batch in splits[i].iter_batches(batch_size=8):
            results[i].extend(batch["id"].tolist())

    ts = [threading.Thread(target=consume, args=(i,)) for i in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    all_ids = sorted(results[0] + results[1])
    assert all_ids == list(range(64))
    assert results[0] and results[1]
    assert not (set(results[0]) & set(results[1]))


def test_materialize_reuse(data):
    calls = []

    def tag(batch):
        calls.append(1)
        return batch

    ds = data.range(16, parallelism=2).map_batches(tag).materialize()
    assert ds.count() == 16
    n_after_first = len(calls)
    assert ds.count() == 16
    assert len(calls) == n_after_first  # no re-execution


def test_parquet_roundtrip(data, tmp_path):
    import ray_tpu.data as rd

    ds = rd.range(50, parallelism=3).map_batches(
        lambda b: {"id": b["id"], "half": b["id"] / 2})
    files = rd.write_parquet(ds, str(tmp_path / "out"))
    assert len(files) >= 1
    back = rd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert sorted(back.schema().names) == ["half", "id"]


def test_csv_and_json_and_text(data, tmp_path):
    import ray_tpu.data as rd

    csv = tmp_path / "t.csv"
    csv.write_text("a,b\n1,x\n2,y\n")
    ds = rd.read_csv(str(csv))
    assert ds.take_all() == [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]

    jsn = tmp_path / "t.jsonl"
    jsn.write_text('{"a": 1}\n{"a": 2}\n')
    assert rd.read_json(str(jsn)).count() == 2

    txt = tmp_path / "t.txt"
    txt.write_text("hello\nworld\n")
    assert [r["text"] for r in rd.read_text(str(txt)).take_all()] == [
        "hello", "world"]


def test_device_put_batches(data):
    """TPU-path: iter_batches stages onto jax devices with prefetch."""
    import jax

    ds = data.range(32, parallelism=2)
    batches = list(ds.iter_batches(
        batch_size=16, device_put=True, prefetch_batches=2))
    assert len(batches) == 2
    assert all(isinstance(b["id"], jax.Array) for b in batches)
    total = sum(int(b["id"].sum()) for b in batches)
    assert total == sum(range(32))


def test_dataset_in_trainer_streaming_split(ray_start, tmp_path):
    """Integration: Dataset → TpuTrainer workers via get_dataset_shard
    (reference: §3.3 data ingest path)."""
    import ray_tpu.data as rd
    import ray_tpu.train as train
    from ray_tpu.train import RunConfig, ScalingConfig, TpuTrainer

    ds = rd.range(64, parallelism=4)

    def loop():
        shard = train.get_dataset_shard("train")
        seen = 0
        for batch in shard.iter_batches(batch_size=8):
            seen += len(batch["id"])
        train.report({"rows": seen})

    result = TpuTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="data_it", storage_path=str(tmp_path)),
        datasets={"train": ds},
    ).fit()
    assert result.error is None
    assert result.metrics["rows"] == 32  # rank 0's equal share


class TestByteBudgetBackpressure:
    """Byte-budget backpressure + autoscaling actor pools (reference:
    streaming_executor_state.py:525 dispatch under object-store
    budgets; actor_pool_map_operator.py autoscaling)."""

    def test_byte_window_math(self):
        from ray_tpu.data.executor import _ByteWindow

        bw = _ByteWindow(budget_bytes=4 << 20, max_tasks=16)
        assert bw.limit() == 16  # no sizes observed yet
        bw._avg = float(1 << 20)  # 1 MiB blocks
        assert bw.limit() == 4
        bw._avg = float(512 << 20)  # huge blocks -> one in flight
        assert bw.limit() == 1
        bw._avg = 8.0  # tiny blocks -> task cap rules
        assert bw.limit() == 16

    def test_read_window_shrinks_under_byte_budget(self, ray_start):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        old_tasks, old_bytes = (ctx.max_in_flight_tasks,
                                ctx.max_in_flight_bytes)
        ctx.max_in_flight_tasks = 16
        ctx.max_in_flight_bytes = 3 << 20  # 3 MiB budget
        started = []  # list.append is GIL-atomic; tasks run in-process

        def make_task(i):
            def read():
                started.append(i)
                return {"x": np.zeros((1 << 20,), np.uint8),
                        "i": np.array([i])}
            return read

        try:
            from ray_tpu.data.dataset import Dataset
            from ray_tpu.data.plan import Read

            ds = Dataset(Read([make_task(i) for i in range(24)],
                              "byte-budget-test"))
            it = iter(ds._refs())
            for _ in range(10):
                next(it)
            # Unbounded window would have started 10 + 16 = 26 reads;
            # the 3 MiB budget over ~1 MiB blocks clamps prefetch.
            assert len(started) <= 18, len(started)
        finally:
            ctx.max_in_flight_tasks = old_tasks
            ctx.max_in_flight_bytes = old_bytes

    def test_pipeline_10x_budget_completes(self, ray_start):
        """read -> map -> shuffle whose total bytes are ~10x the stage
        byte budget still completes with correct results."""
        from ray_tpu import data
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        old_bytes = ctx.max_in_flight_bytes
        ctx.max_in_flight_bytes = 2 << 20  # 2 MiB budget, ~24 MiB data
        try:
            ds = (data.range(24, parallelism=24)
                  .map_batches(lambda b: {
                      "i": b["id"],
                      "x": np.zeros((len(b["id"]), (1 << 20) // 8),
                                    np.uint64)})
                  .random_shuffle(seed=0))
            ids = sorted(r["i"] for r in ds.take_all())
            assert ids == list(range(24))
        finally:
            ctx.max_in_flight_bytes = old_bytes

    def test_actor_pool_autoscales(self, ray_start):
        from ray_tpu import data

        class Tagger:
            def __init__(self):
                import uuid as _uuid

                self.tag = _uuid.uuid4().hex

            def __call__(self, batch):
                batch["worker"] = np.array([self.tag] * len(batch["id"]))
                return batch

        grown = (data.range(64, parallelism=16)
                 .map_batches(Tagger, concurrency=(1, 4))
                 .take_all())
        assert len({r["worker"] for r in grown}) >= 2

        fixed = (data.range(64, parallelism=16)
                 .map_batches(Tagger, concurrency=1)
                 .take_all())
        assert len({r["worker"] for r in fixed}) == 1

    def test_bad_concurrency_bounds_rejected(self, ray_start):
        import pytest as _pytest

        from ray_tpu import data

        class Udf:
            def __call__(self, b):
                return b

        with _pytest.raises(ValueError, match="concurrency"):
            (data.range(4).map_batches(Udf, concurrency=(3, 1))
             .take_all())
