"""Prometheus/Grafana export bundle + per-node gauge re-export
(reference coverage model: dashboard/modules/metrics tests — config
shape, dashboard JSON validity, series names matching the exposition)."""

import json
import os
import re
import time

import pytest


class TestExportBundle:
    def test_export_configs_writes_bundle(self, tmp_path):
        from ray_tpu.dashboard.metrics_export import export_configs

        paths = export_configs(str(tmp_path), metrics_addr="10.0.0.1:8265",
                               extra_targets=["10.0.0.2:8265"])
        assert set(paths) == {"prometheus", "datasource", "dashboard",
                              "dashboard_provider"}
        prom = open(paths["prometheus"]).read()
        assert "'10.0.0.1:8265'" in prom and "'10.0.0.2:8265'" in prom
        assert "metrics_path: /metrics" in prom
        dash = json.load(open(paths["dashboard"]))
        assert dash["uid"] == "ray-tpu-default"
        assert len(dash["panels"]) >= 8
        for p in dash["panels"]:
            assert p["targets"][0]["expr"]
            assert p["gridPos"]["w"] == 12
        ds = open(paths["datasource"]).read()
        assert "type: prometheus" in ds
        provider = open(paths["dashboard_provider"]).read()
        assert os.path.dirname(paths["dashboard"]) in provider

    def test_cli_entry(self, tmp_path, capsys):
        from ray_tpu.scripts.cli import main

        rc = main(["metrics", "export-configs", "--out", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "prometheus.yml" in out
        assert (tmp_path / "prometheus.yml").exists()

    def test_panel_series_match_published_names(self):
        """Every panel expression references a series some publisher
        actually registers (guards against silent renames on either
        side): node gauges from the dashboard sampler, task-lifecycle
        series from observability.taskstats, serve series from the
        serve data plane (proxy ingress + replica + handle admission),
        loop-handler gauges from observability.event_stats, anomaly
        counter from observability.tsdb, TTFT gauge from the serve
        controller's stats harvest, outstanding-resource series from
        observability.ledger, critical-path plane series from
        observability.critpath."""
        import inspect

        from ray_tpu.dashboard import server as srv
        from ray_tpu.dashboard.metrics_export import DEFAULT_PANELS
        from ray_tpu.observability import (critpath, event_stats,
                                           ledger, taskstats, tsdb)
        from ray_tpu.serve import controller, handle, proxy, replica

        publish_src = "\n".join([
            inspect.getsource(srv.MetricsHistory._publish_prom),
            inspect.getsource(taskstats),
            inspect.getsource(proxy),
            inspect.getsource(replica),
            inspect.getsource(handle),
            inspect.getsource(event_stats),
            inspect.getsource(tsdb),
            inspect.getsource(controller),
            inspect.getsource(ledger),
            inspect.getsource(critpath),
        ])
        for _title, expr, _unit in DEFAULT_PANELS:
            m = re.search(r"(ray_tpu_[a-z_]+?)(_bucket)?(?:[^a-z_]|$)",
                          expr)
            if m:
                assert m.group(1) in publish_src, expr

    def test_panel_count_pinned(self):
        """Panel-count pin: adding or removing a default Grafana panel
        must be deliberate (update this number with the panel list).
        33 = 31 pre-critpath panels + plane-time budget + dispatch
        share."""
        from ray_tpu.dashboard.metrics_export import DEFAULT_PANELS

        assert len(DEFAULT_PANELS) == 33
        titles = [t for t, _e, _u in DEFAULT_PANELS]
        assert "Critical-path plane budget" in titles
        assert "Critical-path dispatch share" in titles

    def test_serve_series_match_proxy_names(self):
        import inspect

        from ray_tpu.dashboard.metrics_export import DEFAULT_PANELS
        from ray_tpu.serve import handle, proxy, replica

        serve_src = (inspect.getsource(proxy)
                     + inspect.getsource(replica)
                     + inspect.getsource(handle))
        for _t, expr, _u in DEFAULT_PANELS:
            m = re.search(r"(serve_[a-z_]+?)(_bucket)?\[", expr)
            if m:
                assert m.group(1) in serve_src, expr


class TestNodeGaugeExport:
    def test_head_gauges_reach_exposition(self, ray_start):
        """The sampler publishes ray_tpu_node_* gauges that show up in
        the native /metrics exposition."""
        from ray_tpu.dashboard.server import MetricsHistory

        h = MetricsHistory(interval_s=0.1)
        try:
            h._sample()  # direct: no thread-timing dependence
            from ray_tpu._native import metrics as native

            text = native.collect()
            assert "ray_tpu_node_cpu_percent" in text
            assert re.search(r'node_id="[^"]+"', text)
            assert "ray_tpu_scheduler_pending_tasks" in text
        finally:
            h.stop()
