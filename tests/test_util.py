"""ray_tpu.util: collectives, ActorPool, Queue.

Mirrors the reference's test approach for ray.util.collective
(reference: python/ray/util/collective/tests/) with the shm host
backend — each member is an actor, ops checked against numpy.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util import collective as col


@pytest.fixture
def rt():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
class Member(col.CollectiveActorMixin):
    def __init__(self, rank: int, world: int, group: str):
        self.rank = rank
        col.init_collective_group(world, rank, group_name=group)

    def do_allreduce(self, x):
        return col.allreduce(np.asarray(x), group_name=self._g())

    def _g(self):
        return "g" + str(getattr(self, "_gid", ""))

    def set_gid(self, gid):
        self._gid = gid

    def run(self, op, *args, **kw):
        return getattr(col, op)(*args, group_name=kw.pop("group"), **kw)


class TestCollective:
    def test_allreduce_sum(self, rt):
        world = 4
        members = [Member.options(max_concurrency=2).remote(r, world, "ar")
                   for r in range(world)]
        refs = [m.run.remote("allreduce", np.full((3,), float(r + 1)),
                             group="ar")
                for r, m in enumerate(members)]
        outs = ray_tpu.get(refs)
        for o in outs:
            np.testing.assert_allclose(o, np.full((3,), 10.0))

    def test_broadcast_and_allgather(self, rt):
        world = 3
        members = [Member.options(max_concurrency=2).remote(r, world, "bg")
                   for r in range(world)]
        # broadcast from rank 0
        refs = []
        for r, m in enumerate(members):
            refs.append(m.run.remote(
                "broadcast", np.arange(4.0) if r == 0 else np.zeros(4),
                group="bg", src_rank=0))
        for o in ray_tpu.get(refs):
            np.testing.assert_allclose(o, np.arange(4.0))
        # allgather
        refs = [m.run.remote("allgather", np.full((2,), float(r)),
                             group="bg")
                for r, m in enumerate(members)]
        for o in ray_tpu.get(refs):
            assert len(o) == world
            np.testing.assert_allclose(o[2], np.full((2,), 2.0))

    def test_reducescatter(self, rt):
        world = 2
        members = [Member.options(max_concurrency=2).remote(r, world, "rs")
                   for r in range(world)]
        x = np.arange(8.0)
        refs = [m.run.remote("reducescatter", x, group="rs")
                for m in members]
        outs = ray_tpu.get(refs)
        np.testing.assert_allclose(outs[0], np.arange(4.0) * 2)
        np.testing.assert_allclose(outs[1], np.arange(4.0, 8.0) * 2)

    def test_sendrecv_and_barrier(self, rt):
        world = 2
        members = [Member.options(max_concurrency=2).remote(r, world, "sr")
                   for r in range(world)]
        r_send = members[0].run.remote(
            "send", np.full((2, 2), 7.0), 1, group="sr")
        r_recv = members[1].run.remote("recv", 0, group="sr")
        ray_tpu.get(r_send)
        np.testing.assert_allclose(
            ray_tpu.get(r_recv), np.full((2, 2), 7.0))
        ray_tpu.get([m.run.remote("barrier", group="sr") for m in members])

    def test_create_collective_group(self, rt):
        world = 2
        members = [Member.options(max_concurrency=2).remote(r, world, "pre")
                   for r in range(world)]
        col.create_collective_group(
            members, world, list(range(world)), group_name="declared")
        refs = [m.run.remote("allreduce", np.ones(2), group="declared")
                for m in members]
        for o in ray_tpu.get(refs):
            np.testing.assert_allclose(o, np.full((2,), 2.0))


class TestActorPool:
    def test_map_ordered(self, rt):
        @ray_tpu.remote
        class W:
            def double(self, x):
                return 2 * x

        pool = ActorPool([W.remote() for _ in range(3)])
        out = list(pool.map(lambda a, v: a.double.remote(v), range(10)))
        assert out == [2 * i for i in range(10)]

    def test_map_unordered_and_reuse(self, rt):
        @ray_tpu.remote
        class W:
            def sq(self, x):
                return x * x

        pool = ActorPool([W.remote() for _ in range(2)])
        out = sorted(pool.map_unordered(
            lambda a, v: a.sq.remote(v), range(8)))
        assert out == sorted(i * i for i in range(8))
        # pool reusable after map
        pool.submit(lambda a, v: a.sq.remote(v), 5)
        assert pool.get_next() == 25

    def test_push_pop_idle(self, rt):
        @ray_tpu.remote
        class W:
            def f(self, x):
                return x

        pool = ActorPool([W.remote()])
        a = pool.pop_idle()
        assert a is not None
        assert pool.pop_idle() is None
        pool.push(a)
        assert list(pool.map(lambda a, v: a.f.remote(v), [1])) == [1]


class TestQueue:
    def test_fifo(self, rt):
        q = Queue()
        for i in range(5):
            q.put(i)
        assert q.qsize() == 5
        assert [q.get() for _ in range(5)] == list(range(5))
        assert q.empty()

    def test_maxsize_and_nowait(self, rt):
        from ray_tpu.util.queue import Empty, Full

        q = Queue(maxsize=2)
        q.put_nowait(1)
        q.put_nowait(2)
        with pytest.raises(Full):
            q.put_nowait(3)
        assert q.get_nowait() == 1
        q.shutdown()

    def test_cross_actor(self, rt):
        q = Queue()

        @ray_tpu.remote
        class Producer:
            def produce(self, q, n):
                for i in range(n):
                    q.put(i)
                return n

        p = Producer.remote()
        assert ray_tpu.get(p.produce.remote(q, 4)) == 4
        assert [q.get() for _ in range(4)] == [0, 1, 2, 3]


class TestReviewRegressions:
    def test_actor_pool_survives_task_error(self, rt):
        """A raising task must return the actor to the idle set
        (review finding: pool wedged forever after one failure)."""
        @ray_tpu.remote
        class W:
            def f(self, x):
                if x == 1:
                    raise ValueError("boom")
                return x

        pool = ActorPool([W.remote()])
        pool.submit(lambda a, v: a.f.remote(v), 1)
        pool.submit(lambda a, v: a.f.remote(v), 2)
        with pytest.raises(Exception):
            pool.get_next()
        assert pool.get_next() == 2  # pool still alive

    def test_queue_put_batch_all_or_nothing(self, rt):
        from ray_tpu.util.queue import Full, Queue

        q = Queue(maxsize=3)
        q.put_nowait_batch([1, 2])
        with pytest.raises(Full):
            q.put_nowait_batch([3, 4])  # doesn't fit
        assert q.qsize() == 2  # nothing partially inserted
        q.put_nowait_batch([3])
        assert [q.get_nowait() for _ in range(3)] == [1, 2, 3]
        q.shutdown()


# ---------------------------------------------------------------------------
# inspect_serializability (reference: ray.util.check_serialize)
# ---------------------------------------------------------------------------

def test_inspect_serializability_finds_blocker():
    import io
    import threading

    from ray_tpu.util.check_serialize import inspect_serializability

    lock = threading.Lock()

    def captures_lock():
        return lock

    buf = io.StringIO()
    ok, failures = inspect_serializability(
        captures_lock, print_file=buf)
    assert not ok
    assert any("lock" in repr(f.obj).lower() for f in failures)
    assert "FAILED" in buf.getvalue()


def test_inspect_serializability_clean_object():
    import io

    from ray_tpu.util.check_serialize import inspect_serializability

    ok, failures = inspect_serializability(
        {"a": [1, 2, 3]}, name="data", print_file=io.StringIO())
    assert ok and not failures


def test_inspect_serializability_nested_attr():
    import io
    import threading

    from ray_tpu.util.check_serialize import inspect_serializability

    class Holder:
        def __init__(self):
            self.fine = 42
            self.bad = threading.Lock()

    ok, failures = inspect_serializability(
        Holder(), name="holder", print_file=io.StringIO())
    assert not ok
    assert any(".bad" in f.name for f in failures)


def test_inspect_serializability_shared_blocker():
    """A second path to the same unserializable object must not blame
    its container."""
    import io
    import threading

    from ray_tpu.util.check_serialize import inspect_serializability

    lock = threading.Lock()

    class Holder:
        def __init__(self):
            self.a = lock
            self.b = [lock]

    ok, failures = inspect_serializability(
        Holder(), name="holder", print_file=io.StringIO())
    assert not ok
    # The lock (not the list in .b) is reported as a blocker.
    assert any(isinstance(f.obj, type(lock)) for f in failures)
    assert not any(isinstance(f.obj, list) for f in failures)
