"""Transformer model + train-step tests (tiny configs, CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import configs
from ray_tpu.models.transformer import (
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.parallel import ParallelPlan, make_mesh
from ray_tpu.train.step import (
    TrainState,
    init_state,
    make_optimizer,
    make_train_step,
    shard_batch,
)


@pytest.fixture(scope="module")
def tiny():
    return configs.tiny_test()


def _batch(cfg, key, batch=4, seq=32):
    k1, k2 = jax.random.split(jax.random.key(key))
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)
    return tokens, targets, mask


def test_forward_shapes(tiny):
    params = init_params(tiny, jax.random.key(0))
    tokens, _, _ = _batch(tiny, 0)
    logits, aux = forward(tiny, params, tokens)
    assert logits.shape == (4, 32, tiny.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_param_axes_match_structure(tiny):
    params = init_params(tiny, jax.random.key(0))
    axes = param_logical_axes(tiny)
    ps = jax.tree.structure(params)
    As = jax.tree.structure(
        axes, is_leaf=lambda x: x is None or isinstance(x, tuple))
    assert ps == As
    # rank of each axes tuple matches param rank
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(
        axes, is_leaf=lambda x: x is None or isinstance(x, tuple))
    for p, a in zip(flat_p, flat_a):
        assert len(a) == p.ndim, f"{p.shape} vs {a}"


def test_causality(tiny):
    """Changing a future token must not affect earlier logits."""
    params = init_params(tiny, jax.random.key(0))
    tokens, _, _ = _batch(tiny, 1, batch=1, seq=16)
    logits1, _ = forward(tiny, params, tokens)
    tokens2 = tokens.at[0, -1].set((tokens[0, -1] + 1) % tiny.vocab_size)
    logits2, _ = forward(tiny, params, tokens2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]),
        rtol=2e-4, atol=2e-4)


def test_loss_decreases_single_device(tiny):
    opt = make_optimizer(lr=1e-2, warmup_steps=1, total_steps=100)
    params = init_params(tiny, jax.random.key(0))
    state_params = params
    opt_state = opt.init(params)

    tokens, targets, mask = _batch(tiny, 0)

    @jax.jit
    def step(params, opt_state):
        (_, m), g = jax.value_and_grad(
            lambda p: loss_fn(tiny, p, tokens, targets, mask),
            has_aux=True)(params)
        upd, opt_state = opt.update(g, opt_state, params)
        import optax
        return optax.apply_updates(params, upd), opt_state, m

    first = None
    for i in range(10):
        state_params, opt_state, m = step(state_params, opt_state)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first


def test_train_step_on_mesh_fsdp_tp(cpu_mesh8, tiny):
    """Full train step under dp=2,fsdp=2,tp=2 on 8 virtual devices."""
    plan = ParallelPlan(dp=2, fsdp=2, tp=2)
    mesh = make_mesh(plan, devices=cpu_mesh8)
    opt = make_optimizer(lr=1e-2, warmup_steps=1, total_steps=100)
    with jax.sharding.set_mesh(mesh):
        state = init_state(tiny, mesh, opt, seed=0)
        step_fn = make_train_step(tiny, opt)
        tokens, targets, mask = _batch(tiny, 0, batch=8, seq=32)
        batch = shard_batch(
            {"tokens": tokens, "targets": targets, "mask": mask}, mesh)
        losses = []
        for _ in range(5):
            state, m = step_fn(
                state, batch["tokens"], batch["targets"], batch["mask"])
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5
    # FSDP actually sharded the embedding across fsdp axis.
    emb = state.params["embed"]
    assert any(
        s.data.shape != emb.shape for s in emb.addressable_shards)


def test_mesh_equals_single_device(tiny, cpu_mesh8):
    """Sharded forward == unsharded forward (numerical SPMD parity)."""
    params = init_params(tiny, jax.random.key(0))
    tokens, targets, mask = _batch(tiny, 0, batch=8)
    expected, _ = forward(tiny, params, tokens)

    plan = ParallelPlan(dp=2, tp=2, fsdp=2)
    mesh = make_mesh(plan, devices=cpu_mesh8)
    from ray_tpu.parallel.sharding import shard_pytree
    from ray_tpu.models.transformer import param_logical_axes
    with jax.sharding.set_mesh(mesh):
        sp = shard_pytree(params, param_logical_axes(tiny), mesh)
        st = shard_batch({"tokens": tokens}, mesh)
        got, _ = jax.jit(lambda p, t: forward(tiny, p, t))(sp, st["tokens"])
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=5e-4, atol=5e-4)


def test_moe_forward_and_grad():
    cfg = configs.tiny_moe_test()
    params = init_params(cfg, jax.random.key(0))
    tokens, targets, mask = _batch(cfg, 0)
    logits, aux = forward(cfg, params, tokens)
    assert logits.shape == (4, 32, cfg.vocab_size)
    assert float(aux) > 0  # load-balance loss active

    g = jax.grad(
        lambda p: loss_fn(cfg, p, tokens, targets, mask)[0])(params)
    gn = jax.tree.leaves(jax.tree.map(lambda x: float(jnp.sum(x * x)), g))
    assert sum(gn) > 0


def test_moe_on_ep_mesh(cpu_mesh8):
    cfg = configs.tiny_moe_test()
    plan = ParallelPlan(ep=4, dp=2)
    mesh = make_mesh(plan, devices=cpu_mesh8)
    opt = make_optimizer(lr=1e-2, warmup_steps=1, total_steps=50)
    with jax.sharding.set_mesh(mesh):
        state = init_state(cfg, mesh, opt, seed=0)
        step_fn = make_train_step(cfg, opt)
        tokens, targets, mask = _batch(cfg, 0, batch=8)
        b = shard_batch(
            {"t": tokens, "y": targets, "m": mask}, mesh)
        # warmup lr(step0)=0 → first update is a no-op; compare over 3.
        state, m1 = step_fn(state, b["t"], b["y"], b["m"])
        state, _ = step_fn(state, b["t"], b["y"], b["m"])
        state, m3 = step_fn(state, b["t"], b["y"], b["m"])
    assert float(m3["loss"]) < float(m1["loss"])


def test_num_params_accounting():
    cfg = configs.gpt2_125m()
    params = init_params(configs.tiny_test(), jax.random.key(0))
    reported = cfg.num_params()
    # ~124-163M with the padded vocab — sanity band.
    assert 1.0e8 < reported < 2.0e8


class TestFullScaleConfigs:
    """BASELINE configs 2/3 (Llama-3-8B, Mixtral 8x7B) at their REAL
    dimensions: abstract evaluation of the sharded train step under a
    production-shaped plan. jax.eval_shape traces the full program —
    shape/dtype/sharding-rule consistency at 8B/47B scale — without
    allocating parameters (single-host CI cannot hold them)."""

    def _abstract_step(self, cfg, plan, cpu_devices):
        from ray_tpu.parallel.sharding import tree_shardings
        from ray_tpu.models.transformer import param_logical_axes

        devices = cpu_devices[:plan.num_devices]
        mesh = make_mesh(plan, devices=devices)
        opt = make_optimizer(lr=3e-4, warmup_steps=10, total_steps=100)
        with jax.sharding.set_mesh(mesh):
            p_axes = param_logical_axes(cfg)
            tree_shardings(p_axes, mesh)  # sharding rules resolve

            def init_abstract():
                return init_params(cfg, jax.random.key(0))

            params_shape = jax.eval_shape(init_abstract)
            step_fn = make_train_step(cfg, opt)
            B, S = 8, 512

            def full_step(params, tokens, targets, mask):
                state = TrainState(
                    step=jnp.zeros((), jnp.int32), params=params,
                    opt_state=jax.eval_shape(opt.init, params))
                # Only shapes flow here — eval_shape never executes.
                return step_fn(state, tokens, targets, mask)

            out = jax.eval_shape(
                full_step, params_shape,
                jax.ShapeDtypeStruct((B, S), jnp.int32),
                jax.ShapeDtypeStruct((B, S), jnp.int32),
                jax.ShapeDtypeStruct((B, S), jnp.float32))
        return params_shape, out

    def test_llama3_8b_sharded_step_shapes(self, cpu_mesh8):
        from ray_tpu.models import configs

        cfg = configs.llama3_8b()
        n_params = cfg.num_params()
        assert 7.5e9 < n_params < 8.5e9  # 8B-class
        params_shape, (state_out, metrics) = self._abstract_step(
            cfg, ParallelPlan(fsdp=4, tp=2), cpu_mesh8)
        assert metrics["loss"].shape == ()
        assert state_out.params["embed"].shape == (
            cfg.vocab_size, cfg.d_model)

    def test_mixtral_8x7b_sharded_step_shapes(self, cpu_mesh8):
        from ray_tpu.models import configs

        cfg = configs.mixtral_8x7b()
        n_params = cfg.num_params()
        assert 4.4e10 < n_params < 5.0e10  # 8x7B sparse total ≈ 47B
        params_shape, (state_out, metrics) = self._abstract_step(
            cfg, ParallelPlan(fsdp=2, ep=2, tp=2), cpu_mesh8)
        assert metrics["loss"].shape == ()
        # Expert tensors exist at full dimension in the abstract tree.
        assert params_shape["layers"]["w_gate"].shape == (
            cfg.n_layers, cfg.moe_experts, cfg.d_model, cfg.d_ff)


def test_chunked_cross_entropy_matches_full():
    """ce_chunk>0 (blockwise vocab projection, chunked_cross_entropy)
    is numerically identical to the full-logits loss."""
    from dataclasses import replace

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import configs
    from ray_tpu.models.transformer import init_params, loss_fn

    cfg0 = configs.tiny_test()
    cfgc = replace(cfg0, ce_chunk=32)
    p = init_params(cfg0, jax.random.key(0))
    tok = jax.random.randint(jax.random.key(1), (2, 128), 0,
                             cfg0.vocab_size)
    tgt = jnp.roll(tok, -1, 1)
    mask = (tok % 7 != 0).astype(jnp.float32)

    l0, m0 = loss_fn(cfg0, p, tok, tgt, mask)
    l1, m1 = loss_fn(cfgc, p, tok, tgt, mask)
    assert abs(float(l0) - float(l1)) < 1e-4
    assert float(m0["tokens"]) == float(m1["tokens"])

    g0 = jax.grad(lambda pp: loss_fn(cfg0, pp, tok, tgt, mask)[0])(p)
    g1 = jax.grad(lambda pp: loss_fn(cfgc, pp, tok, tgt, mask)[0])(p)
    import numpy as np

    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
