"""Dispatch-plane parity: native C++ front end vs pure-Python fallback.

The node daemon's dispatch socket has two implementations — the C++
epoll loop (src/node_dispatch.cc, default) and the pure-Python accept
loop (`RAY_TPU_NATIVE_DISPATCH=0`). They must be observationally
identical: same task/actor results, same typed errors, same spillback
refusal replies (retry_at included), same load-report vocabulary. The
parametrized cluster fixture runs every scenario under both planes;
the chaos test then crashes workers mid-dispatch and requires the
native loop to keep serving with an intact resource ledger.

Direct NativeDispatch unit tests (no cluster) cover the binding's
wire handling: framing, native pong, admission/refusal, ledger
semantics vs ResourceSet, oversized-frame teardown, destroy guard.
"""

import contextlib
import json
import socket
import struct
import time

import pytest

import ray_tpu as ray
from ray_tpu import NodeAffinitySchedulingStrategy
from ray_tpu.cluster_utils import RealCluster
from ray_tpu.core import runtime as _runtime


def _rt():
    return _runtime.global_runtime()


@pytest.fixture(scope="class", params=["0", "1"], ids=["py", "native"])
def pcluster(request):
    """Two 1-CPU daemons, both forced onto one dispatch plane; the
    driver head contributes no CPUs so every scenario crosses the
    dispatch socket under test."""
    ray.shutdown()
    cluster = RealCluster()
    env = {"RAY_TPU_NATIVE_DISPATCH": request.param}
    try:
        cluster.add_node(num_cpus=1, env=env)
        cluster.add_node(num_cpus=1, env=env)
        cluster.connect(num_cpus=0)
        yield cluster
    finally:
        cluster.shutdown()


class TestDispatchParity:
    def test_tasks_and_typed_task_error(self, pcluster):
        @ray.remote
        def double(x):
            return 2 * x

        assert ray.get([double.remote(i) for i in range(6)],
                       timeout=60) == [0, 2, 4, 6, 8, 10]

        @ray.remote(max_retries=0)
        def boom():
            raise ValueError("boom-parity")

        with pytest.raises(ray.TaskError) as ei:
            ray.get(boom.remote(), timeout=60)
        assert "boom-parity" in str(ei.value)

    def test_actor_lifecycle_and_typed_errors(self, pcluster):
        @ray.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

            def fail(self):
                raise KeyError("actor-boom")

        c = Counter.remote()
        assert ray.get([c.inc.remote() for _ in range(3)],
                       timeout=60) == [1, 2, 3]
        with pytest.raises(ray.TaskError) as ei:
            ray.get(c.fail.remote(), timeout=60)
        assert "actor-boom" in str(ei.value)
        # the error left the actor alive — state intact
        assert ray.get(c.inc.remote(), timeout=60) == 4
        # release the actor's charge (an alive actor holds a running
        # slot; the ledger test below asserts full quiescence)
        ray.kill(c)

    def test_actor_death_typed_error(self, pcluster):
        @ray.remote
        class Mortal:
            def die(self):
                import os

                os._exit(1)

            def ping(self):
                return "alive"

        a = Mortal.remote()
        assert ray.get(a.ping.remote(), timeout=60) == "alive"
        with pytest.raises((ray.TaskError, ray.ActorDiedError)):
            ray.get(a.die.remote(), timeout=60)
        with pytest.raises(ray.ActorDiedError):
            ray.get(a.ping.remote(), timeout=60)

    def test_worker_crash_task_typed_error(self, pcluster):
        @ray.remote(max_retries=0)
        def die():
            import os

            os._exit(1)

        with pytest.raises(ray.TaskError):
            ray.get(die.remote(), timeout=60)

    def test_spillback_refusal_and_load_vocabulary(self, pcluster):
        """A crafted spillable push to a saturated daemon must be
        refused the same way by both planes: spillback=True, retry_at
        naming the idle peer, and a load report speaking the full
        heartbeat vocabulary (shm_pins attribution included)."""

        @ray.remote(num_cpus=1, scheduling_strategy=(
            NodeAffinitySchedulingStrategy("daemon-1", soft=False)))
        class Holder:
            def ready(self):
                return True

        h = Holder.remote()
        assert ray.get(h.ready.remote(), timeout=60) is True
        # Let one heartbeat land so the daemon's peer view (native: the
        # pushed digest) knows daemon-2 is idle.
        time.sleep(0.6)
        node1 = _rt().scheduler.get_node("daemon-1")
        r = node1.client.call({
            "type": "task", "task_id": b"probe-parity",
            "args": (), "kwargs": {}, "num_returns": 1,
            "return_ids": [], "resources": {"CPU": 1.0},
            "spillable": True, "spill_exclude": [],
        })
        assert r.get("spillback") is True
        assert r.get("retry_at") == "daemon-2"
        load = r.get("load")
        assert load is not None
        assert {"available", "total", "queued", "running", "spilled",
                "host", "event_stats", "transfer",
                "shm_pins"} <= set(load)
        assert load["available"].get("CPU", 0.0) == 0.0
        # shm attribution carries labeled per-pid holders
        assert "holders" in load["shm_pins"]
        for holder in load["shm_pins"]["holders"]:
            assert {"pid", "label", "pinned_bytes"} <= set(holder)
        ray.kill(h)

    def test_ledger_restored_after_errors(self, pcluster):
        """Typed failures above must not leak admission charges: both
        daemons report a fully available ledger once quiesced."""
        deadline = time.monotonic() + 30
        while True:
            loads = [
                _rt().scheduler.get_node(nid).client.call(
                    {"type": "ping"})["load"]
                for nid in ("daemon-1", "daemon-2")]
            if all(ld["available"].get("CPU") == 1.0
                   and ld["running"] == 0 for ld in loads):
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"ledger leaked: {loads}")
            time.sleep(0.2)


class TestNativeChaos:
    """Worker crashes mid-dispatch with the native loop in front: the
    C plane must survive, keep answering, and not strand ledger
    charges."""

    @pytest.fixture(scope="class")
    def chaos_cluster(self):
        ray.shutdown()
        cluster = RealCluster()
        try:
            cluster.add_node(num_cpus=1,
                             env={"RAY_TPU_NATIVE_DISPATCH": "1"})
            cluster.connect(num_cpus=0)
            yield cluster
        finally:
            cluster.shutdown()

    def test_native_loop_survives_worker_crashes(self, chaos_cluster):
        @ray.remote(max_retries=0)
        def die():
            import os

            os._exit(1)

        @ray.remote
        def ok(x):
            return x + 1

        # Interleave crashers with healthy tasks: each crash kills the
        # worker process while the native loop has admitted (and
        # precharged) the task.
        crashers = [die.remote() for _ in range(3)]
        for ref in crashers:
            with pytest.raises(ray.TaskError):
                ray.get(ref, timeout=60)
        assert ray.get([ok.remote(i) for i in range(4)],
                       timeout=120) == [1, 2, 3, 4]
        # The daemon still answers protocol-level pings natively and
        # the crashes released their admission charges.
        node = _rt().scheduler.get_node("daemon-1")
        deadline = time.monotonic() + 30
        while True:
            load = node.client.call({"type": "ping"})["load"]
            if (load["available"].get("CPU") == 1.0
                    and load["running"] == 0):
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"ledger leaked: {load}")
            time.sleep(0.2)
        # and the native handler stats saw real traffic
        native = load["event_stats"].get("node_dispatch_native", {})
        assert native, load["event_stats"].keys()


# ---------------------------------------------------------------------------
# Direct NativeDispatch unit coverage (no cluster)
# ---------------------------------------------------------------------------

_LEN = struct.Struct("!Q")
_HLEN = struct.Struct("<I")


def _frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + payload


def _hybrid(header: dict, body: bytes) -> bytes:
    h = json.dumps(header).encode()
    return _frame(b"\x01" + _HLEN.pack(len(h)) + h + body)


def _read_frame(sock) -> bytes:
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    (n,) = _LEN.unpack(buf)
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise EOFError
        out += chunk
    return out


@pytest.fixture
def nd():
    from ray_tpu._native.node_dispatch import NativeDispatch

    srv = NativeDispatch(queue_cap=16)
    try:
        yield srv
    finally:
        srv.stop()
        srv.destroy()


class TestNativeDispatchUnit:
    def test_native_pong_and_refusal(self, nd):
        nd.set_node_id("unit-node")
        nd.ledger_set({"CPU": 1.0})
        nd.set_load_report({"available": {"CPU": 1.0}, "queued": 0,
                            "spilled": 0})
        nd.set_peers([{"id": "peer-a", "queued": 0, "headroom": 1.0,
                       "avail": {"CPU": 1.0}}])
        nd.set_ping_native(True)
        nd.start()
        with socket.create_connection(("127.0.0.1", nd.port),
                                      timeout=5) as s:
            s.sendall(_frame(json.dumps({"type": "ping"}).encode()))
            pong = json.loads(_read_frame(s))
            assert pong["type"] == "pong"
            assert pong["node_id"] == "unit-node"
            assert pong["load"]["available"] == {"CPU": 1.0}
            # saturate, then push a spillable task: refused natively,
            # redirected to the pushed peer digest's feasible candidate
            nd.ledger_charge({"CPU": 1.0})
            s.sendall(_hybrid(
                {"type": "task", "tid": "ab12",
                 "res": {"CPU": 1.0}, "spillable": True},
                b"\x00" * 16))
            refusal = json.loads(_read_frame(s))
            assert refusal["type"] == "result"
            assert refusal["spillback"] is True
            assert refusal["retry_at"] == "peer-a"
            # a task no peer can fit is refused with no redirect —
            # same feasibility rule as _recommend_spill_target
            s.sendall(_hybrid(
                {"type": "task", "tid": "ab13",
                 "res": {"CPU": 2.0}, "spillable": True},
                b"\x00" * 16))
            refusal2 = json.loads(_read_frame(s))
            assert refusal2["spillback"] is True
            assert refusal2["retry_at"] is None
        assert nd.spilled() == 2

    def test_admission_precharge_and_reply(self, nd):
        from ray_tpu._native.node_dispatch import (EV_MESSAGE,
                                                   FLAG_PRECHARGED)

        nd.ledger_set({"CPU": 1.0})
        nd.start()
        with socket.create_connection(("127.0.0.1", nd.port),
                                      timeout=5) as s:
            body = b"opaque-task-body"
            s.sendall(_hybrid(
                {"type": "task", "tid": "cd34",
                 "res": {"CPU": 1.0}, "spillable": True}, body))
            ev = None
            deadline = time.monotonic() + 5
            while ev is None and time.monotonic() < deadline:
                ev = nd.next_event(timeout_ms=200)
            assert ev is not None
            conn_id, kind, flags, payload = ev
            assert kind == EV_MESSAGE
            assert flags & FLAG_PRECHARGED
            assert payload.endswith(body)  # header rides in front
            # the admitted charge is visible in the live ledger (zero
            # entries drop, matching ResourceSet.to_dict)
            assert nd.ledger_available() == {}
            nd.ledger_release({"CPU": 1.0})
            assert nd.send(conn_id,
                           json.dumps({"type": "result",
                                       "result": "done"}).encode())
            reply = json.loads(_read_frame(s))
            assert reply == {"type": "result", "result": "done"}

    def test_ledger_matches_resource_set_semantics(self, nd):
        nd.ledger_set({"CPU": 2.0, "mem": 0.5})
        assert nd.ledger_available() == {"CPU": 2.0, "mem": 0.5}
        assert nd.ledger_try_charge({"CPU": 1.5}) is True
        assert nd.ledger_try_charge({"CPU": 1.0}) is False  # atomic: no debit
        assert nd.ledger_available()["CPU"] == 0.5
        # charge() mirrors ResourceSet.subtract: raises on underflow
        with pytest.raises(ValueError):
            nd.ledger_charge({"CPU": 1.0})
        nd.ledger_release({"CPU": 1.5})
        assert nd.ledger_available() == {"CPU": 2.0, "mem": 0.5}

    def test_oversized_frame_closes_connection(self):
        from ray_tpu._native.node_dispatch import (EV_CLOSED,
                                                   NativeDispatch)

        srv = NativeDispatch(max_frame=1 << 12, queue_cap=16)
        try:
            srv.start()
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5) as s:
                s.sendall(_LEN.pack((1 << 12) + 1))
                s.sendall(b"x" * 64)
                s.settimeout(5)
                assert s.recv(1) == b""  # server hung up
            ev = None
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                got = srv.next_event(timeout_ms=200)
                if got is not None and got[1] == EV_CLOSED:
                    ev = got
                    break
            assert ev is not None, "no EV_CLOSED for torn-down conn"
        finally:
            srv.stop()
            srv.destroy()

    def test_destroy_guard_makes_calls_safe(self):
        from ray_tpu._native.node_dispatch import NativeDispatch

        srv = NativeDispatch(queue_cap=16)
        srv.start()
        srv.stop()
        with pytest.raises(StopIteration):
            while True:
                srv.next_event(timeout_ms=50)
        srv.destroy()
        srv.destroy()  # idempotent
        assert srv.send(1, b"x") is False
        assert srv.stats() == {}
        assert srv.ledger_available() == {}
        assert srv.spilled() == 0
        with pytest.raises(StopIteration):
            srv.next_event(timeout_ms=50)


# ---------------------------------------------------------------------------
# Native worker hand-off (no cluster): the C loop forwards plain-task
# frames straight onto an idle worker's socket and relays the reply —
# zero daemon-side Python on the warm path.
# ---------------------------------------------------------------------------


class _FakeTaskID:
    """Stands in for core.task.TaskID: an object with .binary(), the
    shape hybrid_frame actually receives from the driver."""

    def __init__(self, b: bytes):
        self._b = b

    def binary(self) -> bytes:
        return self._b


def _plain_msg(tid=b"\x01\x02\x03\x04", fid=b"\xab\xcd", fn=None,
               res=None):
    msg = {
        "type": "task", "task_id": _FakeTaskID(tid), "fid": fid,
        # Tracing is on by default in the driver runtime; a trace_id
        # must NOT demote the task to the cold path.
        "trace_id": "deadbeefdeadbeef",
        "spillable": True,
        "resources": {"CPU": 1.0} if res is None else res,
        "args": (), "kwargs": {}, "num_returns": 1, "return_ids": [],
    }
    if fn is not None:
        msg["fn"] = fn
    return msg


def _send_framed(sock, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


class TestNativeHandoffUnit:
    def test_plain_header_stamping(self):
        """hybrid_frame must mark real driver messages (TaskID object,
        trace_id set) as hand-off eligible."""
        from ray_tpu.node.client import hybrid_frame

        frame = hybrid_frame(_plain_msg(fn=b"fn-bytes"))
        assert frame[8:9] == b"\x01"
        (hlen,) = _HLEN.unpack(frame[9:13])
        header = json.loads(frame[13:13 + hlen])
        assert header["plain"] is True
        assert header["tid"] == b"\x01\x02\x03\x04".hex()
        assert header["fid"] == b"\xab\xcd".hex()
        assert header["has_fn"] is True
        # streaming / non-spillable stay cold
        streaming = _plain_msg()
        streaming["streaming"] = True
        (hlen,) = _HLEN.unpack(hybrid_frame(streaming)[9:13])
        assert "plain" not in json.loads(
            hybrid_frame(streaming)[13:13 + hlen])

    def test_handoff_roundtrip_releases_ledger(self, nd):
        """Plain frame → idle worker's socket verbatim → worker reply
        → driver, with the admission charge released. Worker id 0 on
        purpose: the acquire/hand-off ABI must not confuse the first
        wid with a sentinel."""
        import cloudpickle

        from ray_tpu.node.client import hybrid_frame

        nd.ledger_set({"CPU": 1.0})
        nd.start()
        wsock, wpeer = socket.socketpair()
        try:
            assert nd.worker_register(0, wsock.fileno(), 4242,
                                      [b"\xab\xcd"])
            msg = _plain_msg()
            with socket.create_connection(
                    ("127.0.0.1", nd.port), timeout=5) as c:
                c.sendall(hybrid_frame(msg))
                wpeer.settimeout(5)
                body = _read_frame(wpeer)
                got = cloudpickle.loads(body)
                assert got["type"] == "task"
                assert got["fid"] == b"\xab\xcd"
                # mid-flight: charge held, worker busy
                assert nd.ledger_available() == {}
                reply = cloudpickle.dumps(
                    {"type": "result", "task_id": b"\x01\x02\x03\x04",
                     "returns": []})
                _send_framed(wpeer, reply)
                c.settimeout(5)
                echoed = _read_frame(c)
                assert cloudpickle.loads(echoed)["type"] == "result"
            assert nd.ledger_available() == {"CPU": 1.0}
            h = nd.handoff()
            assert h["handoffs"] == 1 and h["completed"] == 1
            assert [w["state"] for w in nd.workers()] == ["idle"]
        finally:
            wsock.close()
            with contextlib.suppress(OSError):
                wpeer.close()

    def test_worker_death_mid_handoff(self, nd):
        """A worker dying after accepting a hand-off must produce a
        typed crashed reply on the driver connection, release the
        ledger charge, and surface EV_WORKER_DEAD to Python — no
        hang anywhere."""
        from ray_tpu._native.node_dispatch import EV_WORKER_DEAD
        from ray_tpu.node.client import hybrid_frame

        nd.ledger_set({"CPU": 1.0})
        nd.start()
        wsock, wpeer = socket.socketpair()
        try:
            assert nd.worker_register(3, wsock.fileno(), 4343,
                                      [b"\xab\xcd"])
            with socket.create_connection(
                    ("127.0.0.1", nd.port), timeout=5) as c:
                c.sendall(hybrid_frame(_plain_msg()))
                wpeer.settimeout(5)
                _read_frame(wpeer)  # worker took the task...
                wpeer.close()       # ...and died
                wsock.close()
                c.settimeout(5)
                reply = _read_frame(c)
                assert reply[:1] == b"{"  # crashed replies are JSON
                parsed = json.loads(reply)
                assert parsed["type"] == "result"
                assert "crashed" in parsed
                assert parsed["task_id"] == b"\x01\x02\x03\x04".hex()
            assert nd.ledger_available() == {"CPU": 1.0}
            assert nd.workers() == []
            assert nd.handoff()["worker_deaths"] == 1
            deadline = time.monotonic() + 5
            seen_dead = None
            while time.monotonic() < deadline and seen_dead is None:
                got = nd.next_event(timeout_ms=200)
                if got is not None and got[1] == EV_WORKER_DEAD:
                    seen_dead = got
            assert seen_dead is not None, "no EV_WORKER_DEAD event"
            assert seen_dead[0] == 3  # conn_id carries the worker id
        finally:
            with contextlib.suppress(OSError):
                wsock.close()
            with contextlib.suppress(OSError):
                wpeer.close()

    def test_all_workers_busy_queues_pending(self, nd):
        """With the only worker busy, a second plain frame waits in
        the native pending queue and is served the moment the worker
        turns idle — no Python wakeup in between."""
        import cloudpickle

        from ray_tpu.node.client import hybrid_frame

        nd.ledger_set({"CPU": 2.0})
        nd.start()
        wsock, wpeer = socket.socketpair()
        try:
            assert nd.worker_register(0, wsock.fileno(), 4444,
                                      [b"\xab\xcd"])
            wpeer.settimeout(5)
            c1 = socket.create_connection(("127.0.0.1", nd.port),
                                          timeout=5)
            c2 = socket.create_connection(("127.0.0.1", nd.port),
                                          timeout=5)
            try:
                c1.sendall(hybrid_frame(_plain_msg(tid=b"\x0a" * 4)))
                _read_frame(wpeer)  # worker now busy on task 1
                c2.sendall(hybrid_frame(_plain_msg(tid=b"\x0b" * 4)))
                deadline = time.monotonic() + 5
                while (nd.handoff()["pending"] != 1
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                assert nd.handoff()["pending"] == 1
                reply = cloudpickle.dumps({"type": "result",
                                           "returns": []})
                _send_framed(wpeer, reply)
                c1.settimeout(5)
                _read_frame(c1)
                # the pending task reaches the worker with no new
                # client traffic
                _read_frame(wpeer)
                _send_framed(wpeer, reply)
                c2.settimeout(5)
                _read_frame(c2)
            finally:
                c1.close()
                c2.close()
            assert nd.ledger_available() == {"CPU": 2.0}
            h = nd.handoff()
            assert h["handoffs"] == 2 and h["completed"] == 2
            assert h["pending"] == 0
        finally:
            wsock.close()
            with contextlib.suppress(OSError):
                wpeer.close()

    def test_acquire_release_checkout(self, nd):
        """Cold-path checkout: acquire returns the wid (0 is a valid
        id, not a sentinel), the worker leaves the epoll set while
        Python owns it, and release returns it to the idle registry.
        Timeouts return None; a stopped plane raises StopIteration."""
        nd.start()
        assert nd.worker_acquire(timeout_ms=50) is None  # no workers
        wsock, wpeer = socket.socketpair()
        try:
            assert nd.worker_register(0, wsock.fileno(), 4545, [])
            assert nd.worker_acquire(timeout_ms=1000) == 0
            assert [w["state"] for w in nd.workers()] == ["py"]
            assert nd.worker_acquire(timeout_ms=50) is None  # held
            assert nd.worker_release(0, [b"\xab\xcd"])
            assert [w["state"] for w in nd.workers()] == ["idle"]
            assert nd.worker_unregister(0)
            assert nd.workers() == []
        finally:
            wsock.close()
            wpeer.close()


class TestNativeWarmPath:
    """End-to-end zero-Python proof: under the native plane, plain
    tasks complete while the daemon's Python task-execution counter
    stays frozen — the drainer never runs for them. Actors and
    streaming generators still route through Python."""

    @pytest.fixture(scope="class")
    def warm_cluster(self):
        ray.shutdown()
        cluster = RealCluster()
        try:
            cluster.add_node(num_cpus=1,
                             env={"RAY_TPU_NATIVE_DISPATCH": "1"})
            cluster.connect(num_cpus=0)
            yield cluster
        finally:
            cluster.shutdown()

    @staticmethod
    def _load():
        return _rt().scheduler.get_node("daemon-1").client.call(
            {"type": "ping"})["load"]

    def test_zero_python_warm_path(self, warm_cluster):
        before = self._load()

        @ray.remote
        def double(x):
            return 2 * x

        assert ray.get([double.remote(i) for i in range(8)],
                       timeout=60) == [2 * i for i in range(8)]
        after = self._load()
        nh = after["native_handoff"]
        assert (nh["completed"]
                - before["native_handoff"]["completed"]) >= 8
        # the PYTHON execution path never ran: warm-path tasks execute
        # zero daemon-side Python bytecode
        assert after["py_exec_tasks"] == before["py_exec_tasks"]
        # attribution parity: native hand-offs land in the nd stats
        # surface like every other handler
        native = after["event_stats"].get("node_dispatch_native", {})
        assert "task_native" in native
        assert "task_native_handoff" in native
        assert native["task_native"]["count"] >= 8

    def test_warm_dispatch_span_closes_timeline_hole(self, warm_cluster):
        """A native hand-off runs zero daemon-side Python, so the
        daemon never opens its dispatch span — yet the trace must NOT
        show a submit→execute hole. The C loop's dispatch_timing reply
        stamps (admission arrival / worker write / reply forward)
        back-fill the lifecycle phases and synthesize the
        daemon_dispatch span driver-side."""
        from ray_tpu.util import tracing

        @ray.remote
        def stamped(x):
            return x + 1

        spans: list = []
        tracing.setup_tracing(spans.append)
        try:
            # first call exports the fn; the next one is a pure native
            # hand-off (the shape the blind spot hid)
            assert ray.get(stamped.remote(1), timeout=60) == 2
            with tracing.span("warm_root"):
                trace_id = tracing.current_trace_id()
                assert ray.get(stamped.remote(2), timeout=60) == 3
        finally:
            tracing.clear_tracing()

        deadline = time.time() + 10
        native_spans = []
        while time.time() < deadline and not native_spans:
            native_spans = [
                e for e in ray.timeline()
                if e.get("cat") == "daemon_dispatch"
                and (e.get("args") or {}).get("native")
                and (e.get("args") or {}).get("trace_id") == trace_id]
            time.sleep(0.05)
        assert native_spans, \
            "warm task produced no synthesized dispatch span"
        sp = native_spans[-1]
        assert str(sp.get("pid", "")).startswith("daemon:")
        assert sp["args"].get("task_id")
        assert sp.get("dur", -1.0) >= 0.0

        # lifecycle closure: the warm task's timing has scheduled AND
        # running back-filled from the native stamps — no hole between
        # submit and finish
        task_evs = [e for e in ray.timeline()
                    if (e.get("args") or {}).get("trace_id") == trace_id
                    and (e.get("args") or {}).get("timing")]
        assert task_evs, "warm task left no task event in the timeline"
        timing = task_evs[-1]["args"]["timing"]
        for stamp in ("submitted", "scheduled", "running", "finished"):
            assert timing.get(stamp) is not None, (stamp, timing)
        assert timing["submitted"] <= timing["scheduled"] \
            <= timing["running"] <= timing["finished"]

    def test_actor_and_streaming_stay_python(self, warm_cluster):
        before = self._load()

        @ray.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray.get([c.inc.remote() for _ in range(3)],
                       timeout=60) == [1, 2, 3]
        ray.kill(c)

        @ray.remote(num_returns="streaming")
        def gen(n):
            for i in range(n):
                yield i

        assert [ray.get(r) for r in gen.remote(3)] == [0, 1, 2]
        after = self._load()
        # cold-path work completed without a single native hand-off
        assert (after["native_handoff"]["handoffs"]
                == before["native_handoff"]["handoffs"])
        # ...because it rode the Python plane
        assert after["py_exec_tasks"] > before["py_exec_tasks"]
