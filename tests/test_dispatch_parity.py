"""Dispatch-plane parity: native C++ front end vs pure-Python fallback.

The node daemon's dispatch socket has two implementations — the C++
epoll loop (src/node_dispatch.cc, default) and the pure-Python accept
loop (`RAY_TPU_NATIVE_DISPATCH=0`). They must be observationally
identical: same task/actor results, same typed errors, same spillback
refusal replies (retry_at included), same load-report vocabulary. The
parametrized cluster fixture runs every scenario under both planes;
the chaos test then crashes workers mid-dispatch and requires the
native loop to keep serving with an intact resource ledger.

Direct NativeDispatch unit tests (no cluster) cover the binding's
wire handling: framing, native pong, admission/refusal, ledger
semantics vs ResourceSet, oversized-frame teardown, destroy guard.
"""

import json
import socket
import struct
import time

import pytest

import ray_tpu as ray
from ray_tpu import NodeAffinitySchedulingStrategy
from ray_tpu.cluster_utils import RealCluster
from ray_tpu.core import runtime as _runtime


def _rt():
    return _runtime.global_runtime()


@pytest.fixture(scope="class", params=["0", "1"], ids=["py", "native"])
def pcluster(request):
    """Two 1-CPU daemons, both forced onto one dispatch plane; the
    driver head contributes no CPUs so every scenario crosses the
    dispatch socket under test."""
    ray.shutdown()
    cluster = RealCluster()
    env = {"RAY_TPU_NATIVE_DISPATCH": request.param}
    try:
        cluster.add_node(num_cpus=1, env=env)
        cluster.add_node(num_cpus=1, env=env)
        cluster.connect(num_cpus=0)
        yield cluster
    finally:
        cluster.shutdown()


class TestDispatchParity:
    def test_tasks_and_typed_task_error(self, pcluster):
        @ray.remote
        def double(x):
            return 2 * x

        assert ray.get([double.remote(i) for i in range(6)],
                       timeout=60) == [0, 2, 4, 6, 8, 10]

        @ray.remote(max_retries=0)
        def boom():
            raise ValueError("boom-parity")

        with pytest.raises(ray.TaskError) as ei:
            ray.get(boom.remote(), timeout=60)
        assert "boom-parity" in str(ei.value)

    def test_actor_lifecycle_and_typed_errors(self, pcluster):
        @ray.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

            def fail(self):
                raise KeyError("actor-boom")

        c = Counter.remote()
        assert ray.get([c.inc.remote() for _ in range(3)],
                       timeout=60) == [1, 2, 3]
        with pytest.raises(ray.TaskError) as ei:
            ray.get(c.fail.remote(), timeout=60)
        assert "actor-boom" in str(ei.value)
        # the error left the actor alive — state intact
        assert ray.get(c.inc.remote(), timeout=60) == 4
        # release the actor's charge (an alive actor holds a running
        # slot; the ledger test below asserts full quiescence)
        ray.kill(c)

    def test_actor_death_typed_error(self, pcluster):
        @ray.remote
        class Mortal:
            def die(self):
                import os

                os._exit(1)

            def ping(self):
                return "alive"

        a = Mortal.remote()
        assert ray.get(a.ping.remote(), timeout=60) == "alive"
        with pytest.raises((ray.TaskError, ray.ActorDiedError)):
            ray.get(a.die.remote(), timeout=60)
        with pytest.raises(ray.ActorDiedError):
            ray.get(a.ping.remote(), timeout=60)

    def test_worker_crash_task_typed_error(self, pcluster):
        @ray.remote(max_retries=0)
        def die():
            import os

            os._exit(1)

        with pytest.raises(ray.TaskError):
            ray.get(die.remote(), timeout=60)

    def test_spillback_refusal_and_load_vocabulary(self, pcluster):
        """A crafted spillable push to a saturated daemon must be
        refused the same way by both planes: spillback=True, retry_at
        naming the idle peer, and a load report speaking the full
        heartbeat vocabulary (shm_pins attribution included)."""

        @ray.remote(num_cpus=1, scheduling_strategy=(
            NodeAffinitySchedulingStrategy("daemon-1", soft=False)))
        class Holder:
            def ready(self):
                return True

        h = Holder.remote()
        assert ray.get(h.ready.remote(), timeout=60) is True
        # Let one heartbeat land so the daemon's peer view (native: the
        # pushed digest) knows daemon-2 is idle.
        time.sleep(0.6)
        node1 = _rt().scheduler.get_node("daemon-1")
        r = node1.client.call({
            "type": "task", "task_id": b"probe-parity",
            "args": (), "kwargs": {}, "num_returns": 1,
            "return_ids": [], "resources": {"CPU": 1.0},
            "spillable": True, "spill_exclude": [],
        })
        assert r.get("spillback") is True
        assert r.get("retry_at") == "daemon-2"
        load = r.get("load")
        assert load is not None
        assert {"available", "total", "queued", "running", "spilled",
                "host", "event_stats", "transfer",
                "shm_pins"} <= set(load)
        assert load["available"].get("CPU", 0.0) == 0.0
        # shm attribution carries labeled per-pid holders
        assert "holders" in load["shm_pins"]
        for holder in load["shm_pins"]["holders"]:
            assert {"pid", "label", "pinned_bytes"} <= set(holder)
        ray.kill(h)

    def test_ledger_restored_after_errors(self, pcluster):
        """Typed failures above must not leak admission charges: both
        daemons report a fully available ledger once quiesced."""
        deadline = time.monotonic() + 30
        while True:
            loads = [
                _rt().scheduler.get_node(nid).client.call(
                    {"type": "ping"})["load"]
                for nid in ("daemon-1", "daemon-2")]
            if all(ld["available"].get("CPU") == 1.0
                   and ld["running"] == 0 for ld in loads):
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"ledger leaked: {loads}")
            time.sleep(0.2)


class TestNativeChaos:
    """Worker crashes mid-dispatch with the native loop in front: the
    C plane must survive, keep answering, and not strand ledger
    charges."""

    @pytest.fixture(scope="class")
    def chaos_cluster(self):
        ray.shutdown()
        cluster = RealCluster()
        try:
            cluster.add_node(num_cpus=1,
                             env={"RAY_TPU_NATIVE_DISPATCH": "1"})
            cluster.connect(num_cpus=0)
            yield cluster
        finally:
            cluster.shutdown()

    def test_native_loop_survives_worker_crashes(self, chaos_cluster):
        @ray.remote(max_retries=0)
        def die():
            import os

            os._exit(1)

        @ray.remote
        def ok(x):
            return x + 1

        # Interleave crashers with healthy tasks: each crash kills the
        # worker process while the native loop has admitted (and
        # precharged) the task.
        crashers = [die.remote() for _ in range(3)]
        for ref in crashers:
            with pytest.raises(ray.TaskError):
                ray.get(ref, timeout=60)
        assert ray.get([ok.remote(i) for i in range(4)],
                       timeout=120) == [1, 2, 3, 4]
        # The daemon still answers protocol-level pings natively and
        # the crashes released their admission charges.
        node = _rt().scheduler.get_node("daemon-1")
        deadline = time.monotonic() + 30
        while True:
            load = node.client.call({"type": "ping"})["load"]
            if (load["available"].get("CPU") == 1.0
                    and load["running"] == 0):
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"ledger leaked: {load}")
            time.sleep(0.2)
        # and the native handler stats saw real traffic
        native = load["event_stats"].get("node_dispatch_native", {})
        assert native, load["event_stats"].keys()


# ---------------------------------------------------------------------------
# Direct NativeDispatch unit coverage (no cluster)
# ---------------------------------------------------------------------------

_LEN = struct.Struct("!Q")
_HLEN = struct.Struct("<I")


def _frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + payload


def _hybrid(header: dict, body: bytes) -> bytes:
    h = json.dumps(header).encode()
    return _frame(b"\x01" + _HLEN.pack(len(h)) + h + body)


def _read_frame(sock) -> bytes:
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    (n,) = _LEN.unpack(buf)
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise EOFError
        out += chunk
    return out


@pytest.fixture
def nd():
    from ray_tpu._native.node_dispatch import NativeDispatch

    srv = NativeDispatch(queue_cap=16)
    try:
        yield srv
    finally:
        srv.stop()
        srv.destroy()


class TestNativeDispatchUnit:
    def test_native_pong_and_refusal(self, nd):
        nd.set_node_id("unit-node")
        nd.ledger_set({"CPU": 1.0})
        nd.set_load_report({"available": {"CPU": 1.0}, "queued": 0,
                            "spilled": 0})
        nd.set_peers([{"id": "peer-a", "queued": 0, "headroom": 1.0,
                       "avail": {"CPU": 1.0}}])
        nd.set_ping_native(True)
        nd.start()
        with socket.create_connection(("127.0.0.1", nd.port),
                                      timeout=5) as s:
            s.sendall(_frame(json.dumps({"type": "ping"}).encode()))
            pong = json.loads(_read_frame(s))
            assert pong["type"] == "pong"
            assert pong["node_id"] == "unit-node"
            assert pong["load"]["available"] == {"CPU": 1.0}
            # saturate, then push a spillable task: refused natively,
            # redirected to the pushed peer digest's feasible candidate
            nd.ledger_charge({"CPU": 1.0})
            s.sendall(_hybrid(
                {"type": "task", "tid": "ab12",
                 "res": {"CPU": 1.0}, "spillable": True},
                b"\x00" * 16))
            refusal = json.loads(_read_frame(s))
            assert refusal["type"] == "result"
            assert refusal["spillback"] is True
            assert refusal["retry_at"] == "peer-a"
            # a task no peer can fit is refused with no redirect —
            # same feasibility rule as _recommend_spill_target
            s.sendall(_hybrid(
                {"type": "task", "tid": "ab13",
                 "res": {"CPU": 2.0}, "spillable": True},
                b"\x00" * 16))
            refusal2 = json.loads(_read_frame(s))
            assert refusal2["spillback"] is True
            assert refusal2["retry_at"] is None
        assert nd.spilled() == 2

    def test_admission_precharge_and_reply(self, nd):
        from ray_tpu._native.node_dispatch import (EV_MESSAGE,
                                                   FLAG_PRECHARGED)

        nd.ledger_set({"CPU": 1.0})
        nd.start()
        with socket.create_connection(("127.0.0.1", nd.port),
                                      timeout=5) as s:
            body = b"opaque-task-body"
            s.sendall(_hybrid(
                {"type": "task", "tid": "cd34",
                 "res": {"CPU": 1.0}, "spillable": True}, body))
            ev = None
            deadline = time.monotonic() + 5
            while ev is None and time.monotonic() < deadline:
                ev = nd.next_event(timeout_ms=200)
            assert ev is not None
            conn_id, kind, flags, payload = ev
            assert kind == EV_MESSAGE
            assert flags & FLAG_PRECHARGED
            assert payload.endswith(body)  # header rides in front
            # the admitted charge is visible in the live ledger (zero
            # entries drop, matching ResourceSet.to_dict)
            assert nd.ledger_available() == {}
            nd.ledger_release({"CPU": 1.0})
            assert nd.send(conn_id,
                           json.dumps({"type": "result",
                                       "result": "done"}).encode())
            reply = json.loads(_read_frame(s))
            assert reply == {"type": "result", "result": "done"}

    def test_ledger_matches_resource_set_semantics(self, nd):
        nd.ledger_set({"CPU": 2.0, "mem": 0.5})
        assert nd.ledger_available() == {"CPU": 2.0, "mem": 0.5}
        assert nd.ledger_try_charge({"CPU": 1.5}) is True
        assert nd.ledger_try_charge({"CPU": 1.0}) is False  # atomic: no debit
        assert nd.ledger_available()["CPU"] == 0.5
        # charge() mirrors ResourceSet.subtract: raises on underflow
        with pytest.raises(ValueError):
            nd.ledger_charge({"CPU": 1.0})
        nd.ledger_release({"CPU": 1.5})
        assert nd.ledger_available() == {"CPU": 2.0, "mem": 0.5}

    def test_oversized_frame_closes_connection(self):
        from ray_tpu._native.node_dispatch import (EV_CLOSED,
                                                   NativeDispatch)

        srv = NativeDispatch(max_frame=1 << 12, queue_cap=16)
        try:
            srv.start()
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=5) as s:
                s.sendall(_LEN.pack((1 << 12) + 1))
                s.sendall(b"x" * 64)
                s.settimeout(5)
                assert s.recv(1) == b""  # server hung up
            ev = None
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                got = srv.next_event(timeout_ms=200)
                if got is not None and got[1] == EV_CLOSED:
                    ev = got
                    break
            assert ev is not None, "no EV_CLOSED for torn-down conn"
        finally:
            srv.stop()
            srv.destroy()

    def test_destroy_guard_makes_calls_safe(self):
        from ray_tpu._native.node_dispatch import NativeDispatch

        srv = NativeDispatch(queue_cap=16)
        srv.start()
        srv.stop()
        with pytest.raises(StopIteration):
            while True:
                srv.next_event(timeout_ms=50)
        srv.destroy()
        srv.destroy()  # idempotent
        assert srv.send(1, b"x") is False
        assert srv.stats() == {}
        assert srv.ledger_available() == {}
        assert srv.spilled() == 0
        with pytest.raises(StopIteration):
            srv.next_event(timeout_ms=50)
