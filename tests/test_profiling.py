"""Tier-1 tests for the profiling plane (profplane): on-demand stack
sampling (driver + worker), per-loop handler event stats, the
dependency-free OTLP exporter against an in-process HTTP sink, and
whole-trace head-based sampling determinism."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest


# ---------------------------------------------------------------------------
# Stack sampler
# ---------------------------------------------------------------------------

def _busy_marker_fn(stop):
    x = 0
    while not stop.is_set():
        x += sum(i * i for i in range(500))
    return x


def test_sample_stacks_captures_named_thread():
    from ray_tpu.observability import sample_stacks

    stop = threading.Event()
    t = threading.Thread(target=_busy_marker_fn, args=(stop,),
                         daemon=True)
    t.start()
    try:
        samples = sample_stacks(0.3, interval_s=0.005)
    finally:
        stop.set()
        t.join(timeout=5)
    assert samples, "no stacks captured"
    assert any("_busy_marker_fn" in stack for stack in samples), (
        sorted(samples)[:5])


def test_collapsed_and_chrome_outputs():
    from ray_tpu.observability.stack_sampler import (
        merge_samples, to_chrome_trace, to_collapsed)

    merged = merge_samples({
        "driver": {"a.py:f;b.py:g": 3},
        "worker:42": {"a.py:f": 2},
    })
    assert merged == {"driver;a.py:f;b.py:g": 3, "worker:42;a.py:f": 2}
    text = to_collapsed(merged)
    assert "driver;a.py:f;b.py:g 3" in text.splitlines()
    doc = to_chrome_trace(merged, interval_s=0.01)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"driver", "a.py:f", "b.py:g", "worker:42"} <= names


def test_profile_cluster_merges_driver_and_worker_stacks():
    """The acceptance-bar capture: frames from >= 2 distinct processes
    in one merged flamegraph (driver samples itself; the worker answers
    {"type": "profile"} on its command socket)."""
    import ray_tpu
    from ray_tpu.core.runtime import global_runtime
    from ray_tpu.observability import profile_cluster

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0, num_worker_procs=1)
    try:
        out = profile_cluster(global_runtime(), duration_s=0.6,
                              interval_s=0.01)
        labels = {k for k, v in out["processes"].items() if v}
        assert "driver" in labels, labels
        assert any(lbl.startswith("worker:") for lbl in labels), labels
        prefixes = {s.split(";", 1)[0] for s in out["merged"]}
        assert len(prefixes) >= 2, prefixes
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Event-loop handler stats
# ---------------------------------------------------------------------------

def test_event_stats_accounting_under_concurrency():
    from ray_tpu.observability.event_stats import EventStats

    es = EventStats()

    def hammer():
        for _ in range(200):
            es.record("loopA", "handler_x", 0.001)
        with es.timed("loopA", "handler_y"):
            pass

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = es.snapshot()
    hx = snap["loopA"]["handler_x"]
    assert hx["count"] == 8 * 200
    assert hx["total_s"] == pytest.approx(8 * 200 * 0.001, rel=0.01)
    assert hx["max_s"] >= 0.001 - 1e-9
    assert hx["p95_s"] >= 0.0
    assert snap["loopA"]["handler_y"]["count"] == 8
    es.reset()
    assert es.snapshot() == {}


def test_event_stats_records_from_instrumented_loops():
    """Running tasks through the scheduler must tick the scheduler
    loop's pump handler in the module-level registry."""
    import ray_tpu
    from ray_tpu.observability import event_stats

    event_stats.get_event_stats().reset()
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        @ray_tpu.remote
        def one():
            return 1

        assert ray_tpu.get(one.remote()) == 1
        deadline = time.time() + 5
        while time.time() < deadline:
            snap = event_stats.snapshot()
            if snap.get("scheduler", {}).get("pump_once", {}).get(
                    "count", 0) > 0:
                break
            time.sleep(0.05)
        snap = event_stats.snapshot()
        assert snap["scheduler"]["pump_once"]["count"] > 0, snap
    finally:
        ray_tpu.shutdown()
        event_stats.get_event_stats().reset()


# ---------------------------------------------------------------------------
# OTLP export
# ---------------------------------------------------------------------------

@pytest.fixture
def otlp_sink():
    """In-process HTTP sink collecting decoded OTLP JSON payloads."""
    import http.server

    bodies = []

    class Sink(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            bodies.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *args):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Sink)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}/v1/traces", bodies
    finally:
        srv.shutdown()
        srv.server_close()


def _otlp_spans(bodies):
    return [s for b in bodies
            for rs in b.get("resourceSpans", [])
            for ss in rs.get("scopeSpans", [])
            for s in ss.get("spans", [])]


def test_otlp_exporter_roundtrip(otlp_sink, monkeypatch):
    endpoint, bodies = otlp_sink
    from ray_tpu.util import tracing

    monkeypatch.delenv("RAY_TPU_TRACE_SAMPLE", raising=False)
    monkeypatch.setenv("RAY_TPU_OTLP_ENDPOINT", endpoint)
    tracing.clear_tracing()
    tracing.setup_tracing()
    try:
        assert tracing.get_otlp_exporter() is not None
        with tracing.span("otlp-root", "test"):
            with tracing.span("otlp-child", "test"):
                pass
        tracing.flush_otlp()
        spans = _otlp_spans(bodies)
        names = {s["name"] for s in spans}
        assert {"otlp-root", "otlp-child"} <= names, names
        child = next(s for s in spans if s["name"] == "otlp-child")
        root = next(s for s in spans if s["name"] == "otlp-root")
        # Parent-linked, same 32-hex trace id, nanosecond timestamps.
        assert child["parentSpanId"] == root["spanId"]
        assert child["traceId"] == root["traceId"]
        assert len(root["traceId"]) == 32
        assert int(child["endTimeUnixNano"]) >= int(
            child["startTimeUnixNano"])
    finally:
        tracing.clear_tracing()


def test_otlp_exporter_survives_dead_endpoint():
    """Export toward nothing must never raise (fire-and-forget)."""
    from ray_tpu.util.tracing import OTLPSpanExporter

    exp = OTLPSpanExporter("http://127.0.0.1:9/v1/traces",
                           flush_interval_s=60.0)
    try:
        exp.export({"name": "x", "cat": "test", "ts": 1.0, "dur": 2.0,
                    "pid": "driver", "tid": "span:abc", "args": {}})
        exp.flush()
    finally:
        exp.shutdown()


# ---------------------------------------------------------------------------
# Whole-trace head sampling
# ---------------------------------------------------------------------------

def test_trace_sampled_deterministic():
    from ray_tpu.util.tracing import trace_sampled

    ids = [f"trace-{i:05d}" for i in range(400)]
    v1 = [trace_sampled(t, 0.5) for t in ids]
    v2 = [trace_sampled(t, 0.5) for t in ids]
    assert v1 == v2
    kept = sum(v1)
    assert 0 < kept < len(ids)  # sha1 buckets actually split the set
    assert all(trace_sampled(t, 1.0) for t in ids)
    assert not any(trace_sampled(t, 0.0) for t in ids)
    assert trace_sampled(None, 0.5)  # no id -> keep (can't bucket)


def test_trace_sampled_agrees_across_processes():
    """The keep/drop verdict must be identical in a fresh interpreter
    (PYTHONHASHSEED-independent), or distributed traces would be
    recorded in some processes and dropped in others."""
    from ray_tpu.util.tracing import trace_sampled

    ids = [f"xproc-{i:03d}" for i in range(64)]
    local = [trace_sampled(t) for t in ids]
    code = (
        "import json, sys\n"
        "from ray_tpu.util.tracing import trace_sampled\n"
        "ids = json.loads(sys.argv[1])\n"
        "print(json.dumps([trace_sampled(t) for t in ids]))\n")
    env = dict(os.environ)
    env["RAY_TPU_TRACE_SAMPLE"] = "0.5"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", code, json.dumps(ids)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    remote = json.loads(r.stdout.strip().splitlines()[-1])
    expect = [trace_sampled(t, 0.5) for t in ids]
    assert remote == expect
    del local  # env-driven default (unset here) keeps everything


def test_sampled_out_trace_produces_zero_spans(monkeypatch):
    """Record-time gate: a sampled-out trace id silences every span in
    its context; a sampled-in id exports the complete parent-linked
    tree."""
    from ray_tpu.util import tracing

    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.5")
    candidates = [f"gate-{i:04d}" for i in range(256)]
    kept_id = next(t for t in candidates if tracing.trace_sampled(t))
    dropped_id = next(
        t for t in candidates if not tracing.trace_sampled(t))
    events = []
    tracing.clear_tracing()
    tracing.setup_tracing(events.append)
    try:
        with tracing.trace_context(dropped_id):
            with tracing.span("gate-a", "test"):
                with tracing.span("gate-b", "test"):
                    pass
        assert events == [], events

        with tracing.trace_context(kept_id, "feedbeef00000000"):
            with tracing.span("gate-a", "test"):
                with tracing.span("gate-b", "test"):
                    pass
        assert len(events) == 2, events
        by_name = {e["name"]: e for e in events}
        a, b = by_name["gate-a"], by_name["gate-b"]
        assert all(e["args"]["trace_id"] == kept_id for e in events)
        assert a["args"]["parent"] == "feedbeef00000000"
        assert b["args"]["parent"] == a["tid"].split(":", 1)[1]
    finally:
        tracing.clear_tracing()


def test_span_exceptions_survive_sampling_gate(monkeypatch):
    """The gate lives in span()'s finally — it must not swallow
    in-flight exceptions for either verdict."""
    from ray_tpu.util import tracing

    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.5")
    candidates = [f"exc-{i:04d}" for i in range(256)]
    for tid in (next(t for t in candidates
                     if tracing.trace_sampled(t)),
                next(t for t in candidates
                     if not tracing.trace_sampled(t))):
        with pytest.raises(RuntimeError, match="boom"):
            with tracing.trace_context(tid):
                with tracing.span("exploding", "test"):
                    raise RuntimeError("boom")


# ---------------------------------------------------------------------------
# Worker-side profile handler (command-socket protocol)
# ---------------------------------------------------------------------------

def test_worker_profile_message_roundtrip():
    """A worker answers {"type": "profile"} with its own pid and
    non-empty samples, and keeps serving tasks afterwards."""
    import ray_tpu
    from ray_tpu.core.runtime import global_runtime

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0, num_worker_procs=1)
    try:
        pool = global_runtime().worker_pool
        w = pool.acquire(timeout=10)
        try:
            reply = w.run_task({"type": "profile", "duration_s": 0.3,
                                "interval_s": 0.005})
            assert reply["type"] == "profile_result"
            assert reply["pid"] == w.pid
            assert reply["samples"], reply
        finally:
            pool.release(w)

        @ray_tpu.remote
        def two():
            return 2

        strategy = ray_tpu.NodeAffinitySchedulingStrategy(
            node_id="node-procs", soft=False)
        assert ray_tpu.get(
            two.options(scheduling_strategy=strategy).remote()) == 2
    finally:
        ray_tpu.shutdown()
