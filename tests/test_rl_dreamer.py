"""Dreamer — model-based RL family (reference:
rllib/algorithms/dreamerv3/): world model + imagination-trained
actor-critic. Thresholds are deliberately loose — RL smoke tests are
init-lottery-sensitive; the contract is that every phase runs, learns
in the right DIRECTION, and checkpoints."""

import numpy as np
import pytest

from ray_tpu.rl.dreamer import Dreamer, DreamerConfig


@pytest.fixture(scope="module")
def trained():
    cfg = DreamerConfig(
        env="CartPole", num_envs=4, rollout_length=16, seq_len=8,
        batch_size=8, learning_starts=64, deter_dim=32, stoch_dim=8,
        hidden=32, imagine_horizon=8, updates_per_iteration=4,
        seed=0)
    algo = Dreamer(cfg)
    results = algo.train(14)
    yield algo, results
    algo.stop()


def test_world_model_learns(trained):
    _, results = trained
    with_model = [r for r in results if "model_loss" in r]
    assert len(with_model) >= 8, "updates never started"
    # The model must fit the env over training: compare the first vs
    # last thirds (single iterations are noisy — the early data
    # distribution also shifts under the improving policy).
    third = max(1, len(with_model) // 3)
    early = float(np.mean([r["model_loss"] for r in with_model[:third]]))
    late = float(np.mean([r["model_loss"] for r in with_model[-third:]]))
    assert late < early, (early, late)
    assert np.isfinite(with_model[-1]["recon_loss"])
    assert np.isfinite(with_model[-1]["kl"])


def test_imagination_and_behavior_metrics(trained):
    _, results = trained
    last = [r for r in results if "actor_loss" in r][-1]
    for key in ("actor_loss", "critic_loss", "imagined_return",
                "entropy"):
        assert np.isfinite(last[key]), key
    assert last["entropy"] > 0.0  # categorical over 2 actions


def test_collect_reports_episodes(trained):
    _, results = trained
    assert results[-1]["env_steps"] >= 10 * 4 * 16
    assert results[-1]["episodes"] > 0
    assert results[-1]["episode_return_mean"] > 0.0


def test_action_and_checkpoint_roundtrip(trained, tmp_path):
    algo, _ = trained
    obs = np.zeros(algo.obs_dim, np.float32)
    a = algo.compute_single_action(obs)
    assert 0 <= a < algo.num_actions

    path = algo.save(str(tmp_path / "ckpt"))
    cfg2 = algo.config.with_overrides(train_iterations=1)
    algo2 = Dreamer(cfg2)
    algo2.restore(path)
    assert algo2.iteration == algo.iteration
    assert algo2.total_env_steps == algo.total_env_steps
    # Restored params are numerically identical.
    p1 = algo.get_state()["state"][0]["actor"][0]["w"]
    p2 = algo2.get_state()["state"][0]["actor"][0]["w"]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))
    # And the restored algorithm keeps training.
    r = algo2.step()
    assert "env_steps" in r
    algo2.stop()
